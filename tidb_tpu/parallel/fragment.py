"""General distributed fragments: an agg-rooted plan subtree compiled
into ONE shard_map program over the mesh.

This generalizes distsql.py's two fixed shapes (ref: the MPP exchange +
coprocessor tiers, SURVEY.md §2 parallelism table) to:

  * join trees of any depth/width — each equi-join hash-repartitions
    both sides over lax.all_to_all and joins locally by sorted-key
    ranges with duplicate expansion (many-many joins), all inside the
    same per-shard program
  * all join kinds: inner, left (NULL-padded unmatched probe rows),
    semi, anti (incl. NOT IN null semantics via a psum'd build-NULL
    count), with other_cond filters and multi-key equi joins (routed by
    a combined key hash, verified by exact per-key equality)
  * build sides that aren't scans (subquery results, small dimension
    pipelines) materialize on the host and enter the fragment as
    REPLICATED broadcast inputs — the broadcast-join exchange — which
    also skips repartitioning the probe side entirely
  * both aggregation strategies at the root: segment (dense [G] states,
    psum/pmin/pmax merge) and generic (per-shard sort-based partial
    tables from executor/agg_device.py, hash-repartitioned by group key
    and locally merged — the two-phase MPP shuffle agg), so
    high-cardinality GROUP BY runs on the mesh too

Every fixed-capacity buffer (exchange buckets, join expansion slots)
counts its overflow instead of dropping rows; the driving executor
doubles the blown growth factor and re-runs — the static-shape analogue
of the reference's spill/split retry.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column
from tidb_tpu.executor.agg_device import (
    _bits64,
    _sort_reduce,
    _state_layout,
    make_partial_kernel,
)
from tidb_tpu.executor.aggregate import make_segment_kernel
from tidb_tpu.executor.builder import peel_stages, scan_stages_for
from tidb_tpu.executor.scan import make_pipeline_fn
from tidb_tpu.expression.compiler import compile_predicate, eval_expr
from tidb_tpu.parallel.distsql import merge_state, pmax_compat, repartition_by_key
from tidb_tpu.parallel.mesh import dcn_axis, shard_axis, shard_map_compat
from tidb_tpu.planner.physical import PHashAgg, PHashJoin, PScan
from tidb_tpu.types import TypeKind

__all__ = ["compile_fragment", "FragmentProgram"]

_AXES = (dcn_axis, shard_axis)
_SPEC = P(_AXES, None)

# rows above this don't broadcast — the subtree is too big to replicate
BROADCAST_LIMIT = 1 << 21


# group/join key identity bits: same rule as the local sort-reduce
# (NULL -> 0 + validity flag; floats by bit pattern) so exchange routing
# and local grouping can never disagree
_key_bits = _bits64


def _compact(arrays: Dict[str, jax.Array], sel: jax.Array, cap: int):
    """Scatter live rows to the prefix of [cap] buffers (linear — no sort).

    Static capacities cascade: every stage inherits the worst case of the
    stage before, while selective joins/filters collapse the LIVE count.
    Sorts and exchanges pay for capacity, so compacting to an
    estimate-sized buffer (with the usual overflow-retry knob) is the
    static-shape analogue of a dynamic repartition. Returns
    (arrays', sel', required_factor_minus_one)."""
    pos = jnp.cumsum(sel.astype(jnp.int64)) - 1
    total = jnp.sum(sel.astype(jnp.int64))
    tgt = jnp.where(sel & (pos < cap), pos, cap)  # dead rows -> drop lane
    out = {}
    for name, a in arrays.items():
        buf = jnp.zeros((cap + 1,) + a.shape[1:], dtype=a.dtype)
        out[name] = buf.at[tgt].set(a, mode="drop")[:cap]
    nsel = jnp.arange(cap) < jnp.minimum(total, cap)
    factor = (total + cap - 1) // cap
    return out, nsel, jnp.maximum(factor - 1, 0)


def _compact_chunk(chunk: Chunk, cap: int):
    """Compact a Chunk's live rows into a capacity-`cap` Chunk."""
    arrays = {}
    for uid, col in chunk.columns.items():
        arrays[uid + ".d"] = col.data
        arrays[uid + ".v"] = col.valid
    out, nsel, ovf = _compact(arrays, chunk.sel, cap)
    cols = {
        uid: Column(data=out[uid + ".d"], valid=out[uid + ".v"],
                    type_=col.type_)
        for uid, col in chunk.columns.items()
    }
    return Chunk(cols, nsel), ovf


def _mix_hash(bits: List[jax.Array]) -> jax.Array:
    """Combine per-key bit patterns into one routing/sort hash."""
    if len(bits) == 1:
        return bits[0]  # exact value: collision-free fast path
    h = jnp.zeros_like(bits[0])
    for b in bits:
        h = (h ^ b) * np.int64(-7046029254386353131) + np.int64(0x165667B19E3779F9)
    return h


def _normalize_red_limbs(red, layout, aggs):
    """Carry-normalize (lo, hi) decimal-sum limb pairs in a reduced
    payload list (post-exchange reduce), keeping lo in [0, 2^32) for
    the TopN limb sort keys and the host finalize."""
    from tidb_tpu.executor.aggregate import normalize_limbs

    idx_of = {name: i for i, (name, _) in enumerate(layout)}
    red = list(red)
    for j, _a in enumerate(aggs):
        hi_i = idx_of.get(f"a{j}.sumhi")
        if hi_i is not None:
            lo_i = idx_of[f"a{j}.sum"]
            lo, hi = normalize_limbs(red[lo_i], red[hi_i])
            red[lo_i], red[hi_i] = lo, hi
    return red


@dataclass
class _Source:
    """A sharded scan input (4 fragment args: data, valid, sel, refs —
    refs carries the FoR bases of encoded staged columns, {} raw)."""
    scan: PScan
    stages: list


@dataclass
class _Broadcast:
    """A host-materialized subtree entering replicated (2 args + sel)."""
    plan: object  # physical subtree to materialize
    schema: list


@dataclass
class FragmentProgram:
    """Compiled description of a distributable agg subtree."""
    agg: PHashAgg
    sources: List[_Source]
    broadcasts: List[_Broadcast]
    n_growth: int                      # number of growth knobs
    sig: str
    build_fn: Callable                 # (growths tuple) -> per-shard program
    out_kind: str                      # "segment" | "generic"
    domains: List[int] = field(default_factory=list)
    growth_defaults: Tuple[float, ...] = ()
    growth_kinds: Tuple[str, ...] = ()
    # source indexes that must NOT be streamed in batches: they sit on
    # the build side of a non-inner join, where partitioning the build
    # set changes per-probe-row match decisions (semi/anti/left)
    stream_unsafe: frozenset = frozenset()
    # (resolved items, k) when a per-shard partial top-k is compiled in;
    # streaming executions must recompile without it (a group's partials
    # span batches — dropping it in one batch would corrupt its state)
    topn: object = None


class _Unsupported(Exception):
    pass


class _Compiler:
    def __init__(self, n_parts: int):
        self.n_parts = n_parts
        self.sources: List[_Source] = []
        self.broadcasts: List[_Broadcast] = []
        self.n_growth = 0
        # default capacity factor per knob, in assignment order: exchanges
        # start at 2x (skew headroom), join expansion at 1x (covers <=1
        # match per probe row — the PK-FK common case). "exch" knobs
        # report an overflow row count (executor doubles); "expand" knobs
        # report required-factor-minus-one (executor jumps in one step —
        # a skewed many-many join can demand 100x+ slots at once)
        self.growth_defaults: List[float] = []
        self.growth_kinds: List[str] = []
        self.sig: List[str] = []
        self.stream_unsafe: set = set()

    def _add_growth(self, default: float, kind: str) -> int:
        idx = self.n_growth
        self.n_growth += 1
        self.growth_defaults.append(default)
        self.growth_kinds.append(kind)
        return idx

    def _compact_knob(self, est_rows: float) -> Tuple[int, int]:
        """Estimate-sized compaction target: a "compact" knob plus its
        base capacity (~2x the per-shard cardinality estimate, floor 64).
        The base is part of the fragment signature — a stats change that
        moves an estimate must not hit a cached fragment compiled with
        the old capacities."""
        base = max(64, int(np.ceil(2.0 * max(est_rows, 1.0) / self.n_parts)))
        idx = self._add_growth(1.0, "compact")
        self.sig.append(f"cap{idx}:{base}")
        return idx, base

    # -- producers ---------------------------------------------------------

    def producer(self, plan) -> Callable:
        """Compile a subtree into emit(env, growths) -> (Chunk, [ovf])."""
        stages, base = peel_stages(plan)
        if isinstance(base, PScan) and base.table is not None:
            return self._scan_producer(base, scan_stages_for(base, stages))
        if isinstance(base, PHashJoin):
            join_emit = self._join_producer(base)
            if stages:
                pipe = make_pipeline_fn(stages)

                def emit(env, growths, _j=join_emit, _p=pipe):
                    ch, ovf = _j(env, growths)
                    return _p(ch), ovf

                self.sig.append(f"stages{stages!r}")
                return emit
            return join_emit
        if isinstance(base, PHashAgg) and not stages:
            p = self._try_partial_agg_producer(base)
            if p is not None:
                return p
        # anything else (agg subtree, union, limit...) becomes a broadcast
        return self._broadcast_producer(plan)

    def _partial_agg_ok(self, plan) -> bool:
        """Can `plan` run as a per-shard partial aggregate (a SHARDED
        join input, not a broadcast)?"""
        stages, agg = peel_stages(plan)
        if stages or not isinstance(agg, PHashAgg):
            return False
        from tidb_tpu.planner.logical import CORE_AGGS

        if (agg.strategy != "generic" or not agg.group_exprs
                or any(a.distinct or a.func not in CORE_AGGS
                       or a.func == "avg" for a in agg.aggs)):
            return False
        # ONLY eager-agg partials (rule-derived 'eagg.' uids): per-shard
        # emission is sound because THAT rule's upper aggregate re-sums
        # partial rows; a user-written derived-table aggregate has plain
        # uids and must broadcast (shard-local groups would duplicate)
        if not all(a.uid.startswith("eagg.") for a in agg.aggs):
            return False
        _, base = peel_stages(agg.child)
        return isinstance(base, PScan) and base.table is not None

    def _try_partial_agg_producer(self, agg: PHashAgg):
        """A partial aggregate as a JOIN INPUT (the device side of eager
        aggregation): each shard reduces its local rows into a group
        table and emits the groups as ordinary rows. No cross-shard
        merge is needed — the rewrite's upper aggregate re-sums partial
        rows, so shard-local groups with duplicate keys are exactly what
        the row-level semantics produced. Returns None for shapes the
        kernel can't take (falls back to the broadcast producer).

        DECIMAL sums recombine their two limbs on device (hi*2^32+lo):
        exact while a per-shard per-group partial stays inside int64 —
        the same representability bound as the final DECIMAL result."""
        from tidb_tpu.executor.agg_device import make_partial_kernel

        if not self._partial_agg_ok(agg):
            return None
        child_emit = self.producer(agg.child)
        partial = make_partial_kernel(agg.group_exprs, agg.aggs)
        types = {c.uid: c.type_ for c in agg.schema}
        self.sig.append(
            f"eagg:{agg.group_exprs!r}:{agg.aggs!r}:{agg.group_uids!r}")

        def emit(env, growths):
            chunk, ovfs = child_emit(env, growths)
            t = partial(chunk)
            live = jnp.arange(chunk.capacity) < t["n"]
            cols = {}
            for i, uid in enumerate(agg.group_uids):
                cols[uid] = Column(data=t[f"k{i}.d"],
                                   valid=t[f"k{i}.v"] & live,
                                   type_=types[uid])
            for j, a in enumerate(agg.aggs):
                cnt = t[f"a{j}.cnt"]
                if a.func == "count":
                    data, valid = cnt, live
                elif a.func == "sum":
                    data = t[f"a{j}.sum"]
                    if f"a{j}.sumhi" in t:
                        data = data + (t[f"a{j}.sumhi"] << 32)
                    valid = live & (cnt > 0)
                else:  # min / max
                    data = t[f"a{j}.{a.func}"]
                    valid = live & (cnt > 0)
                cols[a.uid] = Column(
                    data=data.astype(types[a.uid].np_dtype),
                    valid=valid, type_=types[a.uid])
            return Chunk(cols, live), ovfs

        return emit

    def _scan_producer(self, scan: PScan, stages) -> Callable:
        if any(c.name == "__rowid__" for c in scan.schema):
            # physical rowids are a host-engine concept (shardings
            # re-partition rows); DML selects fall back to the host path
            raise _Unsupported("__rowid__ pseudo-column in a fragment")
        idx = len(self.sources)
        self.sources.append(_Source(scan, stages))
        uid_of = {c.name: c.uid for c in scan.schema}
        type_of = {c.name: c.type_ for c in scan.schema}
        pipe = make_pipeline_fn(stages) if stages else (lambda c: c)
        self.sig.append(f"scan{idx}:{scan.table_name}:{stages!r}")

        def emit(env, growths):
            from tidb_tpu.ops.segment_scan import decode_for

            data, valid, sel, refs = env["scan"][idx]
            # the sharding carries every table column; take only the
            # (pruned) scan schema. Encoded columns decode here, inside
            # the compiled program (stored + ref, widened to the device
            # repr), so only the narrow payload crossed the host boundary
            cols = {}
            for name in uid_of:
                t = type_of[name]
                d = decode_for(data[name][0], refs.get(name), t.np_dtype)
                cols[uid_of[name]] = Column(data=d, valid=valid[name][0],
                                            type_=t)
            return pipe(Chunk(cols, sel[0])), []

        return emit

    def _broadcast_producer(self, plan) -> Callable:
        idx = len(self.broadcasts)
        self.broadcasts.append(_Broadcast(plan, list(plan.schema)))
        types = {c.uid: c.type_ for c in plan.schema}
        self.sig.append(f"bcast{idx}:{[(c.uid, c.type_) for c in plan.schema]!r}")

        def emit(env, growths):
            data, valid, sel = env["bcast"][idx]
            cols = {uid: Column(data=data[uid], valid=valid[uid], type_=types[uid])
                    for uid in data}
            return Chunk(cols, sel), []

        return emit

    # -- joins -------------------------------------------------------------

    def _join_producer(self, join: PHashJoin) -> Callable:
        if not join.eq_left:
            raise _Unsupported("keyless (cross) join")
        if join.kind not in ("inner", "left", "semi", "anti"):
            raise _Unsupported(f"join kind {join.kind}")

        probe_idx = 1 - join.build_side
        probe_plan = join.children[probe_idx]
        build_plan = join.children[join.build_side]
        probe_keys = join.eq_left if probe_idx == 0 else join.eq_right
        build_keys = join.eq_right if join.build_side == 1 else join.eq_left

        # decide build mode BEFORE compiling children: a broadcast build
        # skips both exchanges
        def _is_bcast(plan) -> bool:
            _, base = peel_stages(plan)
            if isinstance(base, PScan) and base.table is not None:
                return False
            if isinstance(base, PHashJoin):
                return False
            if self._partial_agg_ok(plan):
                # eager-agg partial over a sharded scan: each shard emits
                # its local groups exactly once — sharded, not replicated
                return False
            return True

        build_is_bcast = _is_bcast(build_plan)
        if _is_bcast(probe_plan):
            # a replicated probe side would be joined (and aggregated)
            # once PER SHARD, inflating every result by n_parts
            raise _Unsupported("broadcast probe side")

        probe_emit = self.producer(probe_plan)
        n_before_build = len(self.sources)
        build_emit = self.producer(build_plan)
        if join.kind != "inner":
            # a batched build side would re-decide semi/anti/left matches
            # per batch: every source under it is pinned resident
            self.stream_unsafe.update(
                range(n_before_build, len(self.sources)))

        exchange = not build_is_bcast
        g_exch = self._add_growth(2.0, "exch") if exchange else None
        g_expand = self._add_growth(1.0, "expand")
        # estimate-sized compaction targets (overflow-retried): selective
        # filters/joins collapse live counts, and every sort/exchange
        # downstream pays for capacity — so shrink to ~2x the planner's
        # cardinality estimate wherever that is below the static capacity
        g_pcomp, p_base = self._compact_knob(probe_plan.est_rows)
        g_bcomp, b_base = self._compact_knob(build_plan.est_rows)
        g_ocomp, o_base = self._compact_knob(join.est_rows)

        kind = join.kind
        exists_sem = join.exists_sem
        other_cond = join.other_cond
        other_pred = compile_predicate(other_cond) if other_cond is not None else None
        n_parts = self.n_parts
        nk = len(probe_keys)
        need_verify = nk > 1
        self.sig.append(
            f"join:{kind}:{exists_sem}:{probe_keys!r}:{build_keys!r}:{other_cond!r}"
            f":exch{exchange}"
        )
        # probe columns survive the join; build columns only feed inner/left
        # output and other_cond evaluation
        build_cols_out = kind in ("inner", "left")

        def emit(env, growths):
            pch, p_ovf = probe_emit(env, growths)
            bch, b_ovf = build_emit(env, growths)
            ovfs = list(p_ovf) + list(b_ovf)

            capP = int(np.ceil(growths[g_pcomp] * p_base))
            if capP < pch.capacity:
                pch, o = _compact_chunk(pch, capP)
                ovfs.append((g_pcomp, pmax_compat(o, _AXES)))
            capB = int(np.ceil(growths[g_bcomp] * b_base))
            if capB < bch.capacity:
                bch, o = _compact_chunk(bch, capB)
                ovfs.append((g_bcomp, pmax_compat(o, _AXES)))

            p_outs = [eval_expr(k, pch) for k in probe_keys]
            b_outs = [eval_expr(k, bch) for k in build_keys]
            p_bits = [_key_bits(d, v) for d, v in p_outs]
            b_bits = [_key_bits(d, v) for d, v in b_outs]
            p_kvalid = p_outs[0][1]
            b_kvalid = b_outs[0][1]
            for _, v in p_outs[1:]:
                p_kvalid = p_kvalid & v
            for _, v in b_outs[1:]:
                b_kvalid = b_kvalid & v
            p_hash = _mix_hash(p_bits)
            b_hash = _mix_hash(b_bits)

            # NOT IN null semantics: any live build row with a NULL key
            # empties the anti result — counted across the whole mesh
            b_null = None
            if kind == "anti" and not exists_sem:
                b_null = jax.lax.psum(
                    jnp.sum((bch.sel & ~b_kvalid).astype(jnp.int64)), _AXES)

            def flat(ch: Chunk, bits, kvalid):
                arrs = {}
                for uid, col in ch.columns.items():
                    arrs[uid + ".d"] = col.data
                    arrs[uid + ".v"] = col.valid
                for i, b in enumerate(bits):
                    arrs[f"__kb{i}"] = b
                arrs["__kv"] = kvalid
                return arrs

            def unflat(arrs, ref: Chunk, sel):
                cols = {
                    uid: Column(data=arrs[uid + ".d"], valid=arrs[uid + ".v"],
                                type_=col.type_)
                    for uid, col in ref.columns.items()
                }
                bits = [arrs[f"__kb{i}"] for i in range(nk)]
                return Chunk(cols, sel), bits, arrs["__kv"]

            if exchange:
                growth = growths[g_exch]
                pr, pr_sel, pr_hash, povf = repartition_by_key(
                    flat(pch, p_bits, p_kvalid), pch.sel, p_hash,
                    jnp.ones_like(p_kvalid), n_parts, growth)
                br, br_sel, br_hash, bovf = repartition_by_key(
                    flat(bch, b_bits, b_kvalid), bch.sel, b_hash,
                    jnp.ones_like(b_kvalid), n_parts, growth)
                ovfs.append((g_exch, jax.lax.psum(povf + bovf, _AXES)))
                pch2, p_bits2, p_kvalid2 = unflat(pr, pch, pr_sel)
                bch2, b_bits2, b_kvalid2 = unflat(br, bch, br_sel)
                p_hash2, b_hash2 = pr_hash, br_hash
            else:
                pch2, p_bits2, p_kvalid2, p_hash2 = pch, p_bits, p_kvalid, p_hash
                bch2, b_bits2, b_kvalid2, b_hash2 = bch, b_bits, b_kvalid, b_hash

            Rp = pch2.capacity
            Rb = bch2.capacity

            # local sorted-range join on the hash, through the SAME
            # fused primitives the single-chip executor runs
            # (ops/join_kernels): sorted build runs with dead rows
            # sorted after live ones, probe via the configured strategy
            # (ops/hash_probe's open-addressing VMEM table on TPU,
            # searchsorted elsewhere), and scatter+prefix-sum expansion
            from tidb_tpu.ops.join_kernels import (
                probe_hash_ranges,
                sort_build_hashes,
                tile_positions,
            )

            b_live = bch2.sel & b_kvalid2
            sh, cvi, order = sort_build_hashes(b_hash2, b_live)
            p_ok = pch2.sel & p_kvalid2
            # probe strategy threaded per-statement via build_fn (the
            # module-global read raced concurrent sessions, ISSUE 12)
            lo, cnt = probe_hash_ranges(sh, cvi, p_hash2, p_ok,
                                        mode=env.get("probe_mode"))

            cum = jnp.cumsum(cnt)
            total = cum[-1]
            growth_j = growths[g_expand]
            capJ = int(np.ceil(growth_j * Rp))
            # required-factor-minus-one, maxed over shards (0 = fits)
            factor = (total + capJ - 1) // capJ
            ovfs.append((g_expand, pmax_compat(jnp.maximum(factor - 1, 0), _AXES)))

            valid_out, p_row, b_sorted_pos, k = tile_positions(
                lo, cnt, cum, 0, capJ, Rp, Rb)
            b_row = order[b_sorted_pos]

            sel_out = valid_out
            if need_verify:  # hash routing can collide; verify exact keys
                for pb, bb, in zip(p_bits2, b_bits2):
                    sel_out = sel_out & (pb[p_row] == bb[b_row])
                sel_out = sel_out & p_kvalid2[p_row] & b_kvalid2[b_row]

            cols = {}
            for uid, col in pch2.columns.items():
                cols[uid] = col.gather(p_row, valid_out)
            for uid, col in bch2.columns.items():
                bc = col.gather(b_row, valid_out)
                cols[uid] = Column(bc.data, bc.valid & sel_out, col.type_)
            joined = Chunk(cols, sel_out & pch2.sel[p_row])

            if other_pred is not None:
                joined = joined.filter(other_pred(joined))

            if kind == "inner":
                result = joined
            else:
                # per-probe-row match flags (post-cond): scatter-or by p_row
                m = jnp.zeros(Rp, dtype=jnp.int32).at[p_row].add(
                    joined.sel.astype(jnp.int32)) > 0
                if kind == "semi":
                    result = pch2.with_sel(p_ok & m)
                elif kind == "anti":
                    if exists_sem:
                        keep = pch2.sel & ~(p_kvalid2 & m)
                    else:
                        keep = pch2.sel & p_kvalid2 & ~m & (b_null == 0)
                    result = pch2.with_sel(keep)
                else:
                    # left join: expanded matches + one NULL-build row for
                    # each unmatched live probe row, concatenated
                    pad_sel = pch2.sel & ~m
                    out_cols = {}
                    for uid, col in pch2.columns.items():
                        jc = joined.columns[uid]
                        out_cols[uid] = Column(
                            jnp.concatenate([jc.data, col.data]),
                            jnp.concatenate([jc.valid, col.valid]),
                            col.type_,
                        )
                    for uid, col in bch2.columns.items():
                        jc = joined.columns[uid]
                        out_cols[uid] = Column(
                            jnp.concatenate([jc.data, jnp.zeros(Rp, dtype=col.data.dtype)]),
                            jnp.concatenate([jc.valid, jnp.zeros(Rp, dtype=jnp.bool_)]),
                            col.type_,
                        )
                    result = Chunk(out_cols, jnp.concatenate([joined.sel, pad_sel]))

            capO = int(np.ceil(growths[g_ocomp] * o_base))
            if capO < result.capacity:
                result, o = _compact_chunk(result, capO)
                ovfs.append((g_ocomp, pmax_compat(o, _AXES)))
            return result, ovfs

        return emit

    # -- per-shard partial top-k ------------------------------------------

    def _topn_select(self, items, nk, layout, kmax, aggs):
        """Build fn(n, fk, fkv, red) -> (n', fk', fkv', red') keeping
        each shard's top `kmax` groups under the resolved sort items —
        the exchange routes every group to exactly one shard, so the
        union of per-shard top-k sets contains the global top-k; the
        root TopNExec applies the exact host ordering over that superset
        (the mesh analogue of the reference's TopN-into-coprocessor
        pushdown, SURVEY.md:93). Encodings mirror sort.py's _sort_order:
        NULLs first ASC / last DESC, dead lanes always last; desc ints
        invert via ~x (order-exact), floats negate."""
        self.sig.append(f"topn:{items!r}:{kmax}")

        def select(n, fk, fkv, red):
            state = {name: arr for (name, _), arr in zip(layout, red)}
            S = (fk[0] if nk else red[0]).shape[0]
            kcap = min(kmax, S)
            live = jnp.arange(S, dtype=jnp.int64) < n
            ops = []
            for kind, idx, desc in items:
                limbs = None
                if kind == "key":
                    data, valid = fk[idx], fkv[idx]
                elif kind == "cnt":
                    data = state[f"a{idx}.cnt"]
                    valid = jnp.ones(S, dtype=jnp.bool_)
                elif kind == "avg":
                    c = state[f"a{idx}.cnt"]
                    s = state[f"a{idx}.sum"]
                    hi = state.get(f"a{idx}.sumhi")
                    # jnp-native limb->float (limbs_to_float is numpy);
                    # divide in the SAME order as the host finalize
                    # (scale first, then count) so rounding can never
                    # rank two groups differently than the final TopN
                    sf = (hi.astype(jnp.float64) * float(1 << 32)
                          + s.astype(jnp.float64)
                          if hi is not None else s.astype(jnp.float64))
                    a = aggs[idx]
                    if a.arg is not None and a.arg.type_.kind == TypeKind.DECIMAL:
                        sf = sf / (10 ** a.arg.type_.scale)
                    data = sf / jnp.maximum(c, 1).astype(jnp.float64)
                    valid = c > 0
                else:  # sum | min | max: NULL when no non-null input
                    data = state[f"a{idx}.{kind}"]
                    valid = state[f"a{idx}.cnt"] > 0
                    if kind == "sum" and f"a{idx}.sumhi" in state:
                        # two-limb decimal sum: carry-normalize, then
                        # (hi, lo) lexicographic IS the numeric order
                        # (lo in [0, 2^32) after the carry)
                        from tidb_tpu.executor.aggregate import (
                            normalize_limbs,
                        )

                        lo, hi = normalize_limbs(data, state[f"a{idx}.sumhi"])
                        limbs = (hi, lo)
                rank = jnp.where(
                    ~live, jnp.int32(2),
                    jnp.where(valid, jnp.int32(0) if desc else jnp.int32(1),
                              jnp.int32(1) if desc else jnp.int32(0)))
                if limbs is not None:
                    dead = ~(valid & live)
                    khi = jnp.where(dead, 0, limbs[0])
                    klo = jnp.where(dead, 0, limbs[1])
                    if desc:
                        khi, klo = ~khi, ~klo
                    ops += [rank, khi, klo]
                    continue
                if data.dtype == jnp.bool_:
                    data = data.astype(jnp.int64)
                if jnp.issubdtype(data.dtype, jnp.floating):
                    key = jnp.where(valid & live, data.astype(jnp.float64), 0.0)
                    if desc:
                        key = -key
                else:
                    key = jnp.where(valid & live, data.astype(jnp.int64), 0)
                    if desc:
                        key = ~key
                ops += [rank, key]
            perm = jax.lax.sort(
                tuple(ops) + (jnp.arange(S, dtype=jnp.int64),),
                num_keys=len(ops))[-1][:kcap]
            return (jnp.minimum(n, kcap),
                    [a[perm] for a in fk], [a[perm] for a in fkv],
                    [a[perm] for a in red])

        return select

    # -- aggregation root --------------------------------------------------

    def compile_agg(self, agg: PHashAgg,
                    topn=None) -> Tuple[Callable, str, List[int]]:
        # the agg child must peel to a real sharded scan or a join tree;
        # anything else would make the whole input a replicated broadcast
        _, base = peel_stages(agg.child)
        if not (isinstance(base, PHashJoin)
                or (isinstance(base, PScan) and base.table is not None)):
            raise _Unsupported("agg over non-scan/join subtree")
        child_emit = self.producer(agg.child)

        if any(a.distinct for a in agg.aggs):
            raise _Unsupported("DISTINCT aggregates")
        from tidb_tpu.planner.logical import CORE_AGGS

        for a in agg.aggs:
            if a.func not in CORE_AGGS:
                raise _Unsupported(f"aggregate {a.func} on the fragment tier")

        if agg.strategy == "segment":
            sizes = agg.segment_sizes or []
            domains = [s + 1 for s in sizes]
            init_state, update, _ = make_segment_kernel(
                agg.group_exprs, agg.aggs, domains)
            self.sig.append(f"segagg:{agg.group_exprs!r}:{agg.aggs!r}:{domains!r}")

            def emit(env, growths):
                chunk, ovfs = child_emit(env, growths)
                state = merge_state(update(init_state(), chunk))
                return state, ovfs

            return emit, "segment", domains

        if not agg.group_exprs:
            raise _Unsupported("generic global agg")  # planner uses segment
        partial = make_partial_kernel(agg.group_exprs, agg.aggs)
        layout = _state_layout(agg.aggs)
        nk = len(agg.group_exprs)
        topn_fn = (self._topn_select(topn[0], nk, layout, topn[1], agg.aggs)
                   if topn is not None else None)
        g_agg = self._add_growth(2.0, "exch")
        n_parts = self.n_parts
        # estimate-sized shrink targets (see _compact): the partial sort
        # pays for input capacity and the exchange pays for table slots
        g_in, in_base = self._compact_knob(agg.child.est_rows)
        g_tab, tab_base = self._compact_knob(agg.est_rows)
        self.sig.append(f"genagg:{agg.group_exprs!r}:{agg.aggs!r}")

        def emit(env, growths):
            chunk, ovfs = child_emit(env, growths)
            capI = int(np.ceil(growths[g_in] * in_base))
            if capI < chunk.capacity:
                chunk, o = _compact_chunk(chunk, capI)
                ovfs.append((g_in, pmax_compat(o, _AXES)))
            table = partial(chunk)  # local dedup before the exchange
            S = table["k0.d"].shape[0]
            capT = int(np.ceil(growths[g_tab] * tab_base))
            if capT < S:
                # groups are dense in [0, n): slicing the slot arrays is
                # free and shrinks everything the exchange must carry
                factor = (table["n"] + capT - 1) // capT
                ovfs.append((g_tab, pmax_compat(jnp.maximum(factor - 1, 0), _AXES)))
                table = {k: (v if k == "n" else v[:capT])
                         for k, v in table.items()}
                S = capT
            live = jnp.arange(S) < table["n"]
            kd = [table[f"k{i}.d"] for i in range(nk)]
            kv = [table[f"k{i}.v"] for i in range(nk)]
            khash = _mix_hash([_key_bits(d, v) for d, v in zip(kd, kv)])

            arrays = {}
            for i in range(nk):
                arrays[f"k{i}.d"] = kd[i]
                arrays[f"k{i}.v"] = kv[i]
            for name, _ in layout:
                arrays[name] = table[name]
            recv, recv_sel, _, ovf = repartition_by_key(
                arrays, live, khash, jnp.ones_like(live), n_parts,
                growths[g_agg])
            ovfs.append((g_agg, jax.lax.psum(ovf, _AXES)))

            rkd = [recv[f"k{i}.d"] for i in range(nk)]
            rkv = [recv[f"k{i}.v"] for i in range(nk)]
            rbits = [_key_bits(d, v) for d, v in zip(rkd, rkv)]
            payload = [recv[name] for name, _ in layout]
            ops = [op for _, op in layout]
            # exact mode: the emitted tables are duplicate-free, so the
            # host finalize is a straight per-part conversion — no merge
            n, fk, fkv, red = _sort_reduce(rbits, rkv, rkd, recv_sel,
                                           payload, ops, exact=True)
            red = _normalize_red_limbs(red, layout, agg.aggs)
            if topn_fn is not None:
                n, fk, fkv, red = topn_fn(n, fk, fkv, red)
            out = {"n": n[None]}
            for i in range(nk):
                out[f"k{i}.d"] = fk[i]
                out[f"k{i}.v"] = fkv[i]
            for (name, _), arr in zip(layout, red):
                out[name] = arr
            return out, ovfs

        return emit, "generic", []


def compile_fragment(agg: PHashAgg, mesh, n_parts: int,
                     topn=None) -> Optional[FragmentProgram]:
    """Try to compile an agg-rooted subtree; None if not distributable.
    `topn` = (resolved items, k) applies a per-shard partial top-k to
    the generic group tables before they leave the device (SURVEY.md:93
    TopN pushdown); ignored for segment aggs, whose bounded states are
    already cheap to rank on the host."""
    from tidb_tpu.utils.failpoint import inject

    # chaos hook: fail fragment compilation itself (the coordinator
    # must surface a clean error, not a half-built program)
    inject("fragment.compile")
    c = _Compiler(n_parts)
    try:
        emit, out_kind, domains = c.compile_agg(agg, topn=topn)
    except _Unsupported:
        return None
    if not c.sources:
        return None  # nothing sharded: run single-chip
    from tidb_tpu.utils import tracing
    from tidb_tpu.utils.metrics import FRAGMENT_COMPILE

    FRAGMENT_COMPILE.inc(kind=out_kind)
    # compile events become annotations on the statement's trace span
    tracing.annotate(f"compile:fragment:{out_kind}")

    n_src = len(c.sources)
    n_bc = len(c.broadcasts)
    n_knobs = c.n_growth

    def build_fn(growths: Tuple[float, ...], probe_mode: str = None):
        # probe_mode: the statement's resolved tidb_tpu_join_probe_mode
        # (trace-time STATIC — callers key their fragment cache on it so
        # a knob flip can never serve a program traced for the other
        # strategy); None = the hash_probe process default
        def frag(*args):
            env = {"scan": [], "bcast": [], "probe_mode": probe_mode}
            i = 0
            for _ in range(n_src):
                env["scan"].append((args[i], args[i + 1], args[i + 2],
                                    args[i + 3]))
                i += 4
            for _ in range(n_bc):
                env["bcast"].append((args[i], args[i + 1], args[i + 2]))
                i += 3
            out, reports = emit(env, growths)
            # per-knob overflow vector, slot-indexed by knob id so the
            # executor always grows exactly the blown capacity (emission
            # order differs from knob-assignment order)
            slots = [jnp.zeros((), dtype=jnp.int64)] * n_knobs
            for idx, v in reports:
                slots[idx] = slots[idx] + v.astype(jnp.int64)
            ovf = (jnp.stack(slots) if slots
                   else jnp.zeros((0,), dtype=jnp.int64))
            return out, ovf

        out_spec = P() if out_kind == "segment" else P(_AXES)
        in_specs = tuple([_SPEC, _SPEC, _SPEC, P()] * n_src
                         + [P(), P(), P()] * n_bc)
        # lint: disable=jit-hygiene -- signature-keyed: DistFragmentExec
        # caches build_fn(growths) under (sig, growths, shapes, types)
        # via ShardCache.get_fragment; the closure carries the compiled
        # plan description only — every array arrives as an argument
        return jax.jit(shard_map_compat(
            frag, mesh=mesh, in_specs=in_specs, out_specs=(out_spec, P()),
            # pallas_call outputs carry no vma metadata; the fragment's
            # out_specs are the authority here
            check_vma=False,
        ))

    return FragmentProgram(
        agg=agg, sources=c.sources, broadcasts=c.broadcasts,
        n_growth=c.n_growth, sig="|".join(c.sig), build_fn=build_fn,
        out_kind=out_kind, domains=domains,
        growth_defaults=tuple(c.growth_defaults),
        growth_kinds=tuple(c.growth_kinds),
        stream_unsafe=frozenset(c.stream_unsafe),
        topn=topn if out_kind == "generic" else None,
    )
