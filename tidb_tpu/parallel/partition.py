"""Sharded tables: the partition catalog (region-cache analogue).

A host Table is split row-wise into P equal fixed-capacity partitions,
one per mesh shard, padded to a static per-shard row capacity R. Layout
is [P, R] per column with the leading axis sharded over ("dcn","shard"),
so every fragment sees exactly one partition as a capacity-R Chunk and
XLA never moves base data — only exchange traffic crosses ICI.

Ref counterpart: distsql region splitting + tablecodec row layout; here
rows are born columnar and the "region boundary" is a static row range.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tidb_tpu.parallel.mesh import dcn_axis, shard_axis
from tidb_tpu.types import SQLType

__all__ = ["ShardedTable", "shard_table", "stream_batches", "table_bytes"]


@dataclass
class ShardedTable:
    """Columns as [P, R] device arrays sharded on axis 0 of `mesh`.

    With ``encode=True`` staging, integer-backed columns travel
    frame-of-reference encoded: ``data[name]`` holds ``value - ref`` in
    the narrowest of int8/int16/int32 that covers the column's valid
    range, and ``refs[name]`` carries the int64 base. Fragment programs
    decode (``stored + ref``, widened to the column's device repr)
    INSIDE the compiled program, so the narrow bytes are all that cross
    host→device — the columnar store's byte shrink applied to the
    distributed staging path (ISSUE 9 satellite / ROADMAP 5a)."""

    mesh: Mesh
    n_parts: int
    rows_per_part: int
    total_rows: int
    data: Dict[str, jax.Array]      # name -> [P, R]
    valid: Dict[str, jax.Array]     # name -> [P, R] bool
    sel: jax.Array                  # [P, R] bool: live rows
    types: Dict[str, SQLType]
    dicts: Dict[str, object]        # string dictionaries (host-side)
    # FoR bases for encoded columns (absent name = raw staging); np
    # scalars passed to fragments as ARGS so per-batch bases never bake
    # into a trace
    refs: Dict[str, np.int64] = field(default_factory=dict)
    # process-unique, never-recycled id: cache keys built from it can never
    # alias a different sharding the way id()-based keys can after GC
    serial: int = field(default_factory=itertools.count().__next__)



def table_bytes(table, columns: Optional[List[str]] = None) -> int:
    """Device bytes a full sharding of `table` would occupy (data +
    validity for the chosen columns)."""
    names = columns or [c.name for c in table.schema.columns]
    n = table.n
    total = 0
    for name in names:
        total += n * (table.data[name].dtype.itemsize + 1)  # + valid byte
    return total + n  # + sel mask


def _encode_staged(d: np.ndarray, v: np.ndarray, type_: SQLType):
    """(stored, ref) when FoR staging pays for this column slice, else
    (None, 0). Delegates the selection rule AND the NULL-pinning shift
    to columnar.encoding.encode_column — the ONE encoder whose payloads
    ops/segment_scan.decode_for decodes — keeping only the
    did-it-actually-shrink guard local (the segment store accepts
    same-width encodings; the staging path has nothing to gain)."""
    from tidb_tpu.columnar.encoding import INT_BACKED_KINDS, encode_column

    if type_.kind not in INT_BACKED_KINDS \
            or not np.issubdtype(d.dtype, np.integer) \
            or d.dtype.itemsize <= 1 or not v.any():
        return None, 0
    enc, stored = encode_column(d, v, type_)
    if enc.kind != "for" or stored.dtype.itemsize >= d.dtype.itemsize:
        return None, 0
    return stored, enc.ref


def stream_batches(table, mesh: Mesh, columns: Optional[List[str]],
                   rows_per_part: int, encode: bool = False):
    """Yield fixed-shape ShardedTable batches covering the whole table.

    The >HBM path (ref: SURVEY.md hard part 6 + the IndexLookUp double
    pipeline): batch b stages rows [b*P*R, (b+1)*P*R) as one [P, R]
    sharding. Every batch has identical shapes/types, so the compiled
    fragment is reused across batches, and jax's async dispatch overlaps
    batch k's compute with batch k+1's host->device staging."""
    n_parts = mesh.shape[dcn_axis] * mesh.shape[shard_axis]
    rows_per_batch = n_parts * rows_per_part
    n = table.n
    for start in range(0, max(n, 1), rows_per_batch):
        yield shard_table(table, mesh, columns=columns,
                          rows_per_part=rows_per_part,
                          row_range=(start, min(start + rows_per_batch, n)),
                          encode=encode)


def shard_table(table, mesh: Mesh, columns: Optional[List[str]] = None,
                rows_per_part: Optional[int] = None,
                row_range: Optional[tuple] = None,
                encode: bool = False) -> ShardedTable:
    """Partition a host Table (or a row range of it) across the mesh's
    (dcn x shard) grid. ``encode=True`` stages integer-backed columns
    FoR-encoded in narrow dtypes (see ShardedTable.refs)."""
    n_parts = mesh.shape[dcn_axis] * mesh.shape[shard_axis]
    lo, hi = row_range if row_range is not None else (0, table.n)
    n = hi - lo
    R = rows_per_part or max((n + n_parts - 1) // n_parts, 1)
    if R * n_parts < n:
        raise ValueError(f"rows_per_part {R} too small for {n} rows / {n_parts} parts")
    names = columns or [c.name for c in table.schema.columns]
    spec = NamedSharding(mesh, P((dcn_axis, shard_axis), None))

    live = np.zeros((n_parts, R), dtype=np.bool_)
    data: Dict[str, jax.Array] = {}
    valid: Dict[str, jax.Array] = {}
    types: Dict[str, SQLType] = {}
    dicts: Dict[str, object] = {}
    refs: Dict[str, np.int64] = {}

    host_cols = {}
    for name in names:
        info = table.schema.col(name)
        d, v = table.column_slice(name, lo, hi)
        if encode:
            stored, ref = _encode_staged(d, v, info.type_)
            if stored is not None:
                d = stored
                refs[name] = np.int64(ref)
        buf = np.zeros((n_parts, R), dtype=d.dtype)
        vbuf = np.zeros((n_parts, R), dtype=np.bool_)
        host_cols[name] = (buf, vbuf, d, v)
        types[name] = info.type_
        dc = table.dicts.get(name)
        if dc is not None:
            dicts[name] = dc

    row_live = table.live_mask(lo, hi)
    for p in range(n_parts):
        s, e = p * R, min((p + 1) * R, n)
        if s >= n:
            break
        m = e - s
        live[p, :m] = row_live[s:e]
        for name in names:
            buf, vbuf, d, v = host_cols[name]
            buf[p, :m] = d[s:e]
            vbuf[p, :m] = v[s:e]

    from tidb_tpu.utils import dispatch as dsp

    for name in names:
        buf, vbuf, _, _ = host_cols[name]
        data[name] = jax.device_put(buf, spec)
        valid[name] = jax.device_put(vbuf, spec)
        dsp.record(2, site="stage")
    sel = jax.device_put(live, spec)
    dsp.record(site="stage")

    return ShardedTable(
        mesh=mesh, n_parts=n_parts, rows_per_part=R, total_rows=n,
        data=data, valid=valid, sel=sel, types=types, dicts=dicts,
        refs=refs,
    )
