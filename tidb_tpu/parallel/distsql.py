"""Distributed plan fragments: scan/agg/join over the mesh.

This is the coprocessor pushdown tier (ref: distsql.Select fan-out +
mocktikv coprocessor + MPP exchange) rebuilt as XLA collectives:

  * scan+filter+partial-agg fragments run per shard under jax.shard_map;
    partial [G]-shaped agg states merge with psum/pmin/pmax over the mesh
    (merge ops declared next to the kernel in executor/aggregate.py)
  * join repartitioning is a fixed-capacity bucket exchange over
    lax.all_to_all — rows hash to a destination shard, take a slot in a
    [P, cap] send buffer (cap = growth * R / P), and overflow is counted
    and surfaced rather than silently dropped (static shapes: capacity
    overflow is the TPU analogue of the reference's spill trigger)
  * local join per shard is sort + searchsorted probe (TPU-friendly; no
    pointer-chasing hash table). Build side must be unique-key (PK-FK
    joins — the reference's common HashJoinExec shape); many-many joins
    stay on the host executor.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column
from tidb_tpu.executor.aggregate import make_segment_kernel, merge_op_for
from tidb_tpu.executor.scan import make_pipeline_fn
from tidb_tpu.expression.compiler import eval_expr
from tidb_tpu.parallel.mesh import dcn_axis, shard_axis, shard_map_compat
from tidb_tpu.parallel.partition import ShardedTable

__all__ = [
    "merge_state",
    "make_agg_fragment",
    "make_join_agg_fragment",
    "dist_agg_fragment",
    "dist_join_agg_fragment",
    "repartition_by_key",
]

_HASH_MULT = np.int64(-7046029254386353131)  # 0x9E3779B97F4A7C15 as int64

_AXES = (dcn_axis, shard_axis)
_SPEC = P(_AXES, None)


def pmax_compat(v: jax.Array, axes=_AXES) -> jax.Array:
    """lax.pmax via all_gather + local max. The TPU backend here (axon
    TpuAotCompiler) lowers only Sum all-reduces — pmax/pmin fail to
    compile — while AllGather/AllToAll/CollectivePermute all work. The
    merged states are small ([G] or scalars), so the extra gather bytes
    are noise."""
    return jnp.max(jax.lax.all_gather(v, axes), axis=0)


def pmin_compat(v: jax.Array, axes=_AXES) -> jax.Array:
    """See pmax_compat."""
    return jnp.min(jax.lax.all_gather(v, axes), axis=0)


def merge_state(state: Dict[str, jax.Array], axes=_AXES) -> Dict[str, jax.Array]:
    """Merge per-shard partial agg states across mesh axes (final-agg step)."""
    out = {}
    for k, v in state.items():
        op = merge_op_for(k)
        if op == "sum":
            out[k] = jax.lax.psum(v, axes)
        elif op == "min":
            out[k] = pmin_compat(v, axes)
        elif op == "max":
            out[k] = pmax_compat(v, axes)
        else:
            raise ValueError(f"unknown merge op {op}")
    return out


def _shard_chunk(types: Dict, data, valid, sel, uid_map,
                 refs: Optional[Dict] = None) -> Chunk:
    from tidb_tpu.ops.segment_scan import decode_for

    cols = {}
    for name in data:
        uid = uid_map.get(name, name) if uid_map else name
        t = types[name]
        # fused FoR decode: the narrow staged payload widens to the
        # column's device repr INSIDE the program (ISSUE 9)
        d = decode_for(data[name][0], (refs or {}).get(name), t.np_dtype)
        cols[uid] = Column(data=d, valid=valid[name][0], type_=t)
    return Chunk(cols, sel[0])


def make_agg_fragment(st: ShardedTable, stages: List, group_exprs, aggs,
                      domains: List[int], uid_map: Optional[Dict[str, str]] = None):
    """Compile scan->filter->partial-agg->merge over the mesh.

    Returns a jitted fn(data, valid, sel, refs) -> merged [G]-state dict
    (replicated; fetched once); refs carries the FoR bases of encoded
    staged columns ({} for raw staging). Cache the returned fn — jit
    keys on function identity, so rebuilding it means recompiling. The
    closure deliberately captures only st's metadata (types/mesh), never
    the ShardedTable itself, so a cached fragment cannot pin retired
    [P,R] device arrays."""
    pipeline = make_pipeline_fn(stages) if stages else (lambda c: c)
    init_state, update, _ = make_segment_kernel(group_exprs, aggs, domains)
    types, mesh = dict(st.types), st.mesh

    def per_shard(data, valid, sel, refs):
        chunk = pipeline(_shard_chunk(types, data, valid, sel, uid_map,
                                      refs))
        return merge_state(update(init_state(), chunk))

    # lint: disable=jit-hygiene -- signature-keyed: callers cache the
    # returned fn via ShardCache.get_fragment (plan/shape/type key);
    # the closure carries only schema metadata, never table arrays
    return jax.jit(shard_map_compat(
        per_shard, mesh=mesh,
        in_specs=(_SPEC, _SPEC, _SPEC, P()), out_specs=P(),
        check_vma=False,
    ))


def dist_agg_fragment(st: ShardedTable, stages: List, group_exprs, aggs,
                      domains: List[int], uid_map: Optional[Dict[str, str]] = None):
    """Compile + run (convenience; see make_agg_fragment for the cached path)."""
    fn = make_agg_fragment(st, stages, group_exprs, aggs, domains, uid_map)
    return fn(st.data, st.valid, st.sel, st.refs)


# ---------------------------------------------------------------------------
# repartition exchange
# ---------------------------------------------------------------------------


def _hash_dest(key: jax.Array, n_parts: int) -> jax.Array:
    h = key * _HASH_MULT
    return ((h % n_parts) + n_parts) % n_parts


def repartition_by_key(arrays: Dict[str, jax.Array], sel: jax.Array,
                       key: jax.Array, key_valid: jax.Array, n_parts: int,
                       growth: float = 2.0,
                       axes=_AXES) -> Tuple[Dict[str, jax.Array], jax.Array, jax.Array, jax.Array]:
    """Exchange rows so equal keys land on the same shard (call in shard_map).

    arrays: name -> [R]; returns (arrays', sel', key', overflow_count) with
    [n_parts * cap] shapes where cap = ceil(growth * R / n_parts).
    NULL keys never join, so such rows are dropped here (sel'=False).
    """
    R = sel.shape[0]
    cap = int(np.ceil(growth * R / n_parts))
    live = sel & key_valid
    dest = jnp.where(live, _hash_dest(key, n_parts), n_parts)  # P = drop lane

    order = jnp.argsort(dest, stable=True)
    sorted_dest = dest[order]
    seg_start = jnp.searchsorted(sorted_dest, jnp.arange(n_parts + 1, dtype=sorted_dest.dtype))
    pos = jnp.arange(R) - seg_start[jnp.clip(sorted_dest, 0, n_parts)]
    in_cap = (pos < cap) & (sorted_dest < n_parts)
    overflow = jnp.sum((pos >= cap) & (sorted_dest < n_parts))

    # scatter row `order[i]` into send slot [sorted_dest[i], pos[i]];
    # dead/overflow rows land in a trash lane (row n_parts) that is sliced
    # off before the exchange — slot (0,0) must never see collisions
    slot_d = jnp.where(in_cap, sorted_dest, n_parts)
    slot_p = jnp.where(in_cap, pos, 0)

    def scatter(a):
        buf = jnp.zeros((n_parts + 1, cap), dtype=a.dtype)
        return buf.at[slot_d, slot_p].set(a[order])[:n_parts]

    sent_sel = (jnp.zeros((n_parts + 1, cap), dtype=jnp.bool_)
                .at[slot_d, slot_p].set(True))[:n_parts]
    sent_key = scatter(key)
    sent = {name: scatter(a) for name, a in arrays.items()}

    recv_sel = jax.lax.all_to_all(sent_sel, axes, 0, 0).reshape(-1)
    recv_key = jax.lax.all_to_all(sent_key, axes, 0, 0).reshape(-1)
    recv = {name: jax.lax.all_to_all(a, axes, 0, 0).reshape(-1)
            for name, a in sent.items()}
    return recv, recv_sel, recv_key, overflow


def _local_join(build_key, build_sel, probe_key, probe_sel):
    """Sort build keys, searchsorted-probe. Returns (build_idx, hit).

    Validity is a secondary sort key (valid rows first among equal keys),
    not an in-band sentinel — a legitimate INT64_MAX key still joins."""
    n = build_key.shape[0]
    invalid = (~build_sel).astype(jnp.int32)
    skeys, sinv, order = jax.lax.sort(
        (build_key, invalid, jnp.arange(n)), num_keys=2)
    pos = jnp.clip(jnp.searchsorted(skeys, probe_key), 0, n - 1)
    hit = (skeys[pos] == probe_key) & (sinv[pos] == 0) & probe_sel
    return order[pos], hit


def make_join_agg_fragment(
    probe: ShardedTable, build: ShardedTable,
    probe_stages: List, build_stages: List,
    probe_key_ir, build_key_ir,
    probe_uids: Dict[str, str], build_uids: Dict[str, str],
    post_stages: List, group_exprs, aggs, domains: List[int],
    growth: float = 2.0,
):
    """Compile hash-repartition join + partial agg, all on device.

    Pipeline per shard: scan probe/build -> fused FoR decode -> pushed
    filters -> eval join keys -> all_to_all exchange both sides -> local
    unique-build-key join -> post-join filter/project -> partial segment
    agg -> collective merge.

    Returns a jitted fn(p_data, p_valid, p_sel, p_refs, b_data, b_valid,
    b_sel, b_refs) -> (state, overflow) — state is the merged [G] dict;
    overflow is the total row count dropped by exchange capacity (must
    be 0; caller re-runs with higher growth otherwise).
    """
    p_pipe = make_pipeline_fn(probe_stages) if probe_stages else (lambda c: c)
    b_pipe = make_pipeline_fn(build_stages) if build_stages else (lambda c: c)
    post_pipe = make_pipeline_fn(post_stages) if post_stages else (lambda c: c)
    init_state, update, _ = make_segment_kernel(group_exprs, aggs, domains)
    mesh = probe.mesh
    n_parts = probe.n_parts
    # capture metadata only — never the ShardedTables (see make_agg_fragment)
    probe_types, build_types = dict(probe.types), dict(build.types)

    def per_shard(p_data, p_valid, p_sel, p_refs,
                  b_data, b_valid, b_sel, b_refs):
        pch = p_pipe(_shard_chunk(probe_types, p_data, p_valid, p_sel,
                                  probe_uids, p_refs))
        bch = b_pipe(_shard_chunk(build_types, b_data, b_valid, b_sel,
                                  build_uids, b_refs))

        pk, pkv = eval_expr(probe_key_ir, pch)
        bk, bkv = eval_expr(build_key_ir, bch)
        pk = pk.astype(jnp.int64)
        bk = bk.astype(jnp.int64)

        def flat(ch: Chunk):
            arrs = {}
            for uid, col in ch.columns.items():
                arrs[uid + ".d"] = col.data
                arrs[uid + ".v"] = col.valid
            return arrs

        def unflat(arrs, ref: Chunk, sel):
            cols = {}
            for uid, col in ref.columns.items():
                cols[uid] = Column(data=arrs[uid + ".d"], valid=arrs[uid + ".v"],
                                   type_=col.type_)
            return Chunk(cols, sel)

        pr, pr_sel, pr_key, p_ovf = repartition_by_key(
            flat(pch), pch.sel, pk, pkv, n_parts, growth)
        br, br_sel, br_key, b_ovf = repartition_by_key(
            flat(bch), bch.sel, bk, bkv, n_parts, growth)

        bidx, hit = _local_join(br_key, br_sel, pr_key, pr_sel)
        joined_cols = dict(pr)
        for uid, col in bch.columns.items():
            joined_cols[uid + ".d"] = br[uid + ".d"][bidx]
            joined_cols[uid + ".v"] = br[uid + ".v"][bidx] & hit
        ref_cols = dict(pch.columns)
        ref_cols.update(bch.columns)
        ref = Chunk(ref_cols, pch.sel)  # types template only
        joined = unflat(joined_cols, ref, hit)

        joined = post_pipe(joined)
        state = merge_state(update(init_state(), joined))
        ovf = jax.lax.psum(p_ovf + b_ovf, _AXES)
        return state, ovf

    # lint: disable=jit-hygiene -- signature-keyed via
    # ShardCache.get_fragment like make_agg_fragment; closure carries
    # plan metadata only (types/mesh/keys), never the ShardedTables
    return jax.jit(shard_map_compat(
        per_shard, mesh=mesh,
        in_specs=(_SPEC, _SPEC, _SPEC, P(), _SPEC, _SPEC, _SPEC, P()),
        out_specs=(P(), P()), check_vma=False,
    ))


def dist_join_agg_fragment(probe: ShardedTable, build: ShardedTable, *args, **kwargs):
    """Compile + run (convenience; see make_join_agg_fragment)."""
    fn = make_join_agg_fragment(probe, build, *args, **kwargs)
    return fn(probe.data, probe.valid, probe.sel, probe.refs,
              build.data, build.valid, build.sel, build.refs)
