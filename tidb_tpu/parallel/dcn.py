"""Multi-host tier: coprocessor fan-out over host RPC (ref: distsql's
per-region gRPC fan-out to TiKV coprocessors; SURVEY.md §7.6 "DCN tier +
host RPC after single-slice works").

Architecture (the reference's shape, re-mapped):

    coordinator (this process)          workers (one process per host)
    ───────────────────────────        ─────────────────────────────────
    parse + plan the query              own a row-range PARTITION of
    rewrite agg -> partial form         each table (region analogue)
    fan out partial SQL over RPC   ->   run scan+filter+partial-agg on
    merge partial states by group       their local backend (their own
    key via a final agg (MPP final      chip/mesh — the ICI tier works
    stage on the coordinator)      <-   below this one unchanged)

Partial/final split: COUNT->SUM of counts, SUM->SUM, MIN/MAX->MIN/MAX,
AVG->SUM(sum)/SUM(count). Plain SELECT ... ORDER BY ... LIMIT pushes the
TopN into every worker (local top-n) and merges on the coordinator — the
reference's coprocessor TopN pushdown. Group keys travel as decoded host
values, so workers' independent string dictionaries never reconcile.

Transport: length-prefixed messages in a RESTRICTED codec (scalars,
strings, bytes, date/time/decimal, lists/dicts, allowlisted numpy
arrays — never arbitrary objects), so a hostile peer cannot execute
code by serialization alone. An optional shared secret adds an
HMAC-SHA256 challenge handshake per connection; binding a worker to a
non-loopback interface REQUIRES the secret.

Failure handling mirrors the reference's region-error model: each
partition may have a REPLICA on another worker (its copy lives in
`<table>__part<i>`); a worker RPC failure retries the partial there
before failing the query."""

from __future__ import annotations

import datetime
import decimal
import hashlib
import hmac
import itertools
import os
import random
import re
import socket
import struct
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from tidb_tpu.errors import (
    ExecutionError,
    QueryKilledError,
    QueryTimeoutError,
    TwoPhaseCommitIncomplete,
    UnsupportedError,
)
from tidb_tpu.parallel.membership import CLUSTER_GATE, TableGates
from tidb_tpu.parser import ast as A
from tidb_tpu.parser import parse
from tidb_tpu.parser.printer import expr_to_sql
from tidb_tpu.utils import tracing
from tidb_tpu.utils.failpoint import inject

__all__ = ["Worker", "Cluster", "partial_rewrite", "clusters_alive",
           "fleet_metrics_entries"]

# health-machine states, exported for tests and /cluster
UP, SUSPECT, DOWN = "up", "suspect", "down"
_STATE_CODE = {UP: 0, SUSPECT: 1, DOWN: 2}

# live coordinator registry for the status port's /cluster endpoint
_CLUSTERS: "weakref.WeakSet" = weakref.WeakSet()

_TOKEN_SEQ = itertools.count(1)


def clusters_alive() -> List["Cluster"]:
    return list(_CLUSTERS)


def fleet_metrics_entries() -> List[tuple]:
    """One cluster scrape: the coordinator's own registry (labeled
    ``coordinator``) plus every live Cluster's per-worker snapshots.
    The input shape metrics.render_cluster / cluster_rows consume —
    /metrics?scope=cluster and information_schema.cluster_metrics read
    the SAME entries, so the two surfaces can never disagree."""
    from tidb_tpu.utils import metrics as _metrics

    entries: List[tuple] = [("coordinator", _metrics.snapshot(), "")]
    for cl in clusters_alive():
        try:
            entries.extend(cl.metrics_snapshots())
        except Exception as e:  # noqa: BLE001 — a cluster mid-shutdown
            entries.append((f"cluster@{id(cl):x}", None,
                            f"{type(e).__name__}: {e}"))
    return entries


def _retype_wire_error(err: str, detail: str) -> ExecutionError:
    """One rule for every hop: a remote error travels the wire as
    `ClassName: message`, and kill/deadline must stay typed end to end
    whether the hop is coordinator->worker (Cluster._remote_error) or
    worker->peer (the shuffle_stage re-dispatch). A second copy of this
    prefix match would silently drift the next typed class."""
    if err.startswith("QueryTimeoutError:"):
        return QueryTimeoutError(detail)
    if err.startswith("QueryKilledError:"):
        return QueryKilledError(detail)
    return ExecutionError(detail)


class DcnCodecError(ExecutionError):
    """Malformed wire frame: the connection is desynced and must die."""


class DcnRpcTimeoutError(ConnectionError):
    """An RPC outlived its socket deadline. Distinguished from a broken
    link because the worker is PROBABLY STILL EXECUTING the request —
    idempotent retry must not re-send (it would double the worker's
    load and collide with the first attempt's cancel token); the caller
    falls to replica failover instead."""

_LEN = struct.Struct(">I")
_D = struct.Struct(">d")

# ---------------------------------------------------------------------------
# restricted wire codec (replaces pickle: data only, no code)
# ---------------------------------------------------------------------------

_DTYPES = {
    "bool", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "float32", "float64",
}


def _enc(obj, out: List[bytes]) -> None:
    if obj is None:
        out.append(b"N")
    elif obj is True:
        out.append(b"T")
    elif obj is False:
        out.append(b"F")
    elif isinstance(obj, (int, np.integer)):
        b = str(int(obj)).encode()
        out += [b"I", _LEN.pack(len(b)), b]
    elif isinstance(obj, (float, np.floating)):
        out += [b"D", _D.pack(float(obj))]
    elif isinstance(obj, str):
        b = obj.encode()
        out += [b"S", _LEN.pack(len(b)), b]
    elif isinstance(obj, (bytes, bytearray)):
        out += [b"B", _LEN.pack(len(obj)), bytes(obj)]
    elif isinstance(obj, np.bool_):
        out.append(b"T" if bool(obj) else b"F")
    elif isinstance(obj, np.ndarray):
        if obj.dtype.name not in _DTYPES:
            raise DcnCodecError(f"dcn codec: dtype {obj.dtype} not allowed")
        a = np.ascontiguousarray(obj)
        dt = a.dtype.name.encode()
        raw = a.tobytes()
        out += [b"A", _LEN.pack(len(dt)), dt,
                _LEN.pack(a.ndim), b"".join(_LEN.pack(d) for d in a.shape),
                _LEN.pack(len(raw)), raw]
    elif isinstance(obj, (list, tuple)):
        out += [b"L" if isinstance(obj, list) else b"U", _LEN.pack(len(obj))]
        for x in obj:
            _enc(x, out)
    elif isinstance(obj, dict):
        out += [b"M", _LEN.pack(len(obj))]
        for k, v in obj.items():
            if not isinstance(k, str):
                raise DcnCodecError("dcn codec: dict keys must be str")
            kb = k.encode()
            out += [_LEN.pack(len(kb)), kb]
            _enc(v, out)
    elif isinstance(obj, datetime.datetime):  # before date (subclass)
        b = obj.isoformat().encode()
        out += [b"t", _LEN.pack(len(b)), b]
    elif isinstance(obj, datetime.date):
        b = obj.isoformat().encode()
        out += [b"d", _LEN.pack(len(b)), b]
    elif isinstance(obj, decimal.Decimal):
        b = str(obj).encode()
        out += [b"c", _LEN.pack(len(b)), b]
    else:
        raise DcnCodecError(
            f"dcn codec: type {type(obj).__name__} not serializable")


def _need(buf: bytes, pos: int, n: int) -> int:
    if pos + n > len(buf):
        raise DcnCodecError("dcn codec: truncated message")
    return pos + n


def _dec(buf: bytes, pos: int):
    tag = buf[pos:pos + 1]
    pos += 1
    if tag == b"N":
        return None, pos
    if tag == b"T":
        return True, pos
    if tag == b"F":
        return False, pos
    if tag in (b"I", b"S", b"B", b"d", b"t", b"c"):
        end = _need(buf, pos, _LEN.size)
        (n,) = _LEN.unpack(buf[pos:end])
        pos = end
        end = _need(buf, pos, n)
        raw = buf[pos:end]
        pos = end
        if tag == b"I":
            return int(raw), pos
        if tag == b"B":
            return raw, pos
        s = raw.decode()
        if tag == b"S":
            return s, pos
        if tag == b"d":
            return datetime.date.fromisoformat(s), pos
        if tag == b"t":
            return datetime.datetime.fromisoformat(s), pos
        return decimal.Decimal(s), pos
    if tag == b"D":
        end = _need(buf, pos, _D.size)
        (v,) = _D.unpack(buf[pos:end])
        return v, end
    if tag in (b"L", b"U"):
        end = _need(buf, pos, _LEN.size)
        (n,) = _LEN.unpack(buf[pos:end])
        pos = end
        items = []
        for _ in range(n):
            v, pos = _dec(buf, pos)
            items.append(v)
        return (items if tag == b"L" else tuple(items)), pos
    if tag == b"M":
        end = _need(buf, pos, _LEN.size)
        (n,) = _LEN.unpack(buf[pos:end])
        pos = end
        d = {}
        for _ in range(n):
            end = _need(buf, pos, _LEN.size)
            (kn,) = _LEN.unpack(buf[pos:end])
            pos = end
            end = _need(buf, pos, kn)
            k = buf[pos:end].decode()
            pos = end
            d[k], pos = _dec(buf, pos)
        return d, pos
    if tag == b"A":
        end = _need(buf, pos, _LEN.size)
        (dn,) = _LEN.unpack(buf[pos:end])
        pos = end
        end = _need(buf, pos, dn)
        dtname = buf[pos:end].decode()
        pos = end
        if dtname not in _DTYPES:
            raise DcnCodecError(f"dcn codec: dtype {dtname} not allowed")
        end = _need(buf, pos, _LEN.size)
        (ndim,) = _LEN.unpack(buf[pos:end])
        pos = end
        shape = []
        for _ in range(ndim):
            end = _need(buf, pos, _LEN.size)
            shape.append(_LEN.unpack(buf[pos:end])[0])
            pos = end
        end = _need(buf, pos, _LEN.size)
        (rn,) = _LEN.unpack(buf[pos:end])
        pos = end
        end = _need(buf, pos, rn)
        arr = np.frombuffer(buf[pos:end], dtype=dtname).reshape(shape).copy()
        return arr, end
    raise DcnCodecError(f"dcn codec: bad tag {tag!r}")


def _dumps(obj) -> bytes:
    out: List[bytes] = []
    _enc(obj, out)
    return b"".join(out)


def _loads(buf: bytes):
    try:
        obj, pos = _dec(buf, 0)
    except DcnCodecError:
        raise
    except Exception as e:  # noqa: BLE001 — int()/decode()/reshape/...
        raise DcnCodecError(f"dcn codec: malformed message ({e})")
    if pos != len(buf):
        raise DcnCodecError("dcn codec: trailing bytes")
    return obj


# last frame sizes on THIS thread: _call annotates its rpc span with
# per-call (and per-page) byte counts without threading them through
# every return value — send/recv pairs never change threads mid-call
_IO_TLS = threading.local()


def _send(sock: socket.socket, obj) -> None:
    # runtime wire witness (ISSUE 14): while the sanitizer is enabled,
    # every request leaving a socket is diffed against the committed
    # static protocol model (unknown cmd/field or missing required
    # field = typed finding). Cost when off = one flag check — the
    # always-wrap contract tracked locks follow (README "Sanitizer
    # mode"); analysis.sanitizer is stdlib-only, so the import is free.
    from tidb_tpu.analysis import sanitizer as _san

    if _san.enabled():
        _san.note_wire_msg(obj)
    payload = _dumps(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)
    from tidb_tpu.utils.metrics import DCN_BYTES

    _IO_TLS.last_sent = _LEN.size + len(payload)
    DCN_BYTES.inc(_LEN.size + len(payload), direction="sent")


def _recv(sock: socket.socket):
    hdr = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(hdr)
    obj = _loads(_recv_exact(sock, n))
    from tidb_tpu.utils.metrics import DCN_BYTES

    _IO_TLS.last_recv = _LEN.size + n
    DCN_BYTES.inc(_LEN.size + n, direction="recv")
    return obj


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _is_loopback(host: str) -> bool:
    # NB "" binds INADDR_ANY (all interfaces) — decidedly not loopback
    return host in ("127.0.0.1", "::1", "localhost")


def dial(host: str, port: int, secret: Optional[str] = None,
         timeout: Optional[float] = None) -> socket.socket:
    """Client-side connect + mutual auth handshake against a Worker.
    Shared by the coordinator (``Cluster._connect``) and by workers
    dialing PEERS for the shuffle exchange (ISSUE 13) — one handshake
    implementation, so the endpoint-binding and downgrade-refusal
    rules hold on every link in the fleet."""
    s = socket.create_connection((host, port), timeout=timeout)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    flag = _recv_exact(s, 1)
    if flag == b"\x01":
        if not secret:
            s.close()
            raise ExecutionError(
                "dcn worker demands auth but no secret configured")
        nonce_w = _recv_exact(s, 16)
        nonce_c = os.urandom(16)
        claim_host = "127.0.0.1" if host == "localhost" else host
        endpoint = f"{claim_host}:{port}".encode()
        transcript = endpoint + b"|" + nonce_w + nonce_c
        s.sendall(nonce_c + bytes([len(endpoint)]) + endpoint
                  + hmac.new(secret.encode(),
                             b"dcn-coord|" + transcript,
                             hashlib.sha256).digest())
        # reverse challenge: the worker must prove the secret too — a
        # spoofed worker that merely echoed the \x01 flag cannot
        mac_w = _recv_exact(s, 32)
        want = hmac.new(secret.encode(), b"dcn-worker|" + transcript,
                        hashlib.sha256).digest()
        if not hmac.compare_digest(mac_w, want):
            s.close()
            raise ExecutionError(
                f"dcn worker {host}:{port} failed the reverse "
                "handshake (wrong or missing secret)")
    elif secret:
        # downgrade refusal: a client configured for auth must not talk
        # to an endpoint that waives it (spoofed worker)
        s.close()
        raise ExecutionError(
            f"dcn worker {host}:{port} does not require auth but this "
            "cluster is configured with a secret")
    # create_connection leaves its connect timeout armed on the socket;
    # callers apply per-RPC deadlines themselves
    s.settimeout(None)
    return s


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------


class Worker:
    """One host's coprocessor service: a Session over its partition."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 secret: Optional[str] = None):
        from tidb_tpu.session import Session

        if not _is_loopback(host) and not secret:
            raise ExecutionError(
                "dcn worker: binding a non-loopback interface requires a "
                "shared secret (--secret-file / DCN_SECRET)")
        self.secret = secret
        # normalized for the handshake's endpoint-claim check
        self._bind_host = "127.0.0.1" if host == "localhost" else host
        self.session = Session()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(4)
        self._running = False
        # paged-partial result cursors: handle -> (created_ts, rows).
        # Bounded so a crashed coordinator can't leak worker memory, but
        # eviction is age-aware: an actively-draining cursor must never
        # be expired just because other coordinators opened newer ones.
        self._cursors: Dict[int, Tuple[float, List[tuple]]] = {}
        # idempotency: token -> open cursor handle. A coordinator that
        # lost a partial_paged RESPONSE retries with the same token; the
        # retry evicts the orphaned first-attempt cursor so a lossy link
        # can't pin partials until the TTL
        self._token_cursors: Dict[str, int] = {}
        self._next_cursor = 1
        self._cursor_lock = threading.Lock()
        # coordinator-cancellable in-flight statements: token -> Event.
        # The cancel RPC arrives on its OWN connection (the statement's
        # connection is blocked producing the response), sets the event,
        # and the executing session's chunk-boundary poll aborts. A
        # cancel can RACE the statement it targets (the side channel is
        # faster than a queued partial): unknown tokens are remembered
        # so a late-registering statement starts already-cancelled.
        self._inflight: Dict[str, threading.Event] = {}
        self._cancelled_tokens: Dict[str, float] = {}
        self._inflight_lock = threading.Lock()
        # ONE statement at a time on the shared session: an abandoned
        # RPC's thread may still be executing when the coordinator
        # reconnects and sends the next statement — unsynchronized,
        # both would mutate session state concurrently. Cancels bypass
        # this lock (own connection, _inflight only), so a queued
        # statement can't deadlock behind one being cancelled.
        self._exec_lock = threading.Lock()
        # observable failure-domain counters (cmd "stats"): chaos tests
        # and the kill/deadline suites assert workers actually stopped
        self.stats: Dict[str, int] = {
            "executed": 0, "cancelled": 0, "deadline_exceeded": 0,
            "cancel_rpcs": 0, "pages": 0,
            "shuffle_bytes_in": 0, "shuffle_bytes_out": 0,
        }
        self._stats_lock = threading.Lock()
        # sharded placement (ISSUE 13): table -> (owned shard ids, bytes)
        # recorded by the coordinator's place_shards RPC; surfaced via
        # cmd "stats" -> information_schema.dcn_worker_stats
        self._placed: Dict[str, Tuple[List[int], int]] = {}
        self._placed_lock = threading.Lock()
        # shuffle exchange inbox: batches from peer workers staged here
        # until the coordinator's gather/apply phase drains them; bytes
        # charged to a MemTracker (budget re-read from the session's
        # tidb_mem_quota_query before every stage) so a hot shuffle hits
        # typed backpressure instead of silent growth
        from tidb_tpu.sharding.shuffle import ShuffleInbox
        from tidb_tpu.utils.memory import MemTracker

        self._shuffle_tracker = MemTracker("shuffle", budget=None,
                                           spill_enabled=False)
        self._inbox = ShuffleInbox(self._shuffle_tracker)
        # pooled peer connections for scatter sends: one authed socket
        # per peer endpoint, serialized by a per-peer lock (an
        # interleaved send/recv pair would desync the framing — same
        # rule as the coordinator's _sock_locks). Re-dialing per batch
        # paid TCP connect + the mutual-auth handshake on the hot path.
        self._peer_socks: Dict[Tuple[str, int], socket.socket] = {}
        self._peer_locks: Dict[Tuple[str, int], threading.Lock] = {}
        self._peer_pool_lock = threading.Lock()
        # reshard idempotency ledger: per-(run, shard) install keys this
        # worker already applied — a re-driven reshard_install (lost
        # response, recover_reshard) must NOT land the staging rows twice
        self._reshards_done: Dict[str, int] = {}
        # one pending prepared 2PC transaction at a time (the shared
        # session holds its provisional writes between the prepare and
        # commit RPCs); other statements are refused typed while it is
        # pending so they cannot be absorbed into the open transaction
        self._txn2pc: Optional[Tuple[str, float]] = None
        self._txn2pc_lock = threading.Lock()

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    def _drop_cursor_locked(self, h) -> None:
        self._cursors.pop(h, None)
        for t in [t for t, c in self._token_cursors.items() if c == h]:
            del self._token_cursors[t]

    def _drop_token_cursor_locked(self, token) -> None:
        if token is None:
            return
        h = self._token_cursors.pop(token, None)
        if h is not None:
            self._cursors.pop(h, None)

    CURSOR_CAP = 64          # hard cap on concurrently open cursors
    CURSOR_TTL_S = 600.0     # only cursors idle this long are evictable

    def serve_forever(self) -> None:
        self._running = True
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _handshake(self, conn: socket.socket) -> bool:
        """Mutual challenge/response before any message is decoded. The
        flag byte tells the client whether auth is demanded.

        The coordinator's MAC is bound to its role, the endpoint it
        believes it dialed, and both nonces — so a MAC harvested by a
        spoofed endpoint cannot be relayed to a worker at a different
        address (the worker refuses an endpoint claim that isn't
        itself), and neither side's MAC can be replayed in the other
        direction. The worker then proves knowledge of the secret with
        its own role-bound MAC over the same transcript. This is
        authentication only: there is NO transport encryption or
        post-handshake integrity — run DCN links over trusted networks
        (the reference's gRPC-over-TLS analogue is out of scope)."""
        if not self.secret:
            conn.sendall(b"\x00")
            return True
        nonce_w = os.urandom(16)
        conn.sendall(b"\x01" + nonce_w)
        try:
            nonce_c = _recv_exact(conn, 16)
            elen = _recv_exact(conn, 1)[0]
            endpoint = _recv_exact(conn, elen)
            mac_c = _recv_exact(conn, 32)
        except (ConnectionError, OSError):
            return False
        # the claimed endpoint must be this worker: port must match; host
        # must match the bind host unless bound to a wildcard. The
        # compare is literal (no DNS resolution): coordinators must dial
        # workers by the exact bind address, or bind workers to a
        # wildcard — a hostname dial against an IP-bound worker is
        # indistinguishable from a relayed claim and is refused
        try:
            ep_host, ep_port = endpoint.decode().rsplit(":", 1)
            port_ok = int(ep_port) == self.port
        except (UnicodeDecodeError, ValueError):
            conn.close()
            return False
        if ep_host == "localhost":  # match the coordinator's normalization
            ep_host = "127.0.0.1"
        host_ok = self._bind_host in ("", "0.0.0.0", "::") \
            or ep_host == self._bind_host
        if not port_ok or not host_ok:
            conn.close()
            return False
        transcript = endpoint + b"|" + nonce_w + nonce_c
        want = hmac.new(self.secret.encode(), b"dcn-coord|" + transcript,
                        hashlib.sha256).digest()
        if not hmac.compare_digest(mac_c, want):
            conn.close()
            return False
        conn.sendall(hmac.new(self.secret.encode(),
                              b"dcn-worker|" + transcript,
                              hashlib.sha256).digest())
        return True

    # a worker-side RPC trace is small: the statement's own spans plus
    # page/cancel observations — far below the coordinator's budget
    RPC_TRACE_MAX_SPANS = 128

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            if not self._handshake(conn):
                return
            while True:
                msg = _recv(conn)
                # trace-context arrival: record this RPC's server-side
                # spans (receive -> parse/plan -> execute -> page drain)
                # into a per-request trace anchored at RECEIPT, and
                # piggyback them on the response — errors included (a
                # failing attempt's spans matter most). The executing
                # session nests its statement spans automatically via
                # the thread-local tracing context.
                wtr = wroot = None
                if isinstance(msg, dict) and msg.get("trace_id"):
                    wtr = tracing.Trace(str(msg["trace_id"]),
                                        max_spans=self.RPC_TRACE_MAX_SPANS)
                    wroot = wtr.begin(f"worker.{msg.get('cmd', '?')}")
                    tracing.push(wtr, wroot)
                try:
                    try:
                        resp = {"ok": True, "result": self._handle(msg)}
                    except Exception as e:  # noqa: BLE001 — travels back
                        resp = {"ok": False,
                                "error": f"{type(e).__name__}: {e}"}
                finally:
                    if wtr is not None:
                        tracing.pop()
                        wtr.end(wroot)
                if wtr is not None:
                    resp["trace"] = wtr.export()
                try:
                    _send(conn, resp)
                except DcnCodecError as e:
                    # an unserializable RESULT fails before any bytes
                    # hit the wire: the connection is still synced, so
                    # the error can travel back like a handler error
                    _send(conn, {"ok": False,
                                 "error": f"{type(e).__name__}: {e}"})
                if msg.get("cmd") == "shutdown":
                    self._running = False
                    try:
                        # close() alone doesn't wake a thread blocked in
                        # accept() on Linux; shutdown() does
                        self._sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    self._sock.close()
                    return
        except (ConnectionError, OSError, DcnCodecError):
            pass  # desynced or dropped peer: close this connection
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _run_sql(self, msg: Dict):
        """Execute a shipped statement under the RPC's failure domain:
        the message's `deadline_s` (the coordinator statement's
        REMAINING budget) arms the session's external deadline, and its
        `token` registers a cancel event a coordinator-side KILL can set
        out of band. Both are polled by the session's chunk loop, so the
        worker stops burning CPU server-side instead of computing a
        result nobody will read.

        One worker session serves every connection, so the hooks are
        save/restored around each statement — concurrent statements from
        two coordinators would contend, which matches the single-session
        design of the rest of this Worker."""
        token = msg.get("token")
        ev: Optional[threading.Event] = None
        if token is not None:
            ev = threading.Event()
            with self._inflight_lock:
                self._inflight[token] = ev
                # the cancel may have beaten us here
                if self._cancelled_tokens.pop(token, None) is not None:
                    ev.set()
        sess = self.session
        # ownership-guarded hooks: an ABANDONED earlier attempt (the
        # coordinator timed out and moved on, this thread kept running)
        # finishes later — its cleanup must not clobber a newer
        # statement's cancel event or deadline. Each attempt only
        # resets state that is still ITS OWN (belt-and-braces under
        # the exec lock; still needed for two-coordinator workers).
        my_cancel = ev.is_set if ev is not None else None
        my_deadline = msg.get("_deadline_mono")  # anchored at RECEIPT
        self._bump("executed")
        try:
            with self._exec_lock:
                self._guard_2pc_locked()
                if my_cancel is not None:
                    sess._ext_cancel = my_cancel
                if my_deadline is not None:
                    sess._ext_deadline = my_deadline
                try:
                    return sess.execute(msg["sql"])
                finally:
                    if my_cancel is not None \
                            and sess._ext_cancel is my_cancel:
                        sess._ext_cancel = None
                    if my_deadline is not None \
                            and sess._ext_deadline == my_deadline:
                        sess._ext_deadline = None
        except QueryTimeoutError:
            self._bump("deadline_exceeded")
            raise
        except QueryKilledError:
            self._bump("cancelled")
            raise
        finally:
            if token is not None:
                with self._inflight_lock:
                    if self._inflight.get(token) is ev:
                        del self._inflight[token]

    # -- sharded placement + shuffle exchange + 2PC (ISSUE 13) ----------

    def _guard_2pc_locked(self) -> None:
        """Called under the exec lock before any statement runs: while
        a prepared 2PC transaction is pending, foreign statements are
        refused TYPED (they would otherwise silently join the open
        transaction on the shared session). A prepared participant
        NEVER resolves unilaterally — it voted yes, and the coordinator
        may hold a commit decision it cannot see, so only txn_commit /
        txn_abort (a coordinator's recover_txns()) releases it. This is
        the textbook 2PC blocking window, kept observable on purpose."""
        with self._txn2pc_lock:
            pend = self._txn2pc
            if pend is not None:
                raise ExecutionError(
                    f"dcn worker: 2pc transaction {pend[0]} is pending "
                    "(prepare acknowledged, decision not yet received); "
                    "statement refused until a coordinator resolves it")

    def _txn2pc_cmd(self, cmd: str, msg: Dict):
        """txn_prepare / txn_commit / txn_abort: one participant's half
        of the coordinator's two-phase commit (storage/txn2pc.py is the
        single-process committer the session's COMMIT already runs; this
        wraps it in the cross-process prepare/decide protocol)."""
        xid = str(msg["xid"])
        sess = self.session
        if cmd == "txn_prepare":
            with self._exec_lock:
                with self._txn2pc_lock:
                    pend = self._txn2pc
                if pend is not None and pend[0] == xid:
                    return "prepared"  # retried prepare: already staged
                if pend is not None:
                    raise ExecutionError(
                        f"dcn worker: 2pc transaction {pend[0]} still "
                        f"pending; cannot prepare {xid}")
                sess.execute("begin")
                try:
                    # batched group-commit prepare (ISSUE 17): a window
                    # of coalesced writes arrives as one `sqls` list and
                    # stages inside ONE participant transaction; the
                    # singleton `sql` form stays wire-compatible
                    for one in (msg.get("sqls") or [msg["sql"]]):
                        sess.execute(one)
                except Exception:
                    try:
                        sess.execute("rollback")
                    except Exception:  # noqa: BLE001 — abort cleanup
                        pass
                    raise
                with self._txn2pc_lock:
                    self._txn2pc = (xid, time.monotonic())
            return "prepared"
        with self._exec_lock:
            with self._txn2pc_lock:
                mine = self._txn2pc is not None and self._txn2pc[0] == xid
            if not mine:
                # already finished here, or never prepared (a commit
                # retry after a lost response): idempotent ack
                return "idempotent"
            sess.execute("commit" if cmd == "txn_commit" else "rollback")
            # cleared only AFTER the commit/rollback lands: a failed
            # commit must keep the guard up, or the next statement
            # would silently join the still-open prepared transaction
            # and a commit re-drive would get a hollow idempotent ack
            with self._txn2pc_lock:
                self._txn2pc = None
        return "done"

    def _shuffle_budget(self) -> None:
        """Re-arm the inbox tracker's budget from the session's memory
        quota before a stage lands — the knob is a live sysvar, and the
        budget must be whatever it says NOW."""
        q = int(self.session.sysvars.get("tidb_mem_quota_query"))
        self._shuffle_tracker.budget = q if q > 0 else None

    def _shuffle_stage(self, msg: Dict) -> int:
        """A PEER worker's batch arriving: charge, stage, account. The
        propagated statement budget (deadline_s -> _deadline_mono,
        anchored at receipt like every RPC) is honored here: staging
        bytes for a statement that already expired would pin inbox
        memory nobody will ever gather."""
        dl = msg.get("_deadline_mono")
        if dl is not None and time.monotonic() > dl:
            raise QueryTimeoutError(
                "Query execution was interrupted, maximum statement "
                "execution time exceeded (shuffle stage received after "
                "the deadline)")
        inject("shuffle.recv")
        self._shuffle_budget()
        n = self._inbox.stage(str(msg["shuffle_id"]), str(msg["side"]),
                              msg["batch"])
        self._bump("shuffle_bytes_in", n)
        from tidb_tpu.utils.metrics import SHUFFLE_BYTES_TOTAL

        SHUFFLE_BYTES_TOTAL.inc(n, dir="in")
        return n

    def _shuffle_scatter(self, msg: Dict) -> Dict:
        """Partition this worker's live rows of `table` by the shipped
        shard map (mode=hash) — or replicate them to every peer
        (mode=broadcast) — and ship per-destination batches
        FoR-encoded. dest == self stages straight into the local inbox
        (no wire). All socket work happens with NO worker lock held."""
        from tidb_tpu.sharding import placement as pl
        from tidb_tpu.sharding import shuffle as shfl
        from tidb_tpu.utils.metrics import SHUFFLE_BYTES_TOTAL

        # budget checked BEFORE any extract/partition/encode work (and
        # before the self-destination local stage, which has no peer
        # hop to catch it): scattering for an expired statement pins
        # inbox bytes nobody will gather
        dl0 = msg.get("_deadline_mono")
        if dl0 is not None and time.monotonic() > dl0:
            raise QueryTimeoutError(
                "Query execution was interrupted, maximum statement "
                "execution time exceeded (shuffle scatter received "
                "after the deadline)")
        table = self.session.catalog.table(
            msg.get("db") or self.session.db, msg["table"])
        arrays, valids, strings, n = shfl.extract_live_columns(
            table, msg.get("columns") or None)
        n_workers = int(msg["n_workers"])
        mode = msg.get("mode", "hash")
        parts = None
        if mode != "broadcast":
            key = msg["key"]
            if key in strings:
                raise UnsupportedError(
                    "dcn shuffle: string shuffle keys are unsupported "
                    "(dictionary codes are process-local)")
            smap = pl.ShardMap.from_wire(msg["map"])
            shards = pl.shard_of_array(smap, arrays[key], valids[key])
            dest = shards % np.int64(max(n_workers, 1))
            parts = shfl.partition_rows(arrays, valids, strings, dest,
                                        n_workers)
        types = {c.name: c.type_ for c in table.schema.columns}
        sid, side = str(msg["shuffle_id"]), str(msg["side"])
        self_i = int(msg["self_index"])
        peers = msg["peers"]
        timeout = float(msg.get("timeout_s") or 30.0)
        sent_bytes = 0
        local_bytes = 0
        # broadcast replicates to the GATHER set only (`dests`) and
        # encodes its one identical batch ONCE; a hash shuffle routes
        # over every worker — each owns a key range — with a distinct
        # batch per destination
        bcast_batch = None
        if mode == "broadcast":
            dests = [int(d) for d in (msg.get("dests")
                                      or range(n_workers))]
            if n:
                bcast_batch = shfl.encode_batch(types, arrays, valids,
                                                strings)
        else:
            dests = range(n_workers)
        for w in dests:
            if mode == "broadcast":
                batch = bcast_batch
            else:
                batch = (shfl.encode_batch(types, *parts[w])
                         if parts[w] is not None else None)
            if batch is None:
                continue
            if w == self_i:
                self._shuffle_budget()
                # the self-destination copy never crosses a socket but
                # IS part of the exchange volume: ack it separately so
                # the coordinator's plan feedback sizes the side by ALL
                # copies, not just the remote ones (wire metrics stay
                # honest — sent_bytes counts shipped bytes only)
                local_bytes += self._inbox.stage(sid, side, batch)
                continue
            inject("shuffle.send")
            host, port = peers[w]
            # mandatory-envelope propagation (ISSUE 14): this hop is a
            # fan-out re-send, and _peer_call injects nothing — the
            # statement's remaining budget and trace context must ride
            # the message explicitly or they die at this worker (the
            # protocol-conformance pass enforces it; a peer staging for
            # an expired statement would burn memory nobody drains)
            peer_msg = {"cmd": "shuffle_stage", "shuffle_id": sid,
                        "side": side, "batch": batch}
            dl = msg.get("_deadline_mono")
            if dl is not None:
                rem = dl - time.monotonic()
                if rem <= 0:
                    raise QueryTimeoutError(
                        "Query execution was interrupted, maximum "
                        "statement execution time exceeded (before "
                        f"shuffle stage to worker {w})")
                peer_msg["deadline_s"] = rem
                timeout = min(timeout, rem)
            tr = tracing.current()
            sp = (tr.begin(f"peer.shuffle_stage[w{w}]",
                           tracing.current_span_id())
                  if tr is not None else None)
            if tr is not None:
                peer_msg["trace_id"] = tr.trace_id
            resp = None
            try:
                try:
                    resp = self._peer_call(str(host), int(port),
                                           peer_msg, timeout)
                except (socket.timeout, TimeoutError) as e:
                    # the clamped socket timeout IS the deadline when
                    # the budget ran out mid-hop: surface the same 3024
                    # the pre-send rem<=0 check raises (same mapping as
                    # Cluster._call's timeout path)
                    if dl is not None and time.monotonic() >= dl:
                        raise QueryTimeoutError(
                            "Query execution was interrupted, maximum "
                            "statement execution time exceeded "
                            f"(shuffle stage to worker {w})") from e
                    raise
            finally:
                if tr is not None:
                    if isinstance(resp, dict) and resp.get("trace"):
                        tr.graft(resp["trace"], sp, proc=f"{host}:{port}")
                    tr.end(sp)
            if not resp.get("ok"):
                # the peer's typed refusal (inbox OOM backpressure, or
                # the new deadline check) travels through this worker
                # back to the coordinator — RE-TYPED, so a peer-side
                # deadline expiry reaches the client as the same 3024
                # the sender-side rem<=0 check raises
                err = str(resp.get("error"))
                raise _retype_wire_error(
                    err, f"shuffle stage to worker {w} failed: {err}")
            nb = int(resp["result"])
            sent_bytes += nb
            self._bump("shuffle_bytes_out", nb)
            SHUFFLE_BYTES_TOTAL.inc(nb, dir="out")
        return {"rows": int(n), "bytes": sent_bytes,
                "local_bytes": local_bytes}

    def _peer_call(self, host: str, port: int, msg: Dict,
                   timeout: float) -> Dict:
        """One RPC to a peer worker over the pooled connection for that
        endpoint (dialed + authed on first use, dropped on any wire
        fault so the next call re-dials). The per-peer lock serializes
        concurrent scatters — two sides of one shuffle ship in
        parallel threads and must not interleave frames."""
        key = (host, port)
        with self._peer_pool_lock:
            lk = self._peer_locks.setdefault(key, threading.Lock())
        with lk:
            s = self._peer_socks.get(key)
            if s is None:
                s = dial(host, port, secret=self.secret, timeout=timeout)
                self._peer_socks[key] = s
            try:
                s.settimeout(timeout)
                _send(s, msg)
                resp = _recv(s)
                s.settimeout(None)
            except (ConnectionError, OSError, DcnCodecError):
                try:
                    s.close()
                except OSError:
                    pass
                self._peer_socks.pop(key, None)
                raise
        return resp

    def _clone_temp_table(self, base, name: str, columns: List[str]):
        """Fresh table holding the shipped column subset of `base`'s
        schema — no constraints, defaults, or generated columns (the
        exchange ships materialized values; re-running column logic
        would double-apply it)."""
        import copy

        from tidb_tpu.storage.table import TableSchema

        cat = self.session.catalog
        db = self.session.db
        cat.drop_table(db, name, if_exists=True)
        cols = []
        for cn in columns:
            ci = copy.deepcopy(base.schema.col(cn))
            ci.not_null = False
            ci.auto_increment = False
            ci.default = None
            ci.state = "public"
            cols.append(ci)
        cat.create_table(db, TableSchema(name, cols))
        return cat.table(db, name)

    def _shuffle_gather(self, msg: Dict) -> Dict:
        """Assemble this worker's staged batches into temp tables (one
        per exchanged side), run the partial SQL over the co-partitioned
        slice, and release the shuffle state. The result pages through
        the SAME cursor machinery as partial_paged, so drains, cancel
        tokens, and leak accounting are identical."""
        from tidb_tpu.sharding import shuffle as shfl

        sid = str(msg["shuffle_id"])
        cat = self.session.catalog
        created: List[str] = []
        try:
            for sd in msg["sides"]:
                base = cat.table(msg.get("db") or self.session.db,
                                 sd["table"])
                t = self._clone_temp_table(base, sd["temp"], sd["columns"])
                created.append(sd["temp"])
                types = {c.name: c.type_ for c in t.schema.columns}
                shfl.assemble_into_table(self.session, sd["temp"], types,
                                         self._inbox.drain(sid, sd["side"]))
            return self._partial_paged(msg)
        finally:
            # the cursor holds materialized host rows: the staged
            # batches and temp tables are dead weight from here (and on
            # error they must not outlive the statement)
            self._inbox.close(sid)
            for name in created:
                try:
                    cat.drop_table(self.session.db, name, if_exists=True)
                except Exception:  # noqa: BLE001 — cleanup best effort
                    pass

    def _table_like(self, db: str, name: str, like: str):
        """Resolve `name`, cloning `like`'s FULL schema (defaults,
        constraints, generated columns intact) when absent. Replica
        `__part` mirrors and reshard backfill staging tables both go
        through here: a staging table must apply double-written DML
        exactly like the real table, or the cutover fingerprints can
        never match."""
        import copy

        cat = self.session.catalog
        try:
            return cat.table(db, name)
        except Exception:  # noqa: BLE001 — absent: clone it
            base = cat.table(db, like)
            schema = copy.deepcopy(base.schema)
            schema.name = name
            cat.create_table(db, schema)
            return cat.table(db, name)

    def _reshard_backfill(self, msg: Dict) -> Dict:
        """Online-reshard backfill SOURCE (ISSUE 19): extract this
        worker's live rows of `table` that the NEW map assigns to
        `shard` and stage them into the destination owner's staging
        table — a peer-to-peer hop like _shuffle_scatter (no
        coordinator copy), carrying the same mandatory deadline/trace
        envelope. The extract+encode runs under the exec lock (a point
        snapshot no concurrent statement can tear); the peer send runs
        with NO lock held."""
        from tidb_tpu.sharding import placement as pl
        from tidb_tpu.sharding import shuffle as shfl

        inject("reshard.backfill")
        db = msg.get("db") or self.session.db
        smap = pl.ShardMap.from_wire(msg["map"])
        shard = int(msg["shard"])
        with self._exec_lock:
            self._guard_2pc_locked()
            table = self.session.catalog.table(db, msg["table"])
            arrays, valids, strings, _n = shfl.extract_live_columns(table)
            if smap.column in strings:
                raise UnsupportedError(
                    "reshard: string shard keys are unsupported "
                    "(dictionary codes are process-local)")
            shards = pl.shard_of_array(smap, arrays[smap.column],
                                       valids[smap.column])
            idx = np.nonzero(shards == np.int64(shard))[0]
            if not len(idx):
                return {"rows": 0, "bytes": 0}
            types = {c.name: c.type_ for c in table.schema.columns}
            batch = shfl.encode_batch(
                types, {k: v[idx] for k, v in arrays.items()},
                {k: v[idx] for k, v in valids.items()},
                {k: [col[i] for i in idx] for k, col in strings.items()})
        stage_msg = {"cmd": "reshard_stage", "table": msg["staging"],
                     "like": msg["table"], "db": msg.get("db"),
                     "batch": batch}
        if int(msg["dest_index"]) == int(msg["self_index"]):
            rows = self._reshard_stage(stage_msg)
            return {"rows": int(rows), "bytes": 0}
        host, port = msg["dest"]
        timeout = float(msg.get("timeout_s") or 30.0)
        dl = msg.get("_deadline_mono")
        if dl is not None:
            rem = dl - time.monotonic()
            if rem <= 0:
                raise QueryTimeoutError(
                    "Query execution was interrupted, maximum statement "
                    "execution time exceeded (before reshard stage to "
                    f"{host}:{port})")
            stage_msg["deadline_s"] = rem
            timeout = min(timeout, rem)
        tr = tracing.current()
        if tr is not None:
            stage_msg["trace_id"] = tr.trace_id
        resp = self._peer_call(str(host), int(port), stage_msg, timeout)
        if not resp.get("ok"):
            err = str(resp.get("error"))
            raise _retype_wire_error(
                err, f"reshard stage to {host}:{port} failed: {err}")
        return {"rows": int(resp["result"]), "bytes": 0}

    def _reshard_stage(self, msg: Dict) -> int:
        """Backfill DESTINATION: land one shipped batch into the
        staging table (cloned from the real table's full schema on
        first touch)."""
        from tidb_tpu.sharding import shuffle as shfl

        db = msg.get("db") or self.session.db
        with self._exec_lock:
            self._guard_2pc_locked()
            t = self._table_like(db, msg["table"], msg["like"])
            types = {c.name: c.type_ for c in t.schema.columns}
            b = msg["batch"]
            if not b["n"]:
                return 0
            arrays, valids, strs = shfl.decode_batch(types, b)
            return t.insert_columns(arrays, valids, strings=strs)

    def _reshard_fingerprint(self, msg: Dict) -> Dict:
        """Row-count + order-independent hash of a table's live rows —
        restricted to the rows the shipped map assigns to `shard` when
        a map is given (source side), the whole table otherwise
        (staging side). An absent table is an EMPTY row set, not an
        error: a shard nobody backfilled anything for has no staging
        table and must still validate."""
        from tidb_tpu.parallel.membership import rows_fingerprint
        from tidb_tpu.sharding import placement as pl
        from tidb_tpu.sharding import shuffle as shfl

        db = msg.get("db") or self.session.db
        with self._exec_lock:
            try:
                table = self.session.catalog.table(db, msg["table"])
            except Exception:  # noqa: BLE001 — absent: empty set
                return {"n": 0, "fp": 0}
            arrays, valids, strings, _n = shfl.extract_live_columns(table)
            sel = None
            if msg.get("map") is not None:
                smap = pl.ShardMap.from_wire(msg["map"])
                shards = pl.shard_of_array(smap, arrays[smap.column],
                                           valids[smap.column])
                sel = shards == np.int64(int(msg["shard"]))
            n, fp = rows_fingerprint(arrays, valids, strings,
                                     table.schema.public_names(), sel)
        return {"n": n, "fp": fp}

    def _reshard_install(self, msg: Dict) -> int:
        """Cutover at the new owner: move the validated staging rows
        into the real table and drop the staging. IDEMPOTENT against
        coordinator re-drives (recover_reshard) via the per-(run,shard)
        ledger — a lost response must not install twice."""
        from tidb_tpu.sharding import shuffle as shfl

        key = f"{msg['sid']}#i{int(msg['shard'])}"
        db = msg.get("db") or self.session.db
        cat = self.session.catalog
        with self._exec_lock:
            self._guard_2pc_locked()
            with self._placed_lock:
                done = self._reshards_done.get(key)
            if done is not None:
                return done
            total = 0
            try:
                st = cat.table(db, msg["staging"])
            except Exception:  # noqa: BLE001 — nothing backfilled
                st = None
            if st is not None:
                t = cat.table(db, msg["table"])
                arrays, valids, strings, n = shfl.extract_live_columns(st)
                if n:
                    total = t.insert_columns(arrays, valids,
                                             strings=strings)
                cat.drop_table(db, msg["staging"], if_exists=True)
            with self._placed_lock:
                self._reshards_done[key] = total
                while len(self._reshards_done) > 64:
                    self._reshards_done.pop(
                        next(iter(self._reshards_done)))
            return total

    def _reshard_purge(self, msg: Dict) -> int:
        """Cutover at an old owner: delete the live rows the NEW map
        assigns to `shard` (their installed copy at the new owner is
        the surviving one). Naturally idempotent — a re-drive finds no
        matching live rows."""
        from tidb_tpu.sharding import placement as pl

        db = msg.get("db") or self.session.db
        smap = pl.ShardMap.from_wire(msg["map"])
        shard = int(msg["shard"])
        with self._exec_lock:
            self._guard_2pc_locked()
            t = self.session.catalog.table(db, msg["table"])
            n = t.n
            if not n:
                return 0
            idx = np.nonzero(t.live_mask(0, n))[0]
            if not len(idx):
                return 0
            shards = pl.shard_of_array(
                smap, t.data[smap.column][:n][idx],
                t.valid[smap.column][:n][idx])
            victims = idx[shards == np.int64(shard)]
            if not len(victims):
                return 0
            return t.delete_rows(victims)

    def _table_dump(self, msg: Dict) -> Dict:
        """Full live-row snapshot of a table in load_columns shape —
        the coordinator's source for replica-mirror rebuilds and for
        seeding a joining worker's broadcast tables."""
        from tidb_tpu.sharding import shuffle as shfl

        db = msg.get("db") or self.session.db
        with self._exec_lock:
            try:
                table = self.session.catalog.table(db, msg["table"])
            except Exception:  # noqa: BLE001 — absent: empty dump
                return {"arrays": {}, "valids": {}, "strings": {},
                        "n": 0}
            arrays, valids, strings, n = shfl.extract_live_columns(table)
        return {"arrays": arrays, "valids": valids, "strings": strings,
                "n": n}

    def _partial_paged(self, msg: Dict) -> Dict:
        """Run the partial once, return the first page + a cursor the
        coordinator drains with "fetch" — bounds the coordinator's
        in-flight volume to one page per worker. Shared by the plain
        partial path and the shuffle gather (same cursor, token, and
        leak discipline)."""
        inject("dcn.worker.partial")
        rs = self._run_sql(msg)
        rows = rs.rows
        tracing.annotate(f"partial:rows={len(rows)}")
        page = int(msg.get("page_rows", 8192))
        token = msg.get("token")
        if len(rows) <= page:
            with self._cursor_lock:
                self._drop_token_cursor_locked(token)
            return {"rows": rows, "cursor": None, "total": len(rows)}
        now = time.time()
        if token is not None:
            with self._inflight_lock:
                poisoned = self._cancelled_tokens.pop(
                    token, None) is not None
            if poisoned:
                # the coordinator abandoned this statement (cancel
                # arrived after execution finished): don't pin a
                # cursor nobody will ever drain
                return {"rows": rows[:page], "cursor": None,
                        "total": len(rows)}
        with self._cursor_lock:
            # a RETRY of this token (first response lost on the
            # wire) must not leave the first attempt's cursor
            # pinned: evict it before opening the replacement
            self._drop_token_cursor_locked(token)
            # reap abandoned cursors (a crashed coordinator must not
            # leak result memory); live drains are refreshed on every
            # fetch so they never look idle
            stale = [h for h, (ts, _r) in self._cursors.items()
                     if now - ts > self.CURSOR_TTL_S]
            for h in stale:
                self._drop_cursor_locked(h)
            if len(self._cursors) >= self.CURSOR_CAP:
                raise ExecutionError(
                    f"dcn worker: {self.CURSOR_CAP} partial cursors "
                    "already open")
            h = self._next_cursor
            self._next_cursor += 1
            self._cursors[h] = (now, rows)
            if token is not None:
                self._token_cursors[token] = h
        return {"rows": rows[:page], "cursor": h, "total": len(rows)}

    def _handle(self, msg: Dict):
        if msg.get("deadline_s") is not None:
            # statement budget anchored NOW, before any injected fault
            # or queueing delay can defer it
            msg["_deadline_mono"] = time.monotonic() + float(
                msg["deadline_s"])
        inject("dcn.worker.handle")
        cmd = msg["cmd"]
        # lint: disable=protocol-conformance -- health-probe arm with no
        # in-tree sender by design: tests and operators dial it raw to
        # check liveness without touching any statement machinery
        if cmd == "ping":
            return "pong"
        if cmd == "cancel":
            # out-of-band: stop the statement registered under `token`
            self._bump("cancel_rpcs")
            token = msg.get("token")
            with self._inflight_lock:
                ev = self._inflight.get(token)
                if ev is None and token is not None:
                    # not started yet: poison the token (bounded memory)
                    self._cancelled_tokens[token] = time.time()
                    while len(self._cancelled_tokens) > 256:
                        self._cancelled_tokens.pop(
                            next(iter(self._cancelled_tokens)))
            # cancel observation onto the shipped-back trace: which
            # token, and whether it caught a statement in flight or
            # poisoned ahead of one
            tracing.annotate(f"cancel:token={token} "
                             f"inflight={ev is not None}")
            if ev is None:
                return False  # not in flight (finished, or poisoned)
            ev.set()
            return True
        if cmd == "stats":
            with self._stats_lock:
                out = dict(self.stats)
            with self._cursor_lock:
                out["open_cursors"] = len(self._cursors)
            with self._placed_lock:
                out["shards_owned"] = sum(
                    len(s) for s, _b in self._placed.values())
                out["shard_bytes"] = sum(
                    b for _s, b in self._placed.values())
            out["open_shuffles"] = self._inbox.open_count()
            return out
        if cmd == "metrics_snapshot":
            # fleet metrics plane (ISSUE 16): this process's entire
            # registry in wire form — the coordinator merges per-worker
            # snapshots for /metrics?scope=cluster and
            # information_schema.cluster_metrics
            from tidb_tpu.utils import metrics as _metrics

            return _metrics.snapshot()
        if cmd == "place_shards":
            with self._placed_lock:
                self._placed[str(msg["table"])] = (
                    [int(s) for s in (msg.get("shards") or [])],
                    int(msg.get("bytes") or 0))
            return "placed"
        if cmd == "shuffle_stage":
            return self._shuffle_stage(msg)
        if cmd == "shuffle_scatter":
            return self._shuffle_scatter(msg)
        if cmd == "shuffle_gather":
            return self._shuffle_gather(msg)
        if cmd == "shuffle_close":
            self._inbox.close(str(msg["shuffle_id"]))
            return "closed"
        if cmd == "reshard_backfill":
            return self._reshard_backfill(msg)
        if cmd == "reshard_stage":
            return self._reshard_stage(msg)
        if cmd == "reshard_fingerprint":
            return self._reshard_fingerprint(msg)
        if cmd == "reshard_install":
            return self._reshard_install(msg)
        if cmd == "reshard_purge":
            return self._reshard_purge(msg)
        if cmd == "table_dump":
            return self._table_dump(msg)
        if cmd in ("txn_prepare", "txn_commit", "txn_abort"):
            return self._txn2pc_cmd(cmd, msg)
        if cmd == "exec":
            rs = self._run_sql(msg)
            return rs.rows if rs is not None else None
        if cmd == "ddl_stage":
            # one step of an online schema change (ref: schema-version
            # leases + state machine, SURVEY.md:180-185): the
            # coordinator barriers every worker through the same stage
            # before advancing, so at most two adjacent schema states
            # coexist; DML between stages stays correct (write_only
            # columns default-fill, write_only indexes enforce)
            self.session.apply_ddl_stage(msg["sql"], msg["stage"])
            return {"schema_version": self.session.catalog.schema_version}
        if cmd == "load_columns":
            db = msg.get("db") or self.session.db
            name = msg["table"]
            like = msg.get("like")
            if like is not None:
                # replica partitions clone the base table's schema into
                # their own namespaced table on first load
                table = self._table_like(db, name, like)
            else:
                table = self.session.catalog.table(db, name)
            if msg.get("replace"):
                # mirror rebuild / joiner seed: this load IS the table
                table.truncate()
            return table.insert_columns(
                msg.get("arrays") or {}, msg.get("valids"),
                strings=msg.get("strings"))
        if cmd == "partial_paged":
            return self._partial_paged(msg)
        if cmd == "fetch":
            inject("dcn.worker.page")
            self._bump("pages")
            h = msg["cursor"]
            off = int(msg["offset"])
            page = int(msg.get("page_rows", 8192))
            with self._cursor_lock:
                ent = self._cursors.get(h)
                if ent is None:
                    raise ExecutionError(f"dcn cursor {h} expired")
                rows = ent[1]
                out = rows[off: off + page]
                if off + page >= len(rows):
                    self._drop_cursor_locked(h)
                else:
                    self._cursors[h] = (time.time(), rows)  # refresh idle clock
            tracing.annotate(f"page:offset={off} rows={len(out)}")
            return out
        if cmd == "close_cursor":
            with self._cursor_lock:
                self._drop_cursor_locked(msg["cursor"])
            return "closed"
        if cmd == "shutdown":
            return "bye"
        raise ExecutionError(f"unknown dcn command {cmd!r}")


def worker_main(argv=None) -> None:  # pragma: no cover - subprocess entry
    """python -m tidb_tpu.parallel.dcn [--port N]; prints the bound port."""
    import argparse
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--device", default=None,
                    help="force a jax platform (e.g. cpu) before serving")
    ap.add_argument("--secret-file", default=None,
                    help="path to the cluster shared secret (else DCN_SECRET)")
    args = ap.parse_args(argv)
    if args.device:
        import jax

        jax.config.update("jax_platforms", args.device)
    secret = None
    if args.secret_file:
        secret = open(args.secret_file).read().strip()
    elif os.environ.get("DCN_SECRET"):
        secret = os.environ["DCN_SECRET"]
    w = Worker(args.host, args.port, secret=secret)
    print(f"DCN_WORKER_PORT={w.port}", flush=True)
    sys.stdout.flush()
    w.serve_forever()


if __name__ == "__main__":  # pragma: no cover
    worker_main()


# ---------------------------------------------------------------------------
# partial/final rewrite
# ---------------------------------------------------------------------------

_DIST_AGGS = {"count", "sum", "min", "max", "avg"}
# engine aggregates with no partial/final SQL split on this tier
_NONDIST_AGGS = {"bit_and", "bit_or", "bit_xor", "group_concat", "any_value",
                 "variance", "var_pop", "var_samp", "stddev", "std",
                 "stddev_pop", "stddev_samp"}


def _from_tables(src) -> List[A.TableName]:
    """Base tables of a FROM tree; only inner/cross joins qualify (an
    outer join against a broadcast side would need NULL-extension
    coordination the partial/final split cannot express)."""
    if isinstance(src, A.TableName):
        return [src]
    if isinstance(src, A.Join):
        if src.kind not in ("inner", "cross"):
            raise UnsupportedError(f"dcn tier: {src.kind} join")
        if src.using:
            raise UnsupportedError("dcn tier: JOIN USING")
        return _from_tables(src.left) + _from_tables(src.right)
    raise UnsupportedError("dcn FROM must be base tables")


def _from_sql(src, rename: Dict[str, str]) -> str:
    """Render a FROM tree back to SQL, substituting renamed tables (the
    replica retry reads `<fact>__part<i>`); a renamed table keeps its
    original name as an alias so qualified column refs stay valid."""
    if isinstance(src, A.TableName):
        t = rename.get(src.name, src.name)
        out = f"`{t}`"
        if src.alias:
            out += f" as `{src.alias}`"
        elif t != src.name:
            out += f" as `{src.name}`"
        return out
    left = _from_sql(src.left, rename)
    right = _from_sql(src.right, rename)
    if src.kind == "cross" and src.on is None:
        return f"{left} cross join {right}"
    on = f" on {expr_to_sql(src.on)}" if src.on is not None else ""
    return f"{left} join {right}{on}"


def partial_rewrite(sql: str, table_as: Optional[str] = None,
                    partitioned=frozenset(), broadcast=frozenset(),
                    renames: Optional[Dict[str, str]] = None,
                    co_partitioned=frozenset(),
                    parsed=None) -> Tuple[str, str, List[str]]:
    """One SELECT -> (partial_sql, final_sql, out_names). partial_sql
    runs on every worker; its result rows are unioned into the staging
    table __dcn_partial__ on the coordinator, where final_sql computes
    the merge. Aggregates use the partial/final split; a plain SELECT
    with ORDER BY+LIMIT becomes a local TopN per worker merged by the
    same sort on the coordinator (coprocessor TopN pushdown).

    FROM may be one partitioned table, or `fact JOIN dim...` where
    exactly one table is partitioned across workers and every other
    side was broadcast_table()'d to all of them (the star-schema
    coprocessor-join shape, SURVEY.md:131): each worker joins its fact
    partition against its full local dim copies, so the partial/final
    aggregate split stays exact. `table_as` substitutes the partitioned
    table's name — the replica-partition retry reads `<fact>__part<i>`.

    Shuffle joins (ISSUE 13) relax the one-partitioned-table rule:
    tables in `co_partitioned` are co-partitioned ON THE JOIN KEY at
    execution time (the cross-process exchange routes both sides with
    the same hash), so the partial/final aggregate split stays exact
    with any number of them; `renames` substitutes the per-worker
    staging-table names the exchanged sides materialize into.
    `parsed` (the pre-parsed statement list) skips the re-parse when
    the caller already holds one — the coordinator's planner does."""
    stmts = parsed if parsed is not None else parse(sql)
    if len(stmts) != 1 or not isinstance(stmts[0], A.SelectStmt):
        raise UnsupportedError("dcn tier handles a single SELECT")
    st = stmts[0]
    if st.having is not None or st.distinct or st.ctes:
        raise UnsupportedError(
            "dcn tier pushes coprocessor-shaped aggregates "
            "(no HAVING/DISTINCT/CTE)")
    tables = _from_tables(st.from_)
    if len(tables) == 1:
        fact = tables[0].name
        if fact in broadcast and fact not in partitioned:
            # every worker holds the FULL copy: fanning a partial out
            # and summing would multiply aggregates by the worker count
            raise UnsupportedError(
                f"table {fact!r} is broadcast (replicated), not "
                "partitioned; query it on one worker directly")
    elif co_partitioned:
        # shuffle plan: every side is either co-partitioned on the join
        # key (exchange output, or hash-placed on it already) or a
        # broadcast dim — the coordinator's exchange planner already
        # validated the join keys
        missing = [t.name for t in tables
                   if t.name not in co_partitioned
                   and t.name not in broadcast]
        if missing:
            raise UnsupportedError(
                f"dcn shuffle join sides {missing} are neither "
                "co-partitioned nor broadcast")
        fact = next(t.name for t in tables if t.name in co_partitioned)
    else:
        parts = [t.name for t in tables if t.name in partitioned]
        if len(parts) != 1:
            raise UnsupportedError(
                "dcn join needs exactly one partitioned table "
                f"(got {parts or 'none'} among {[t.name for t in tables]})")
        fact = parts[0]
        missing = [t.name for t in tables
                   if t.name != fact and t.name not in broadcast]
        if missing:
            raise UnsupportedError(
                f"dcn join sides {missing} are not broadcast to the "
                "workers (Cluster.broadcast_table)")

    def has_agg(e) -> bool:
        import dataclasses as _dc

        if isinstance(e, A.EFunc) and e.name in _DIST_AGGS:
            return True
        if isinstance(e, A.EFunc) and e.name in _NONDIST_AGGS:
            # an extended aggregate must NOT fall into the TopN
            # scan-gather path — the workers would each return a local
            # value and the union would silently be wrong
            raise UnsupportedError(
                f"dcn tier: aggregate {e.name} has no partial/final split")
        if not _dc.is_dataclass(e):
            return False
        for fld in _dc.fields(e):
            v = getattr(e, fld.name)
            items = v if isinstance(v, (list, tuple)) else [v]
            for item in items:
                if isinstance(item, tuple):
                    if any(_dc.is_dataclass(x) and has_agg(x) for x in item):
                        return True
                elif _dc.is_dataclass(item) and has_agg(item):
                    return True
        return False

    rename = dict(renames or {})
    if table_as:
        rename[fact] = table_as
    from_sql = _from_sql(st.from_, rename)
    where = f" where {expr_to_sql(st.where)}" if st.where is not None else ""

    if not st.group_by and not any(has_agg(it.expr) for it in st.items):
        return _topn_rewrite(st, from_sql, where)

    group_sqls = [expr_to_sql(g) for g in st.group_by]
    part_items: List[str] = []
    final_items: List[str] = []
    out_names: List[str] = []
    gcol: Dict[str, str] = {}
    for i, g in enumerate(group_sqls):
        gname = f"g{i}"
        part_items.append(f"{g} as {gname}")
        gcol[g] = gname

    for i, item in enumerate(st.items):
        e = item.expr
        alias = item.alias or (
            e.name if isinstance(e, A.EName) else f"col{i}")
        out_names.append(alias)
        esql = expr_to_sql(e)
        if esql in gcol:  # a group-by column in output position
            final_items.append(f"{gcol[esql]} as `{alias}`")
            continue
        if not (isinstance(e, A.EFunc) and e.name in _DIST_AGGS):
            raise UnsupportedError(
                f"dcn output must be group columns or plain aggregates, got {esql}")
        if e.distinct:
            raise UnsupportedError("dcn tier: DISTINCT aggregates")
        argsql = expr_to_sql(e.args[0]) if e.args else "*"
        if e.name == "count":
            part_items.append(f"count({argsql}) as p{i}")
            final_items.append(f"sum(p{i}) as `{alias}`")
        elif e.name in ("sum", "min", "max"):
            part_items.append(f"{e.name}({argsql}) as p{i}")
            final_items.append(f"{e.name}(p{i}) as `{alias}`")
        else:  # avg = sum of sums / sum of counts
            part_items.append(f"sum({argsql}) as p{i}s")
            part_items.append(f"count({argsql}) as p{i}c")
            final_items.append(f"sum(p{i}s) / sum(p{i}c) as `{alias}`")

    groupby = f" group by {', '.join(group_sqls)}" if group_sqls else ""
    partial_sql = (f"select {', '.join(part_items)} from {from_sql}"
                   f"{where}{groupby}")

    fgroup = f" group by {', '.join(gcol.values())}" if gcol else ""
    order = ""
    if st.order_by:
        terms = []
        for o in st.order_by:
            osql = expr_to_sql(o.expr)
            if isinstance(o.expr, A.EName) and o.expr.qualifier is None \
                    and o.expr.name in out_names:
                ref = f"`{o.expr.name}`"
            elif osql in gcol:
                ref = gcol[osql]
            else:
                raise UnsupportedError(
                    "dcn ORDER BY must reference output aliases or group columns")
            terms.append(ref + (" desc" if o.desc else ""))
        order = " order by " + ", ".join(terms)
    limit = f" limit {st.limit}" if st.limit is not None else ""
    offset = f" offset {st.offset}" if st.offset is not None else ""
    final_sql = (f"select {', '.join(final_items)} from `__dcn_partial__`"
                 f"{fgroup}{order}{limit}{offset}")
    return partial_sql, final_sql, out_names


def _topn_rewrite(st: A.SelectStmt, from_sql: str, where: str
                  ) -> Tuple[str, str, List[str]]:
    """Plain SELECT [ORDER BY ... LIMIT n]: each worker returns its
    local rows (top n+offset when limited); the coordinator re-sorts and
    applies the final limit/offset. Without a LIMIT this is a plain
    distributed scan-gather."""
    part_items, out_names = [], []
    for i, item in enumerate(st.items):
        e = item.expr
        alias = item.alias or (
            e.name if isinstance(e, A.EName) else f"col{i}")
        out_names.append(alias)
        part_items.append(f"{expr_to_sql(e)} as `{alias}`")

    item_sqls = [expr_to_sql(it.expr) for it in st.items]
    order_terms = []
    for o in st.order_by:
        osql = expr_to_sql(o.expr)
        if isinstance(o.expr, A.EName) and o.expr.qualifier is None \
                and o.expr.name in out_names:
            ref = f"`{o.expr.name}`"
        elif osql in item_sqls:
            ref = f"`{out_names[item_sqls.index(osql)]}`"
        else:
            raise UnsupportedError(
                "dcn TopN ORDER BY must reference output columns")
        order_terms.append(ref + (" desc" if o.desc else ""))
    order = (" order by " + ", ".join(order_terms)) if order_terms else ""

    part_limit = ""
    if st.limit is not None:
        if not order_terms:
            raise UnsupportedError("dcn LIMIT without ORDER BY is ambiguous")
        part_limit = f" limit {st.limit + (st.offset or 0)}"
    partial_sql = (f"select {', '.join(part_items)} from {from_sql}"
                   f"{where}{order}{part_limit}")
    limit = f" limit {st.limit}" if st.limit is not None else ""
    offset = f" offset {st.offset}" if st.offset is not None else ""
    final_sql = (f"select {', '.join(f'`{n}`' for n in out_names)} "
                 f"from `__dcn_partial__`{order}{limit}{offset}")
    return partial_sql, final_sql, out_names


# ---------------------------------------------------------------------------
# predicate helpers for shard-key pruning + shuffle planning (ISSUE 13)
# ---------------------------------------------------------------------------

_NOT_LITERAL = object()


def _eq_conjuncts(e):
    """Flatten an AND tree into its conjuncts."""
    if isinstance(e, A.EBinary) and e.op == "and":
        yield from _eq_conjuncts(e.left)
        yield from _eq_conjuncts(e.right)
    else:
        yield e


def _literal_int(e):
    """Integer value of a literal expr (None for NULL); _NOT_LITERAL
    when it is anything else — float literals included, because the
    device's f64 compare and python int arithmetic can disagree, so a
    float-pinned shard key must not prune (same rule as zone maps)."""
    neg = False
    while isinstance(e, A.EUnary) and e.op in ("-", "+"):
        neg ^= (e.op == "-")
        e = e.arg
    if isinstance(e, A.ENull):
        return None
    if isinstance(e, A.ENum) and "." not in e.text \
            and "e" not in e.text.lower():
        try:
            v = int(e.text)
        except ValueError:
            return _NOT_LITERAL
        return -v if neg else v
    return _NOT_LITERAL


def _shard_eq_value(where, table: str, column: str):
    """(value, True) when a WHERE conjunct pins `column` to one integer
    literal (col = N, qualifier absent or naming `table`) — the scan
    then dispatches to that single shard's owner."""
    if where is None:
        return None, False
    for c in _eq_conjuncts(where):
        if not (isinstance(c, A.EBinary) and c.op == "="):
            continue
        for name_side, lit_side in ((c.left, c.right),
                                    (c.right, c.left)):
            if not isinstance(name_side, A.EName):
                continue
            if name_side.name != column:
                continue
            if name_side.qualifier not in (None, table):
                continue
            v = _literal_int(lit_side)
            if v is not _NOT_LITERAL:
                return v, True
    return None, False


def _rewrite_dml_table(sql: str, name: str, repl: str) -> str:
    """Retarget an UPDATE/DELETE statement at a different physical
    table (the reshard double-write against a staging copy). Textual
    but anchored: only the leading ``update <name>`` / ``delete from
    <name>`` token rewrites, so a same-named column or string literal
    deeper in the statement stays untouched."""
    pat = re.compile(
        r"^(\s*(?:update|delete\s+from)\s+)(`%s`|%s)\b"
        % (re.escape(name), re.escape(name)), re.IGNORECASE)
    out, n = pat.subn(lambda m: m.group(1) + f"`{repl}`", sql, count=1)
    if not n:
        raise UnsupportedError(
            "dcn dml: cannot retarget statement at the reshard staging "
            f"copy ({sql[:60]!r})")
    return out


def _walk_exprs(node):
    """Every dataclass expr node reachable from `node` (AST subtrees,
    lists, tuples) — the EName harvest for used-column analysis."""
    import dataclasses as _dc

    stack = [node]
    while stack:
        e = stack.pop()
        if isinstance(e, (list, tuple)):
            stack.extend(e)
            continue
        if not _dc.is_dataclass(e):
            continue
        yield e
        for fld in _dc.fields(e):
            stack.append(getattr(e, fld.name))


def _equi_name_pairs(st) -> List[Tuple[A.EName, A.EName]]:
    """(EName, EName) pairs from every equality conjunct in the JOIN ON
    trees and the WHERE — the candidate shuffle keys."""
    conds: List = []

    def walk_src(src):
        if isinstance(src, A.Join):
            if src.on is not None:
                conds.extend(_eq_conjuncts(src.on))
            walk_src(src.left)
            walk_src(src.right)

    walk_src(st.from_)
    if st.where is not None:
        conds.extend(_eq_conjuncts(st.where))
    out = []
    for c in conds:
        if isinstance(c, A.EBinary) and c.op == "=" \
                and isinstance(c.left, A.EName) \
                and isinstance(c.right, A.EName):
            out.append((c.left, c.right))
    return out


# ---------------------------------------------------------------------------
# coordinator
# ---------------------------------------------------------------------------


class _LinkHealth:
    """One worker link's health-machine record (UP -> SUSPECT -> DOWN
    with exponential backoff + jitter between reconnect probes). All
    transitions happen under the link's socket lock."""

    __slots__ = ("state", "attempts", "next_retry", "last_error",
                 "reconnects", "since")

    def __init__(self):
        self.state = UP
        self.attempts = 0        # consecutive failed reconnects
        self.next_retry = 0.0    # monotonic: earliest half-open probe
        self.last_error = ""
        self.reconnects = 0      # successful re-establishments, ever
        self.since = time.monotonic()


class _DmlMember:
    """One execute_dml call waiting inside a 2PC write window."""

    __slots__ = ("per_worker", "done", "result", "exc")

    def __init__(self, per_worker: Dict[int, str]):
        self.per_worker = per_worker
        self.done = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None


class _DmlWindow:
    """Cross-session group commit over the mesh (ISSUE 17): concurrent
    execute_dml calls gather for ``Cluster.dml_window_us`` and ride ONE
    prepare/decide/commit round per shard owner — each worker's prepare
    carries the window's statements as a `sqls` list staged inside one
    participant transaction.

    Exactness mirrors the local batcher's fallback rule: a failure
    BEFORE the commit decision aborted every shard, so the leader
    re-drives each member's own write as a singleton round (exact typed
    errors, no lost statements). A TwoPhaseCommitIncomplete happened
    AFTER the decision — the writes are committed — so it propagates to
    every member unretried (a retry would double-apply)."""

    def __init__(self, cluster: "Cluster"):
        self.cluster = cluster
        self._lock = threading.Lock()
        self._open: Optional[List[_DmlMember]] = None
        self.windows = 0           # merged rounds executed (n >= 2)
        self.coalesced_stmts = 0   # members of merged rounds

    def submit(self, per_worker: Dict[int, str]) -> Dict[str, object]:
        member = _DmlMember(per_worker)
        with self._lock:
            if self._open is not None:
                self._open.append(member)
                leader = False
            else:
                self._open = [member]
                leader = True
        if not leader:
            member.done.wait()
        else:
            # the leader IS the gather clock: it sleeps out the window
            # on its caller's thread (no worker pool on the coordinator)
            time.sleep(self.cluster.dml_window_us / 1e6)
            with self._lock:
                members = self._open or [member]
                self._open = None
            self._run(members)
        if member.exc is not None:
            raise member.exc
        return member.result

    def _run(self, members: List[_DmlMember]) -> None:
        cl = self.cluster
        if len(members) == 1:
            m = members[0]
            try:
                m.result = cl._two_phase(m.per_worker)
            except BaseException as e:  # noqa: BLE001 — relayed
                m.exc = e
            m.done.set()
            return
        merged: Dict[int, List[str]] = {}
        for m in members:
            for w, sql in m.per_worker.items():
                # a member's per-worker value may itself be a LIST
                # (reshard double-writes): flatten, don't nest
                bucket = merged.setdefault(w, [])
                (bucket.extend if isinstance(sql, list)
                 else bucket.append)(sql)
        with self._lock:
            self.windows += 1
            self.coalesced_stmts += len(members)
        try:
            res = cl._two_phase(merged)
        except TwoPhaseCommitIncomplete as e:
            for m in members:
                m.exc = e
                m.done.set()
            return
        except Exception:  # noqa: BLE001 — every shard aborted; the
            # members re-run alone for their exact typed errors
            for m in members:
                try:
                    m.result = cl._two_phase(m.per_worker)
                except BaseException as e:  # noqa: BLE001 — relayed
                    m.exc = e
                m.done.set()
            return
        for m in members:
            # shared xid, member-specific participant list (the workers
            # THIS write touched — what a singleton round would report)
            m.result = {"xid": res["xid"],
                        "workers": sorted(m.per_worker)}
            m.done.set()


class Cluster:
    """Coordinator-side handle on the worker fleet.

    `replicas` maps partition/worker index -> replica worker index; a
    partition loaded with load_partition is mirrored into the replica's
    `<table>__part<i>` table, and a failed partial RPC retries there
    (the region-replica failover analogue).

    Failure domain: every RPC runs under a per-call socket deadline
    (min of `rpc_timeout_s` and the statement deadline's remainder); a
    failed link moves through UP -> SUSPECT (one immediate reconnect
    allowed) -> DOWN (exponential backoff + jitter between half-open
    probes) instead of being permanently dead. Idempotent RPCs retry
    once on a fresh connection before replica failover."""

    # a dim bigger than this doesn't broadcast: replicating it to every
    # worker would cost more than the join saves (ref: the reference's
    # broadcast-join threshold)
    BROADCAST_LIMIT_BYTES = int(os.environ.get(
        "DCN_BROADCAST_LIMIT", str(64 << 20)))

    # reconnect backoff: SUSPECT probes immediately; each further
    # failure doubles the wait (plus up to 25% jitter so a fleet of
    # coordinators doesn't probe a recovering worker in lockstep),
    # capped so a restarted worker is re-admitted within ~RECONNECT_CAP_S
    RECONNECT_BASE_S = 0.05
    RECONNECT_CAP_S = 2.0
    RECONNECT_MAX_DOUBLINGS = 6   # attempts beyond this probe at the cap
    JITTER_FRAC = 0.25
    CANCEL_DIAL_TIMEOUT_S = 2.0   # side-channel cancel must never hang
    # default bound on a statement's wait for a topology-change gate
    # (overridden per-session by tidb_tpu_reshard_gate_wait_ms)
    GATE_WAIT_S = 10.0

    def __init__(self, endpoints: List[Tuple[str, int]],
                 secret: Optional[str] = None,
                 replicas: Optional[Dict[int, int]] = None,
                 rpc_timeout_s: Optional[float] = 30.0,
                 connect_timeout_s: float = 30.0,
                 partial_results: bool = False):
        self.secret = secret
        self.replicas = dict(replicas or {})
        self.rpc_timeout_s = rpc_timeout_s
        self.connect_timeout_s = connect_timeout_s
        # a partition with primary AND replica unreachable: fail the
        # query (False) or serve reachable partitions with a warning
        self.partial_results = partial_results
        self.last_warnings: List[str] = []
        self._socks: List[Optional[socket.socket]] = []
        self._closed = False
        self._endpoints = list(endpoints)
        self._partitioned: set = set()
        self._broadcast: set = set()
        # sharded placement (ISSUE 13): table -> ShardMap snapshot +
        # loaded bytes. The lock is a LEAF: snapshot under it, never a
        # socket send (blocking-under-lock pass enforces the shape —
        # see tests/analysis_fixtures/bad_shuffle_lock.py)
        self._placements: Dict[str, object] = {}
        self._placement_bytes: Dict[str, int] = {}
        self._placement_lock = threading.Lock()
        self._table_cols_cache: Dict[str, List[str]] = {}
        # online reshard (ISSUE 19): table -> per-shard state machine
        # ({"sid","old","new","moves","shards","dw","xl"}). Statements
        # keep routing by the OLD map while shards backfill in the
        # background; DML double-writes to the destination staging for
        # shards in `dw`; the fence narrows to shards left in "cutover"
        # by a fault (recover_reshard re-drives the idempotent half).
        self._reshard_state: Dict[str, Dict] = {}
        # per-table readers/writer gates: every statement read-acquires
        # its tables + CLUSTER_GATE; backfill/cutover/membership
        # finalize write-acquire briefly (bounded — see membership.py)
        self._gates = TableGates()
        # elastic membership: DDL replay log for joiners, and the drain
        # translation (old worker index -> surviving socket index) that
        # keeps already-compacted placements routable mid-drain
        self._ddl_log: List[str] = []
        self._membership_lock = threading.Lock()
        self._draining: Optional[int] = None
        self._drain_xl: Optional[Dict[int, int]] = None
        # 2PC coordinator state: xid -> participant worker ids. A txn
        # moves pending -> decided at the commit point; recover_txns()
        # finishes either side after a coordinator "crash" (failpoint
        # between prepare and commit — the chaos grid's window)
        self._txn_pending: Dict[str, List[int]] = {}
        self._txn_decided: Dict[str, List[int]] = {}
        self._txn_lock = threading.Lock()
        # group-commit write window (ISSUE 17): >0 gathers concurrent
        # execute_dml calls for this many microseconds and two-phase-
        # commits the whole window in ONE round per shard owner
        self.dml_window_us = 0
        self._dml_window = _DmlWindow(self)
        self._health: List[_LinkHealth] = [_LinkHealth() for _ in endpoints]
        # per-call RPC budget (deadline + timeout) travels thread-local
        # so _call keeps its monkeypatch-friendly (i, msg) signature
        self._tl = threading.local()
        # one lock per worker socket: callers may issue RPCs to the same
        # worker from several threads (a DML thread racing online_ddl's
        # stage barriers); an interleaved send/recv pair desyncs the
        # length-prefixed framing permanently
        self._sock_locks: List[threading.Lock] = [
            threading.Lock() for _ in endpoints]
        for i, (host, port) in enumerate(endpoints):
            self._socks.append(self._connect(host, port))
            self._set_state(i, UP)
        from tidb_tpu.session import Session

        self._merge_session = Session()
        # concurrent statements share the merge session and its one
        # __dcn_partial__ staging table: the merge phase serializes
        # behind this lock (sustained mixed traffic runs DURING
        # topology changes — ISSUE 19; worker-side partials still
        # compute concurrently, only the coordinator merge queues)
        self._merge_lock = threading.Lock()
        _CLUSTERS.add(self)

    def _set_state(self, i: int, state: str) -> None:
        # entry FIELDS are confined by _sock_locks[i] (every caller is
        # a *_locked method) or by construction (ctor/add_worker touch
        # an index no statement can reach yet); the list SHAPE is what
        # _membership_lock + the cluster gate guard
        h = self._health[i]
        h.state = state
        h.since = time.monotonic()
        from tidb_tpu.utils.metrics import WORKER_STATE

        host, port = self._endpoints[i]
        WORKER_STATE.set(_STATE_CODE[state], endpoint=f"{host}:{port}")

    def _connect(self, host: str, port: int,
                 timeout: Optional[float] = None) -> socket.socket:
        inject("dcn.connect")
        return dial(host, port, secret=self.secret,
                    timeout=timeout or self.connect_timeout_s)

    def __len__(self):
        return len(self._socks)

    # -- failure domain: budgets, health transitions, reconnect ---------

    def _rpc_budget(self, i: int) -> Optional[float]:
        """Per-call socket deadline: min(rpc timeout, statement
        deadline remainder). Raises the typed timeout when the
        statement's budget is already spent — don't even send."""
        timeout = getattr(self._tl, "rpc_timeout", None)
        if timeout is None:
            timeout = self.rpc_timeout_s
        if timeout is not None and timeout <= 0:
            timeout = None
        dl = getattr(self._tl, "deadline", None)
        if dl is not None:
            rem = dl - time.monotonic()
            if rem <= 0:
                raise QueryTimeoutError(
                    "Query execution was interrupted, maximum statement "
                    f"execution time exceeded (before dcn worker {i} rpc)")
            timeout = rem if timeout is None else min(timeout, rem)
        return timeout

    def _note_failure_locked(self, i: int, e: Exception) -> None:
        """UP -> SUSPECT (one immediate reconnect), further failures ->
        DOWN with exponential backoff + jitter before the next half-open
        probe. Caller holds self._sock_locks[i]."""
        h = self._health[i]
        h.last_error = str(e)
        if h.state == UP:
            self._set_state(i, SUSPECT)
            h.next_retry = 0.0  # half-open immediately: maybe a blip
        else:
            self._set_state(i, DOWN)
            h.attempts += 1
            backoff = self.RECONNECT_BASE_S * (
                2 ** min(h.attempts, self.RECONNECT_MAX_DOUBLINGS))
            backoff = min(backoff, self.RECONNECT_CAP_S)
            backoff *= 1.0 + self.JITTER_FRAC * random.random()
            h.next_retry = time.monotonic() + backoff

    def _note_ok_locked(self, i: int) -> None:
        h = self._health[i]
        if h.state != UP:
            self._set_state(i, UP)
        h.attempts = 0
        h.next_retry = 0.0

    def _reconnect_locked(self, i: int) -> socket.socket:
        """Half-open probe: re-dial a SUSPECT/DOWN worker. Honors the
        circuit breaker — inside the backoff window the call fails fast
        without touching the network. Caller holds the socket lock."""
        h = self._health[i]
        now = time.monotonic()
        if now < h.next_retry:
            raise ConnectionError(
                f"dcn worker {i} is down (circuit open for another "
                f"{h.next_retry - now:.2f}s; last error: {h.last_error})")
        host, port = self._endpoints[i]
        try:
            sock = self._connect(host, port)
        except (ConnectionError, OSError, ExecutionError) as e:
            self._note_failure_locked(i, e)
            raise ConnectionError(
                f"dcn worker {i}: reconnect failed: {e}") from e
        self._socks[i] = sock
        h.reconnects += 1
        from tidb_tpu.utils.metrics import DCN_RETRY_TOTAL

        DCN_RETRY_TOTAL.inc(kind="reconnect")
        tracing.annotate(f"reconnect:w{i}")
        return sock

    def _remote_error(self, i: int, err: str) -> ExecutionError:
        """Re-type a worker-reported error: kill/deadline travel the
        wire as `ClassName: message` and must stay typed end to end."""
        return _retype_wire_error(err, f"dcn worker {i}: {err}")

    def _call(self, i: int, msg: Dict):
        t0 = time.perf_counter()
        timeout = self._rpc_budget(i)
        # trace-context propagation: under an active trace every RPC
        # gets a span, the message carries trace_id (only — see below)
        # so the worker records server-side spans against it, and the
        # response piggybacks those spans back for grafting under the
        # rpc span
        tr = tracing.current()
        sp = None
        if tr is not None:
            sp = tr.begin(f"dcn.rpc.{msg.get('cmd', '?')}[w{i}]",
                          parent_id=tracing.current_span_id())
            # copy before annotating: call sites share one msg dict
            # across workers (`[{...}] * n`), and the trace context is
            # per-call — in-place writes would cross trace ids between
            # workers and race the codec. Only trace_id travels: the
            # worker's spans graft back under THIS side's rpc span, so
            # a wire span_id would be dead bytes on every message (the
            # protocol-conformance pass enforces exactly that).
            msg = dict(msg, trace_id=tr.trace_id)
        try:
            with self._sock_locks[i]:  # one in-flight RPC per worker
                if self._closed:
                    # a late dispatch/drain thread must not redial a
                    # worker after close() — fail loudly instead
                    raise ConnectionError(
                        f"dcn cluster is closed (worker {i})")
                sock = self._socks[i]
                if sock is None:
                    if not getattr(self._tl, "reconnect", True):
                        raise ConnectionError(f"dcn worker {i} is down")
                    sock = self._reconnect_locked(i)
                try:
                    inject("dcn.coord.send")
                    if timeout is not None:
                        sock.settimeout(timeout)
                    _send(sock, msg)
                    inject("dcn.coord.recv")
                    resp = _recv(sock)
                    if timeout is not None:
                        sock.settimeout(None)
                except (ConnectionError, OSError, DcnCodecError) as e:
                    # mark dead so retries don't reuse a broken socket —
                    # still under the lock, so a concurrent caller can
                    # never have its healthy RPC closed out from
                    # underneath it
                    try:
                        sock.close()
                    except OSError:
                        pass
                    self._socks[i] = None
                    self._note_failure_locked(i, e)
                    if isinstance(e, (socket.timeout, TimeoutError)):
                        dl = getattr(self._tl, "deadline", None)
                        if dl is not None and time.monotonic() >= dl:
                            raise QueryTimeoutError(
                                "Query execution was interrupted, maximum "
                                "statement execution time exceeded "
                                f"(dcn worker {i} rpc)") from e
                        # timeout may be None here (timeouts disabled, TCP
                        # stack raised ETIMEDOUT on the blocking socket)
                        after = (f" after {timeout:.2f}s"
                                 if timeout is not None else "")
                        raise DcnRpcTimeoutError(
                            f"dcn worker {i}: rpc timed out{after}") from e
                    raise ConnectionError(f"dcn worker {i}: {e}") from e
                self._note_ok_locked(i)
        except Exception as e:
            if sp is not None:
                sp.notes.append(f"error:{type(e).__name__}")
                tr.end(sp)
            raise
        dt = time.perf_counter() - t0
        if sp is not None:
            sp.notes.append(
                f"sent_bytes={getattr(_IO_TLS, 'last_sent', 0)}")
            sp.notes.append(
                f"recv_bytes={getattr(_IO_TLS, 'last_recv', 0)}")
            tr.end(sp)
            remote = resp.get("trace") if isinstance(resp, dict) else None
            if remote:
                host, port = self._endpoints[i]
                tr.graft(remote, sp, proc=f"{host}:{port}")
        from tidb_tpu.utils.metrics import DCN_RPC_SECONDS, DCN_RTT

        DCN_RTT.observe(dt)
        DCN_RPC_SECONDS.observe(dt, cmd=str(msg.get("cmd", "?")))
        if not resp["ok"]:
            raise self._remote_error(i, resp["error"])
        return resp["result"]

    def _call_retry(self, i: int, msg: Dict):
        """IDEMPOTENT RPCs only (reads, ping, stats): one retry on a
        fresh connection before the caller falls to replica failover.
        Never retries an RPC TIMEOUT (the worker is probably still
        executing the first attempt — re-sending would run it twice
        concurrently and collide the cancel token) nor typed
        kill/deadline errors (the budget is spent)."""
        try:
            return self._call(i, msg)
        except DcnRpcTimeoutError:
            raise
        except ConnectionError:
            from tidb_tpu.utils.metrics import DCN_RETRY_TOTAL

            DCN_RETRY_TOTAL.inc(kind="rpc")
            # a retry path is exactly what tail sampling wants to keep
            tracing.keep("retry")
            tracing.annotate(f"retry:w{i}")
            return self._call(i, msg)

    def _call_all(self, msgs: List[Dict], idempotent: bool = False) -> List:
        """One message per worker, dispatched concurrently. Errors are
        collected PER INDEX: the raised error is the lowest failed
        worker's, and when several died the message carries the full
        list — one failure must not hide that others also failed (nor
        may the raised one be whichever thread lost the append race)."""
        results: List = [None] * len(self._socks)
        errors: List[Optional[Exception]] = [None] * len(self._socks)

        def run(i):
            try:
                fn = self._call_retry if idempotent else self._call
                results[i] = fn(i, msgs[i])
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(self._socks))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        failed = [i for i, e in enumerate(errors) if e is not None]
        if failed:
            first = errors[failed[0]]
            if len(failed) == 1:
                raise first
            detail = "; ".join(f"worker {j}: {errors[j]}" for j in failed)
            try:
                err = type(first)(
                    f"{len(failed)} dcn workers failed — {detail}")
            except Exception:  # noqa: BLE001 — exotic ctor: keep first
                err = first
            raise err from first
        return results

    def broadcast_exec(self, sql: str) -> None:
        self._call_all([{"cmd": "exec", "sql": sql}] * len(self._socks))
        # membership replay log: add_worker() replays the broadcast
        # history so a joiner's schema (and broadcast-table DDL) match
        # the fleet before it takes placement traffic
        with self._membership_lock:
            self._ddl_log.append(sql)

    def online_ddl(self, sql: str, between_stages=None) -> None:
        """ONLINE multi-version schema change across worker processes
        (ref: the DDL owner stepping the schema state machine one
        version at a time while every instance keeps serving,
        SURVEY.md:180-185). Each stage is an all-worker barrier — the
        synchronous-ack equivalent of waiting out a schema lease, giving
        the same ≤2-adjacent-versions guarantee. Concurrent DML between
        stages is exactly the window the write_only states make safe.
        `between_stages(stage)` is a test hook to widen that window.
        A backfill failure (or dead worker) aborts the staged object on
        every reachable worker."""
        from tidb_tpu.parser import parse
        from tidb_tpu.parser import ast as A

        stmt = parse(sql)[0]
        if not (isinstance(stmt, A.AlterTableStmt)
                and stmt.action in ("add_column", "add_index")):
            # shapes without intermediate states apply atomically
            self.broadcast_exec(sql)
            return
        stages = (["write_only", "public"] if stmt.action == "add_column"
                  else ["write_only", "backfill", "public"])
        done = []
        try:
            for stage in stages:
                self._call_all(
                    [{"cmd": "ddl_stage", "sql": sql, "stage": stage}]
                    * len(self._socks))
                done.append(stage)
                if between_stages is not None:
                    between_stages(stage)
            # fully public everywhere: one replayable statement for
            # future joiners (a joiner applies it atomically — it has
            # no concurrent DML to stage around)
            with self._membership_lock:
                self._ddl_log.append(sql)
        except Exception:
            if "public" not in done:
                try:
                    self._call_all(
                        [{"cmd": "ddl_stage", "sql": sql, "stage": "abort"}]
                        * len(self._socks))
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
            raise

    def load_partition(self, worker: int, table: str, arrays=None,
                       valids=None, strings=None, db: Optional[str] = None
                       ) -> int:
        n = self._call(worker, {
            "cmd": "load_columns", "table": table, "arrays": arrays,
            "valids": valids, "strings": strings, "db": db,
        })
        # mark only after the load lands: a stale mark on a failed load
        # would defeat the replicated-table refusal in partial_rewrite
        self._partitioned.add(table)
        rep = self.replicas.get(worker)
        if rep is not None:
            self._call(rep, {
                "cmd": "load_columns", "table": f"{table}__part{worker}",
                "like": table, "arrays": arrays, "valids": valids,
                "strings": strings, "db": db,
            })
        return n

    def broadcast_table(self, table: str, arrays=None, valids=None,
                        strings=None, db: Optional[str] = None) -> int:
        """Ship a full (dimension) table to EVERY worker so partitioned
        fact scans can join it locally (the star-schema broadcast join;
        SURVEY.md:131). Size-capped: replicating a big table would cost
        more than the join saves."""
        size = 0
        for v in (arrays or {}).values():
            size += np.asarray(v).nbytes
        for v in (valids or {}).values():
            size += np.asarray(v).nbytes
        for pool in (strings or {}).values():
            size += sum(len(x) for x in pool)
        if size > self.BROADCAST_LIMIT_BYTES:
            raise ExecutionError(
                f"broadcast_table({table!r}): {size} bytes exceeds the "
                f"{self.BROADCAST_LIMIT_BYTES}-byte broadcast cap")
        msg = {"cmd": "load_columns", "table": table, "arrays": arrays,
               "valids": valids, "strings": strings, "db": db}
        ns = self._call_all([dict(msg) for _ in self._socks])
        self._broadcast.add(table)
        return ns[0]

    def mark_broadcast(self, table: str) -> None:
        """Register a table as present-in-full on every worker when it
        was loaded out of band (e.g. broadcast_exec INSERTs)."""
        self._broadcast.add(table)

    def mark_partitioned(self, table: str) -> None:
        self._partitioned.add(table)

    # -- sharded placement (ISSUE 13) -----------------------------------

    def ddl(self, sql: str) -> None:
        """Broadcast a DDL to the fleet; SHARD BY metadata additionally
        registers a coordinator-side placement so loads, scans, joins,
        and DML route by shard ownership from here on. An ALTER ...
        SHARD BY must go through reshard() — registering a new map
        without moving the rows would route scans to owners that do
        not hold them."""
        shard = None
        stmt = None
        try:
            stmt = parse(sql)[0]
            shard = getattr(stmt, "shard", None)
        except Exception:  # noqa: BLE001 — let the workers' parsers
            pass           # be the authority on malformed DDL
        if shard is not None and isinstance(stmt, A.AlterTableStmt):
            n = stmt.table.name
            # refuse whenever the fleet is known to hold the table's
            # rows (placed, row-range partitioned, OR broadcast) —
            # registering a map without moving them would route scans
            # to owners that do not hold the data (and a broadcast
            # table fanned as partitioned multiplies every aggregate)
            if self.placement(n) is not None or n in self._partitioned \
                    or n in self._broadcast:
                raise UnsupportedError(
                    "ALTER ... SHARD BY over loaded data must go "
                    "through Cluster.reshard() (the rows have to move)")
        self.broadcast_exec(sql)
        self._table_cols_cache.clear()
        if shard is None:
            return
        from tidb_tpu.sharding.placement import ShardMap

        name = stmt.table.name
        kind, col, arg = shard
        with self._placement_lock:
            old = self._placements.get(name)
            version = (old.version + 1) if old is not None else 0
            if kind == "range":
                smap = ShardMap("range", col, len(arg) + 1,
                                len(self._socks), tuple(arg), version)
            else:
                smap = ShardMap("hash", col, int(arg), len(self._socks),
                                (), version)
            self._placements[name] = smap
        self._partitioned.add(name)

    def placement(self, table: str):
        with self._placement_lock:
            return self._placements.get(table)

    def load_sharded(self, table: str, arrays=None, valids=None,
                     strings=None, db: Optional[str] = None) -> int:
        """Route rows to their shard owners per the registered
        placement (register with Cluster.ddl's SHARD BY first). Every
        owner also records its owned shard set + bytes (place_shards),
        so `information_schema.dcn_worker_stats` shows where data
        lives; a worker with a replica mirrors its slice into
        `<table>__part<w>` exactly like load_partition."""
        from tidb_tpu.sharding import placement as pl
        from tidb_tpu.sharding import shuffle as shfl

        self._check_reshard_fence([table])
        # bulk loads don't ride the double-write machinery: rows landed
        # mid-reshard/mid-drain would miss the staging copy and vanish
        # at cutover — refuse typed until the topology settles
        if self._mid_reshard(table):
            raise ExecutionError(
                f"load_sharded({table!r}): table is mid-reshard; "
                "retry after the reshard completes")
        if self._draining is not None:
            raise ExecutionError(
                f"load_sharded({table!r}): worker {self._draining} is "
                "draining; retry after remove_worker completes")
        smap = self.placement(table)
        if smap is None:
            raise ExecutionError(
                f"no shard placement registered for {table!r} "
                "(CREATE ... SHARD BY via Cluster.ddl)")
        arrays = {k: np.asarray(v) for k, v in (arrays or {}).items()}
        valids = {k: np.asarray(v, dtype=bool)
                  for k, v in (valids or {}).items()}
        strings = {k: list(v) for k, v in (strings or {}).items()}
        if smap.column not in arrays:
            raise ExecutionError(
                f"load_sharded({table!r}): shard column "
                f"{smap.column!r} missing from arrays")
        for k, a in arrays.items():
            if k not in valids:
                valids[k] = np.ones(len(a), dtype=bool)
        key = arrays[smap.column]
        shards = pl.shard_of_array(smap, key, valids[smap.column])
        dest = shards % np.int64(max(len(self._socks), 1))
        parts = shfl.partition_rows(arrays, valids, strings, dest,
                                    len(self._socks))
        owners = smap.owners()
        total = 0
        total_bytes = 0
        for w, part in enumerate(parts):
            part_bytes = 0
            if part is not None:
                a, v, s = part
                total += self._call(w, {
                    "cmd": "load_columns", "table": table, "arrays": a,
                    "valids": v, "strings": s, "db": db})
                part_bytes = sum(x.nbytes for x in a.values()) \
                    + sum(x.nbytes for x in v.values()) \
                    + sum(len(x or "") + 1 for col in s.values()
                          for x in col)
                rep = self.replicas.get(w)
                if rep is not None:
                    self._call(rep, {
                        "cmd": "load_columns",
                        "table": f"{table}__part{w}", "like": table,
                        "arrays": a, "valids": v, "strings": s, "db": db})
            total_bytes += part_bytes
            self._call(w, {"cmd": "place_shards", "table": table,
                           "shards": owners.get(w, []),
                           "bytes": part_bytes})
        self._partitioned.add(table)
        with self._placement_lock:
            self._placement_bytes[table] = \
                self._placement_bytes.get(table, 0) + total_bytes
        return total

    def _table_columns(self, table: str) -> List[str]:
        """Public column names of a fleet table in schema order, read
        once from the first REACHABLE worker (the coordinator's merge
        session does not hold worker schemas, and one dead worker must
        not take shuffle planning / INSERT routing down with it)."""
        cached = self._table_cols_cache.get(table)
        if cached is not None:
            return cached
        last: Optional[Exception] = None
        for i in range(len(self._socks)):
            try:
                rows = self._call_retry(i, {
                    "cmd": "exec",
                    "sql": f"show columns from `{table}`"})
                break
            except Exception as e:  # noqa: BLE001 — try the next
                last = e            # endpoint; raise the last failure
        else:
            raise ExecutionError(
                f"no worker could describe {table!r}: {last}")
        cols = [r[0] for r in rows]
        self._table_cols_cache[table] = cols
        return cols

    def _check_reshard_fence(self, names) -> None:
        """Refuse statements against a SHARD left in "cutover" by a
        fault (half-swapped: sources may be part-purged, the
        destination not yet installed — either map double-counts or
        drops its rows). Per-shard, not per-table: a healthy online
        reshard never trips this — its cutover windows hide behind the
        table gate instead — and the refusal names the stuck shard so
        the operator knows exactly what recover_reshard() will fix."""
        with self._placement_lock:
            fenced = []
            for n in names:
                rst = self._reshard_state.get(n)
                if rst is None:
                    continue
                stuck = sorted(s for s, v in rst["shards"].items()
                               if v == "cutover")
                if stuck:
                    fenced.append((n, stuck))
        if fenced:
            detail = "; ".join(f"{n!r} shard(s) {sh}" for n, sh in fenced)
            raise ExecutionError(
                f"shard cutover interrupted: {detail} — "
                "Cluster.recover_reshard() finishes the swap")

    def _acquire_read_gate(self, names, session=None) -> List[str]:
        """Statement-side topology gate: shared-acquire the touched
        tables plus CLUSTER_GATE. Bounded — a stuck cutover degrades
        this statement TYPED after the configured wait, never hangs
        it."""
        wait = self.GATE_WAIT_S
        if session is not None:
            try:
                wait = float(session.sysvars.get(
                    "tidb_tpu_reshard_gate_wait_ms")) / 1e3
            except Exception:  # noqa: BLE001 — default stands
                pass
        try:
            return self._gates.acquire_read([*names, CLUSTER_GATE],
                                            timeout_s=wait)
        except TimeoutError as e:
            raise ExecutionError(
                f"topology change in progress: {e}") from None

    def _owner_socket(self, smap, w: int) -> int:
        """Socket index serving a placement's worker index `w`.
        Identity except mid-drain: a table already compacted onto W-1
        workers (its map's n_workers differs from the live socket
        count) routes through the drain translation until
        remove_worker() finalizes the socket list."""
        if (self._drain_xl is not None
                and smap.n_workers != len(self._socks)):
            return self._drain_xl.get(int(w), int(w))
        return int(w)

    def _mid_reshard(self, name: str) -> bool:
        with self._placement_lock:
            return name in self._reshard_state

    def _effective_owner_workers(self, name: str, smap) -> List[int]:
        """Socket indices that may hold live rows of `name` RIGHT NOW:
        the (drain-translated) old-map owners, plus destinations of
        shards already cut over mid-reshard. This is the scan/scatter
        dispatch set while a topology change is in flight."""
        with self._placement_lock:
            rst = self._reshard_state.get(name)
            extra = ({rst["moves"][s][1]
                      for s, v in rst["shards"].items() if v == "done"}
                     if rst is not None else set())
        out = {self._owner_socket(smap, w) for w in smap.owners()}
        out |= extra
        return sorted(w for w in out if 0 <= w < len(self._socks))

    # -- distributed writes: 2PC across shard owners --------------------

    def execute_dml(self, sql: str) -> Dict[str, object]:
        """A write against a sharded table, two-phase-committed across
        the shard owners it touches: INSERT ... VALUES rows route by
        the shard key (literal rows only); UPDATE/DELETE run on every
        owner (each owns a disjoint slice, so the same statement is
        exact fleet-wide), pruned to one owner when the WHERE pins the
        shard column to a literal. During an online reshard the same
        statement ALSO lands on the staging copy of every moved shard
        still in its double-write window — riding the same 2PC, so
        both placements commit or neither. Returns {"xid", "workers"}."""
        stmts = parse(sql)
        if len(stmts) != 1:
            raise UnsupportedError("dcn dml handles a single statement")
        st = stmts[0]
        names = [st.table.name] if hasattr(st, "table") else []
        self._check_reshard_fence(names)
        gate = self._acquire_read_gate(names)
        try:
            if isinstance(st, A.InsertStmt):
                per_worker = self._route_insert(st)
            elif isinstance(st, (A.UpdateStmt, A.DeleteStmt)):
                per_worker = self._route_update_delete(st, sql)
            else:
                raise UnsupportedError(
                    "dcn dml handles INSERT ... VALUES / UPDATE / "
                    "DELETE")
            if self.dml_window_us > 0:
                return self._dml_window.submit(per_worker)
            return self._two_phase(per_worker)
        finally:
            self._gates.release_read(gate)

    def _reshard_snapshot(self, name: str):
        """Point-in-time copy of a table's reshard routing state (or
        None): (per-shard states, double-write set, moves, new map) —
        taken under the placement lock so a statement routes by ONE
        consistent view even as shards flip around it."""
        with self._placement_lock:
            rst = self._reshard_state.get(name)
            if rst is None:
                return None
            return (dict(rst["shards"]), set(rst["dw"]), rst["moves"],
                    rst["new"])

    def _route_insert(self, st) -> Dict[int, object]:
        name = st.table.name
        smap = self.placement(name)
        if smap is None:
            raise ExecutionError(
                f"no shard placement registered for {name!r}")
        if st.rows is None:
            raise UnsupportedError("dcn dml: INSERT ... SELECT")
        cols = st.columns or self._table_columns(name)
        try:
            ki = cols.index(smap.column)
        except ValueError:
            raise UnsupportedError(
                f"dcn dml: INSERT must supply shard column "
                f"{smap.column!r}")
        snap = self._reshard_snapshot(name)
        # (socket, physical table) -> VALUES tuples. Mid-reshard, a row
        # whose NEW shard already cut over routes to its new owner
        # alone; one still backfilling double-writes old owner + the
        # destination's staging copy
        groups: Dict[Tuple[int, str], List[str]] = {}
        for row in st.rows:
            if ki >= len(row):
                raise UnsupportedError("dcn dml: row narrower than the "
                                       "shard column position")
            v = _literal_int(row[ki])
            if v is _NOT_LITERAL:
                raise UnsupportedError(
                    "dcn dml: shard-key values must be integer "
                    "literals (or NULL)")
            vals = "(" + ", ".join(expr_to_sql(e) for e in row) + ")"
            w_old = self._owner_socket(
                smap, smap.worker_of(smap.shard_of(v)))
            if snap is None:
                groups.setdefault((w_old, name), []).append(vals)
                continue
            shards, dw, moves, new = snap
            s_new = new.shard_of(v)
            if shards.get(s_new) == "done":
                groups.setdefault((moves[s_new][1], name),
                                  []).append(vals)
                continue
            groups.setdefault((w_old, name), []).append(vals)
            if s_new in dw:
                groups.setdefault(
                    (moves[s_new][1], f"{name}__bf{s_new}"),
                    []).append(vals)
        collist = ""
        if st.columns:
            collist = " (" + ", ".join(f"`{c}`" for c in st.columns) + ")"
        per: Dict[int, List[str]] = {}
        for (w, tbl), vals in groups.items():
            per.setdefault(w, []).append(
                f"insert into `{tbl}`{collist} values "
                + ", ".join(vals))
        return {w: (sqls[0] if len(sqls) == 1 else sqls)
                for w, sqls in per.items()}

    def _route_update_delete(self, st, sql: str) -> Dict[int, object]:
        name = st.table.name
        smap = self.placement(name)
        if smap is None:
            raise ExecutionError(
                f"no shard placement registered for {name!r}")
        snap = self._reshard_snapshot(name)
        val, found = _shard_eq_value(getattr(st, "where", None),
                                     name, smap.column)
        per: Dict[int, List[str]] = {}

        def add(w: int, s: str) -> None:
            lst = per.setdefault(w, [])
            if s not in lst:
                lst.append(s)

        if found:
            w_old = self._owner_socket(
                smap, smap.worker_of(smap.shard_of(val)))
            if snap is None:
                add(w_old, sql)
            else:
                shards, dw, moves, new = snap
                s_new = new.shard_of(val)
                if shards.get(s_new) == "done":
                    add(moves[s_new][1], sql)
                else:
                    add(w_old, sql)
                    if s_new in dw:
                        add(moves[s_new][1], _rewrite_dml_table(
                            sql, name, f"{name}__bf{s_new}"))
        else:
            for w in smap.owners():
                add(self._owner_socket(smap, w), sql)
            if snap is not None:
                shards, dw, moves, new = snap
                for s, v in shards.items():
                    if v == "done":
                        add(moves[s][1], sql)
                for s in sorted(dw):
                    add(moves[s][1], _rewrite_dml_table(
                        sql, name, f"{name}__bf{s}"))
        return {w: (sqls[0] if len(sqls) == 1 else sqls)
                for w, sqls in per.items()}

    def _two_phase(self, per_worker: Dict[int, object]) -> Dict[str, object]:
        """PREPARE on every participant -> record the commit decision
        (the Percolator primary-write analogue; recover_txns() replays
        it) -> COMMIT everywhere. Failpoints 2pc.prepare / 2pc.commit
        sit on either side of the decision: a fault before it must
        leave every shard aborted, after it committed — never mixed.
        A per-worker value may be a LIST of statements (a coalesced
        write window, ISSUE 17): they stage inside one participant
        transaction and the whole window costs one round per shard."""
        xid = f"x{os.getpid()}-{next(_TOKEN_SEQ)}"
        parts = sorted(per_worker)
        if not parts:
            return {"xid": xid, "workers": []}
        with self._txn_lock:
            self._txn_pending[xid] = parts
        prepared: List[int] = []
        try:
            inject("2pc.prepare")
            for w in parts:
                stmts = per_worker[w]
                if isinstance(stmts, list):
                    self._call(w, {"cmd": "txn_prepare", "xid": xid,
                                   "sqls": stmts})
                else:
                    self._call(w, {"cmd": "txn_prepare", "xid": xid,
                                   "sql": stmts})
                prepared.append(w)
        except Exception:
            aborted_all = True
            # abort the ACKED participants AND the one whose prepare
            # was in flight: a lost response may have prepared it
            # server-side, and txn_abort is idempotent on the rest
            for w in parts[:len(prepared) + 1]:
                try:
                    self._call(w, {"cmd": "txn_abort", "xid": xid})
                except Exception:  # noqa: BLE001 — recover_txns owns
                    aborted_all = False  # the leftovers
            if aborted_all:
                with self._txn_lock:
                    self._txn_pending.pop(xid, None)
            raise
        # COMMIT POINT: after this record exists the txn IS committed —
        # a crash below re-drives commits from recover_txns()
        with self._txn_lock:
            self._txn_decided[xid] = parts
            self._txn_pending.pop(xid, None)
        inject("2pc.commit")
        errs = []
        for w in parts:
            try:
                self._call(w, {"cmd": "txn_commit", "xid": xid})
            except Exception as e:  # noqa: BLE001 — keep decided entry
                errs.append((w, e))
        if errs:
            # typed: the decision IS recorded, so callers (the DML
            # window especially) must never retry — that double-applies
            raise TwoPhaseCommitIncomplete(
                f"2pc commit {xid} incomplete on workers "
                f"{[w for w, _ in errs]} ({errs[0][1]}); the decision "
                "is recorded — recover_txns() finishes it")
        with self._txn_lock:
            self._txn_decided.pop(xid, None)
        return {"xid": xid, "workers": parts}

    def recover_txns(self) -> Dict[str, str]:
        """Coordinator crash recovery: re-drive COMMIT for every
        decided transaction (idempotent — workers ack unknown xids) and
        ABORT every prepared-but-undecided one. Leaves every shard
        consistent: committed-everywhere or rolled-back-everywhere."""
        with self._txn_lock:
            decided = dict(self._txn_decided)
            pending = dict(self._txn_pending)
        out: Dict[str, str] = {}
        for xid, parts in decided.items():
            ok = True
            for w in parts:
                try:
                    self._call_retry(w, {"cmd": "txn_commit",
                                         "xid": xid})
                except Exception:  # noqa: BLE001 — retry next recover
                    ok = False
            if ok:
                with self._txn_lock:
                    self._txn_decided.pop(xid, None)
                out[xid] = "committed"
        for xid, parts in pending.items():
            ok = True
            for w in parts:
                try:
                    self._call_retry(w, {"cmd": "txn_abort", "xid": xid})
                except Exception:  # noqa: BLE001 — retry next recover
                    ok = False
            if ok:
                with self._txn_lock:
                    self._txn_pending.pop(xid, None)
                out[xid] = "aborted"
        return out

    # -- online resharding (ISSUE 19) -----------------------------------

    def reshard(self, sql: str) -> None:
        """ALTER TABLE ... SHARD BY across the fleet, ONLINE:
        statements keep routing by the OLD map while every moved shard
        backfills into a staging table at its new owner; DML
        double-writes both placements per moved shard; each shard cuts
        over independently behind a brief per-table write gate, only
        after a row-count + order-independent-hash validation against
        its sources. A fault mid-cutover narrows the fence to THAT
        shard; recover_reshard() finishes the run from its per-shard
        watermark. Replica `__part` mirrors rebuild per shard, so
        failover never serves the old placement."""
        stmt = parse(sql)[0]
        if not (isinstance(stmt, A.AlterTableStmt)
                and stmt.action == "reshard"):
            raise UnsupportedError("reshard() takes ALTER ... SHARD BY")
        name = stmt.table.name
        old = self.placement(name)
        if old is None:
            raise ExecutionError(
                f"no shard placement registered for {name!r}")
        from tidb_tpu.sharding.placement import ShardMap

        kind, col, arg = stmt.shard
        W = len(self._socks)
        if kind == "range":
            new = ShardMap("range", col, len(arg) + 1, W, tuple(arg),
                           old.version + 1)
        else:
            new = ShardMap("hash", col, int(arg), W, (), old.version + 1)
        # metadata first, OUTSIDE the reshard state: every worker's
        # schema_version bumps (demoting cached plans), and a failure
        # here leaves nothing to clean up
        self.broadcast_exec(sql)
        self._online_reshard(name, new)

    def _online_reshard(self, name: str, new) -> None:
        """Shared served-through driver for reshard() and membership
        changes: register the per-shard state machine, then drive it.
        A fault BEFORE anything destructive abandons cleanly (old
        placement keeps serving, unfenced); after the first cutover
        began, state is kept for recover_reshard()."""
        from tidb_tpu.sharding import placement as pl
        from tidb_tpu.utils.metrics import RESHARD_ACTIVE

        old = self.placement(name)
        # drain translation: under remove_worker the NEW map's worker
        # indices live in the compacted space — resolve destinations to
        # live socket indices HERE (a placement-level skip test would
        # compare across the two index spaces and mis-skip)
        translate = self._drain_xl if (
            self._draining is not None
            and new.n_workers < old.n_workers) else None
        same_fn = (old.kind == new.kind and old.column == new.column
                   and old.shards == new.shards
                   and old.bounds == new.bounds)
        moves: Dict[int, Tuple[List[int], int]] = {}
        for s in range(new.shards):
            dst = pl.worker_of_shard(s, new.n_workers)
            if translate is not None:
                dst = translate.get(dst, dst)
            if same_fn:
                src = pl.worker_of_shard(s, old.n_workers)
                if src == dst:
                    continue  # same socket keeps the shard: no move
                moves[s] = ([src], dst)
            else:
                # shard function changed: any old shard can feed any
                # new one, so every old owner is a source
                moves[s] = (sorted(old.owners()), dst)
        sid = f"reshard{os.getpid()}-{next(_TOKEN_SEQ)}"
        state = {"sid": sid, "old": old, "new": new, "moves": moves,
                 "shards": {s: "pending" for s in moves},
                 "dw": set(), "xl": translate}
        with self._placement_lock:
            if name in self._reshard_state:
                raise ExecutionError(
                    f"table {name!r} is already mid-reshard")
            self._reshard_state[name] = state
        RESHARD_ACTIVE.set(1, table=name)
        try:
            self._drive_reshard(name, state)
        except Exception:
            if not self._reshard_destructive(state):
                self._abandon_reshard(name, state)
            raise
        finally:
            with self._placement_lock:
                active = name in self._reshard_state
            RESHARD_ACTIVE.set(1 if active else 0, table=name)

    @staticmethod
    def _reshard_destructive(state: Dict) -> bool:
        """True once any shard reached "cutover": sources may be
        part-purged, so the run can no longer abandon — only recover
        forward."""
        return any(v in ("cutover", "done")
                   for v in state["shards"].values())

    def _drive_reshard(self, name: str, state: Dict) -> None:
        """Advance the state machine from wherever it stands (first run
        and recover_reshard both land here): backfill every pending
        shard — the double-write window opens per shard as it stages —
        then cut each staged/stuck shard over. Validation is skipped
        for shards re-entered in "cutover": their sources may already
        be half-purged, and purge/install are idempotent."""
        for s in sorted(state["shards"]):
            if state["shards"][s] == "pending":
                self._backfill_shard(name, state, s)
        for s in sorted(state["shards"]):
            st = state["shards"][s]
            if st in ("staged", "cutover"):
                self._cutover_shard(name, state, s,
                                    validate=(st == "staged"))
        self._finalize_reshard(name, state)

    def _backfill_shard(self, name: str, state: Dict, s: int) -> None:
        """Copy shard `s`'s live rows from every source owner into the
        staging table at its destination (peer-to-peer, off the
        coordinator's wire). The table's write gate is held across
        extract + double-write enable, so the snapshot and the
        double-write stream tile EXACTLY — no statement can slip a
        write between them (the MVCC extract would miss it or the
        staging would double it)."""
        srcs, dst = state["moves"][s]
        staging = f"{name}__bf{s}"
        peers = [[h, p] for h, p in self._endpoints]
        self._gates.acquire_write(name)
        try:
            for w in srcs:
                self._call(w, {
                    "cmd": "reshard_backfill", "table": name,
                    "staging": staging, "shard": int(s),
                    "map": state["new"].to_wire(),
                    "dest": peers[dst], "dest_index": int(dst),
                    "self_index": int(w)})
            with self._placement_lock:
                state["dw"].add(s)
                state["shards"][s] = "staged"
        finally:
            self._gates.release_write(name)
        from tidb_tpu.utils.metrics import RESHARD_SHARDS_TOTAL

        RESHARD_SHARDS_TOTAL.inc(phase="backfill")

    def _cutover_shard(self, name: str, state: Dict, s: int,
                       validate: bool) -> None:
        """Flip one shard to the new placement behind the table's write
        gate: validate the staging against the sources (row count +
        order-independent hash), record the "cutover" watermark, purge
        the moved rows at the sources, install the staging rows at the
        destination, rebuild the touched replica mirrors — all in ONE
        gate hold, so no statement observes the half-swapped shard.
        Purge runs BEFORE install: when the destination is also a
        source (shard-function change), the installed rows must not be
        re-purged as "moved away"."""
        srcs, dst = state["moves"][s]
        staging = f"{name}__bf{s}"
        new_wire = state["new"].to_wire()
        self._gates.acquire_write(name)
        try:
            if validate:
                got = self._call(dst, {"cmd": "reshard_fingerprint",
                                       "table": staging})
                want_n, want_fp = 0, 0
                for w in srcs:
                    r = self._call(w, {
                        "cmd": "reshard_fingerprint", "table": name,
                        "map": new_wire, "shard": int(s)})
                    want_n += int(r["n"])
                    want_fp = (want_fp + int(r["fp"])) % (1 << 64)
                if want_n != int(got["n"]) or want_fp != int(got["fp"]):
                    raise ExecutionError(
                        f"reshard of {name!r}: shard {s} backfill "
                        f"validation failed (sources n={want_n} "
                        f"fp={want_fp:#x}, staging n={int(got['n'])} "
                        f"fp={int(got['fp']):#x}) — not cutting over")
            # WATERMARK: from here the swap is destructive. Recorded
            # BEFORE the first purge so a fault below fences exactly
            # this shard and recover_reshard() re-drives instead of
            # abandoning
            with self._placement_lock:
                state["shards"][s] = "cutover"
            inject("reshard.cutover")
            for w in srcs:
                self._call(w, {"cmd": "reshard_purge", "table": name,
                               "map": new_wire, "shard": int(s)})
            self._call(dst, {"cmd": "reshard_install", "table": name,
                             "staging": staging, "sid": state["sid"],
                             "shard": int(s)})
            with self._placement_lock:
                state["shards"][s] = "done"
                state["dw"].discard(s)
            for w in sorted({dst, *srcs}):
                self._rebuild_mirror(name, w)
        finally:
            self._gates.release_write(name)
        from tidb_tpu.utils.metrics import RESHARD_SHARDS_TOTAL

        RESHARD_SHARDS_TOTAL.inc(phase="cutover")

    def _finalize_reshard(self, name: str, state: Dict) -> None:
        """Every shard flipped: install the new map as THE placement,
        drop the run state (double-writes stop), and refresh each
        socket's owned-shard listing (the stats surface scans read)."""
        new, xl = state["new"], state["xl"]
        listing: Dict[int, List[int]] = {}
        for w_new, shs in new.owners().items():
            sock = xl.get(w_new, w_new) if xl is not None else w_new
            listing[sock] = shs
        per_bytes = self._placement_bytes.get(name, 0) // max(
            len(self._socks), 1)
        for sock in range(len(self._socks)):
            try:
                self._call(sock, {
                    "cmd": "place_shards", "table": name,
                    "shards": listing.get(sock, []),
                    "bytes": per_bytes if listing.get(sock) else 0})
            except Exception:  # noqa: BLE001 — stats-only surface;
                pass           # the placement install is what counts
        with self._placement_lock:
            self._placements[name] = new
            self._reshard_state.pop(name, None)

    def _abandon_reshard(self, name: str, state: Dict) -> None:
        """A fault before anything destructive: pop the state FIRST
        (DML stops double-writing immediately), then best-effort drop
        the staging tables. The table keeps serving the OLD placement,
        unfenced — the failed run simply never happened."""
        with self._placement_lock:
            self._reshard_state.pop(name, None)
        old_dl = getattr(self._tl, "deadline", None)
        self._tl.deadline = None
        try:
            for s, (_srcs, dst) in state["moves"].items():
                try:
                    self._call(dst, {
                        "cmd": "exec",
                        "sql": f"drop table if exists `{name}__bf{s}`"})
                except Exception:  # noqa: BLE001 — worker may be gone;
                    pass           # a later load re-clones over it
        finally:
            self._tl.deadline = old_dl

    def recover_reshard(self) -> Dict[str, str]:
        """Finish interrupted ONLINE reshards from their per-shard
        watermark: pending shards re-backfill, staged shards validate
        and cut over, shards stuck in "cutover" re-drive their
        idempotent purge/install. Tables that finish report
        'resharded'; still-failing ones stay fenced on their stuck
        shard."""
        with self._placement_lock:
            pending = dict(self._reshard_state)
        out: Dict[str, str] = {}
        for name, state in pending.items():
            try:
                self._drive_reshard(name, state)
                out[name] = "resharded"
            except Exception:  # noqa: BLE001 — stays fenced; the next
                continue       # recover_reshard() retries
        return out

    def reshard_progress_rows(self) -> List[tuple]:
        """information_schema.cluster_info rows: one per moved shard of
        every in-flight reshard (operators watch cutover progress and
        spot fenced shards), plus a fleet summary row."""
        out: List[tuple] = []
        with self._placement_lock:
            snap = {n: (st["old"].version, st["new"].version,
                        dict(st["shards"]),
                        {s: m[1] for s, m in st["moves"].items()})
                    for n, st in self._reshard_state.items()}
        drain = self._draining
        out.append(("__fleet__", -1, "serving", -1,
                    -1, -1, len(self._socks),
                    drain if drain is not None else -1))
        for name in sorted(snap):
            old_v, new_v, shards, dsts = snap[name]
            for s in sorted(shards):
                out.append((name, int(s), shards[s], int(dsts[s]),
                            int(old_v), int(new_v), len(self._socks),
                            drain if drain is not None else -1))
        return out

    def _rebuild_mirror(self, name: str, w: int) -> None:
        """Re-mirror socket `w`'s slice of `name` into its replica's
        `__part{w}` table from a fresh dump: after a cutover or a fleet
        compaction, failover must serve the NEW placement — a stale
        mirror would silently resurrect the old one."""
        rep = self.replicas.get(int(w))
        if rep is None or not (0 <= rep < len(self._socks)):
            return
        dump = self._call(int(w), {"cmd": "table_dump", "table": name})
        self._call(rep, {
            "cmd": "load_columns", "table": f"{name}__part{int(w)}",
            "like": name, "replace": True, "arrays": dump["arrays"],
            "valids": dump["valids"], "strings": dump["strings"]})

    # -- elastic membership (ISSUE 19) ----------------------------------

    def _placement_names(self) -> List[str]:
        with self._placement_lock:
            return sorted(self._placements)

    def add_worker(self, host: str, port: int) -> int:
        """Admit a new worker into the serving fleet: dial it, replay
        the DDL history so its schema matches, seed the broadcast
        tables, then rebalance every placed table onto the widened
        fleet via the online reshard path (round-robin remap — the
        co-location identity holds for the new W). Statements only
        pause for the brief CLUSTER_GATE write window that appends the
        socket; a failure during admission rolls the fleet back to W
        workers, typed — never half-admitted. Returns the new index."""
        from tidb_tpu.sharding.placement import with_n_workers
        from tidb_tpu.utils.metrics import MEMBERSHIP_TOTAL

        with self._membership_lock:
            if self._draining is not None:
                raise ExecutionError(
                    "membership change already in progress (worker "
                    f"{self._draining} is draining)")
            inject("member.join")
            sock = self._connect(host, port)
            self._gates.acquire_write(CLUSTER_GATE)
            try:
                i = len(self._socks)
                self._socks.append(sock)
                self._endpoints.append((host, port))
                self._sock_locks.append(threading.Lock())
                self._health.append(_LinkHealth())
                try:
                    self._set_state(i, UP)
                    for ddl_sql in list(self._ddl_log):
                        self._call(i, {"cmd": "exec", "sql": ddl_sql})
                    for t in sorted(self._broadcast):
                        dump = self._call(0, {"cmd": "table_dump",
                                              "table": t})
                        self._call(i, {
                            "cmd": "load_columns", "table": t,
                            "replace": True, "arrays": dump["arrays"],
                            "valids": dump["valids"],
                            "strings": dump["strings"]})
                except Exception as e:
                    self._socks.pop()
                    self._endpoints.pop()
                    self._sock_locks.pop()
                    self._health.pop()
                    try:
                        sock.close()
                    except Exception:  # noqa: BLE001 — best effort
                        pass
                    raise ExecutionError(
                        f"add_worker({host}:{port}) failed during "
                        f"admission: {e}") from e
            finally:
                self._gates.release_write(CLUSTER_GATE)
            MEMBERSHIP_TOTAL.inc(kind="join")
            # rebalance each placed table onto the widened fleet,
            # served through — the joiner starts taking real traffic
            # shard by shard as cutovers land
            for name in self._placement_names():
                smap = self.placement(name)
                if smap is not None \
                        and smap.n_workers != len(self._socks):
                    self._online_reshard(
                        name, with_n_workers(smap, len(self._socks)))
            return i

    def remove_worker(self, j: int, graceful: bool = True) -> None:
        """Drain worker `j` out of the fleet: every placed table
        reshards online onto the surviving W-1 workers (the drain
        translation keeps already-compacted maps routable mid-drain),
        then the socket list compacts under the CLUSTER_GATE write
        window and every replica mirror rebuilds against the new
        placement. RESUMABLE: a fault mid-drain (the draining worker
        dying included) degrades typed with `_draining` kept — tables
        already moved keep serving the new placement, the rest the old
        one — and a second remove_worker(j) picks up where it left
        off. graceful=False skips the data move and is refused while
        any sharded/partitioned table still places rows."""
        from tidb_tpu.sharding.placement import with_n_workers
        from tidb_tpu.utils.metrics import MEMBERSHIP_TOTAL

        with self._membership_lock:
            W = len(self._socks)
            if not (0 <= j < W):
                raise ExecutionError(f"remove_worker: no worker {j}")
            if W <= 1:
                raise ExecutionError(
                    "remove_worker: cannot remove the last worker")
            if self._draining is not None and self._draining != j:
                raise ExecutionError(
                    f"worker {self._draining} is already draining")
            placed_names = self._placement_names()
            loose = sorted(t for t in self._partitioned
                           if t not in placed_names
                           and t not in self._broadcast)
            if loose:
                # row-range tables placed by hand (load_partition) have
                # no ShardMap to drive a drain — moving them silently
                # would break the caller's explicit placement
                raise UnsupportedError(
                    f"remove_worker: table(s) {loose} are partitioned "
                    "by hand (load_partition) — move them explicitly "
                    "first")
            if not graceful and placed_names:
                raise UnsupportedError(
                    "remove_worker(graceful=False) would strand rows "
                    f"of {placed_names} — drain gracefully instead")
            inject("member.drain")
            self._draining = j
            self._drain_xl = {c: (c if c < j else c + 1)
                              for c in range(W - 1)}
            if graceful:
                for name in placed_names:
                    smap = self.placement(name)
                    if smap is not None and smap.n_workers == W:
                        self._online_reshard(
                            name, with_n_workers(smap, W - 1))
            # finalize: compact the fleet under the cluster gate (no
            # statement is mid-flight over the dying index)
            self._gates.acquire_write(CLUSTER_GATE)
            try:
                sock = self._socks.pop(j)
                self._endpoints.pop(j)
                self._sock_locks.pop(j)
                self._health.pop(j)
                self.replicas = {
                    (w if w < j else w - 1): (r if r < j else r - 1)
                    for w, r in self.replicas.items()
                    if w != j and r != j}
                self._draining = None
                self._drain_xl = None
                try:
                    if sock is not None:
                        sock.close()
                except Exception:  # noqa: BLE001 — already dead is fine
                    pass
            finally:
                self._gates.release_write(CLUSTER_GATE)
            MEMBERSHIP_TOTAL.inc(kind="remove")
            # re-mirror every owner's slice in the COMPACTED index
            # space: `__part{w}` names shifted for workers past j, and
            # a failover must serve the new placement
            for name in placed_names:
                smap = self.placement(name)
                if smap is not None:
                    for w in sorted(smap.owners()):
                        self._rebuild_mirror(name, w)

    def _shuffle_close_all(self, sid: str, targets) -> None:
        """Best-effort release of a shuffle's staged state fleet-wide
        (the statement's spent deadline must not strangle cleanup —
        same rule as _close_cursor)."""
        old_dl = getattr(self._tl, "deadline", None)
        self._tl.deadline = None
        try:
            for i in targets:
                try:
                    self._call(i, {"cmd": "shuffle_close",
                                   "shuffle_id": sid})
                except Exception:  # noqa: BLE001 — the worker may be
                    pass           # gone; its TTL reaper backstops
        finally:
            self._tl.deadline = old_dl

    # coordinator-side streaming: one page per round trip; the staging
    # table (columnar, engine-managed) is the only full-volume buffer
    PAGE_ROWS = 8192

    def _drain_pages(self, i: int, first: Dict, cancel=None) -> List[tuple]:
        """Collect one worker's partial from its first page + cursor.
        Bounded: a fetch page that comes back EMPTY while rows are still
        owed means the cursor stopped advancing (worker restarted and
        re-issued cursor ids, or evicted ours) — raise a clean error
        instead of fetching the same offset forever."""
        rows = list(first["rows"])
        cur = first.get("cursor")
        total = int(first["total"])
        while cur is not None and len(rows) < total:
            if cancel is not None:
                r = cancel()
                if r is not None:
                    raise r
            inject("dcn.coord.fetch")
            page = self._call(i, {"cmd": "fetch", "cursor": cur,
                                  "offset": len(rows),
                                  "page_rows": self.PAGE_ROWS})
            if not page:
                raise ExecutionError(
                    f"dcn worker {i}: cursor {cur} stopped advancing at "
                    f"{len(rows)}/{total} rows (restarted worker or "
                    "evicted cursor)")
            rows.extend(page)
        return rows

    def _close_cursor(self, i: int, cursor) -> None:
        """Best-effort release of a worker-held partial cursor. The
        socket state is only examined INSIDE _call's per-socket lock —
        checking it out here raced a concurrent _call marking the worker
        dead and could slip a send onto a closing socket. A dead LINK
        (worker alive, cursor pinned) reconnects via the health machine
        and releases for real; a dead WORKER fails fast once the
        circuit opens, and its restart has no cursors anyway."""
        if cursor is None:
            return
        # cleanup runs AFTER a deadline expiry too: the statement's
        # spent budget must not strangle the release RPC itself (the
        # rpc timeout still bounds it)
        old_dl = getattr(self._tl, "deadline", None)
        self._tl.deadline = None
        try:
            self._call(i, {"cmd": "close_cursor", "cursor": cursor})
        except Exception:  # noqa: BLE001 — the worker may be gone
            pass
        finally:
            self._tl.deadline = old_dl

    def _failover_partial(self, i: int, sql: str, err: Exception,
                          open_cursors: List, cancel=None,
                          tokens: Optional[List[str]] = None) -> List[tuple]:
        """A dead worker's partition re-runs on its replica (reading
        `<table>__part<i>`); the replica's cursor is tracked in
        `open_cursors` so a second failure can't leak it."""
        rep = self.replicas.get(i)
        if rep is None:
            raise err
        from tidb_tpu.utils.metrics import DCN_FAILOVER_TOTAL

        # a failover is a headline tail-sampling event: keep the trace
        # and give the re-run its own span so the assembled tree shows
        # which replica absorbed the partition
        tracing.keep("failover")
        fo_span = tracing.begin(f"dcn.failover[w{i}->w{rep}]")
        if fo_span is not None:
            fo_span.notes.append(f"cause:{type(err).__name__}")
        tables = _from_tables(parse(sql)[0].from_)
        parts = [t.name for t in tables if t.name in self._partitioned]
        tname = parts[0] if parts else tables[0].name
        rep_sql, _f, _n = partial_rewrite(
            sql, table_as=f"{tname}__part{i}",
            partitioned=self._partitioned, broadcast=self._broadcast)
        msg = {"cmd": "partial_paged", "sql": rep_sql,
               "page_rows": self.PAGE_ROWS}
        if tokens:
            # DISTINCT token: the replica may still hold its OWN
            # partition's cursor under the main token (its drain comes
            # later in the sequential pass) — reusing the token would
            # evict it mid-query. Appended to the query's token list so
            # a KILL's cancel fan-out reaches this re-run too.
            fo_token = f"{tokens[0]}-fo{i}"
            tokens.append(fo_token)
            msg["token"] = fo_token
        dl = getattr(self._tl, "deadline", None)
        if dl is not None:
            msg["deadline_s"] = max(dl - time.monotonic(), 1e-3)
        try:
            first = self._call_retry(rep, msg)
            DCN_FAILOVER_TOTAL.inc()
            ent = [rep, first.get("cursor")]
            open_cursors.append(ent)
            rows = self._drain_pages(rep, first, cancel)
            open_cursors.remove(ent)
            return rows
        finally:
            tracing.finish(fo_span)

    def cancel_token(self, token: str) -> None:
        self.cancel_tokens([token])

    def cancel_tokens(self, tokens: List[str]) -> None:
        """Tell every worker to stop the in-flight statements registered
        under `tokens` (the statement's own token plus any failover
        re-runs it spawned). Dials a FRESH connection per worker: the
        primary sockets are busy carrying the very RPCs being
        cancelled. All dials run CONCURRENTLY — a KILL must not queue
        behind connect timeouts to unreachable workers. Best effort —
        an unreachable worker has nothing running that anyone will wait
        on past its socket deadline."""
        from tidb_tpu.utils.metrics import DCN_CANCEL_TOTAL

        DCN_CANCEL_TOTAL.inc()
        # the dial threads have no tracing context of their own: hand
        # them the calling statement's trace so each worker's cancel
        # observation spans assemble under one dcn.cancel span
        tr = tracing.current()
        sp = (tr.begin("dcn.cancel", tracing.current_span_id())
              if tr is not None else None)
        dials = [threading.Thread(
            target=self._cancel_endpoint,
            args=(i, tok, tr, sp.span_id if sp is not None else None),
            daemon=True)
            for i in range(len(self._endpoints)) for tok in tokens]
        for t in dials:
            t.start()
        for t in dials:
            t.join()
        if tr is not None:
            tr.end(sp)

    def _cancel_endpoint(self, i: int, token: str, tr=None,
                         parent_id=None) -> None:
        """Best-effort cancel dial to ONE worker on a fresh connection.
        `tr`/`parent_id` (optional) carry the statement's trace: the
        cancel RPC ships trace context so the worker's observation
        (token, was-it-in-flight) comes back as a grafted span."""
        from tidb_tpu.utils.metrics import DCN_RETRY_TOTAL

        host, port = self._endpoints[i]
        sp = (tr.begin(f"dcn.cancel_dial[w{i}]", parent_id)
              if tr is not None else None)
        try:
            s = self._connect(host, port,
                              timeout=self.CANCEL_DIAL_TIMEOUT_S)
            try:
                s.settimeout(self.CANCEL_DIAL_TIMEOUT_S)
                msg = {"cmd": "cancel", "token": token}
                if tr is not None and sp is not None:
                    msg["trace_id"] = tr.trace_id
                _send(s, msg)
                resp = _recv(s)
                if tr is not None and sp is not None \
                        and isinstance(resp, dict) and resp.get("trace"):
                    tr.graft(resp["trace"], sp, proc=f"{host}:{port}")
            finally:
                s.close()
            DCN_RETRY_TOTAL.inc(kind="cancel_dial")
        except Exception:  # noqa: BLE001 — best-effort side channel
            if sp is not None:
                sp.notes.append("unreachable")
        finally:
            if tr is not None:
                tr.end(sp)

    # -- distributed planning: owner pruning + exchange choice ----------

    def _plan_query(self, sql: str, session=None) -> Dict:
        """Owner-pruned targets and (when two sharded tables join) the
        exchange plan. Placement is snapshotted HERE, at statement
        start: a reshard racing this statement bumps the map version
        but never changes routing mid-flight. The returned plan carries
        the statement's topology read-gate in "gate" — query() releases
        it when the statement finishes (a planning failure releases it
        here)."""
        st = None
        tables: List = []
        try:
            stmts = parse(sql)
            if len(stmts) == 1 and isinstance(stmts[0], A.SelectStmt):
                st = stmts[0]
                tables = _from_tables(st.from_)
        except Exception:  # noqa: BLE001 — malformed/unsupported
            st, tables = None, []  # shapes: let partial_rewrite raise
        names = [t.name for t in tables]
        self._check_reshard_fence(names)
        gate = self._acquire_read_gate(names, session)
        try:
            placed = {}
            for t in tables:
                m = self.placement(t.name)
                if m is not None and t.name not in placed:
                    placed[t.name] = m
            if st is not None and len(placed) >= 2:
                plan = self._plan_shuffle(sql, st, tables, placed)
                plan["gate"] = gate
                return plan
            partial_sql, final_sql, _names = partial_rewrite(
                sql, partitioned=self._partitioned,
                broadcast=self._broadcast,
                parsed=[st] if st is not None else None)
            targets = None
            if len(placed) == 1:
                name, smap = next(iter(placed.items()))
                targets = self._effective_owner_workers(name, smap)
                val, found = _shard_eq_value(st.where, name, smap.column)
                if found:
                    snap = self._reshard_snapshot(name)
                    if snap is not None:
                        # pinned scan mid-reshard: a cut-over shard's
                        # rows live at the new owner, everything else
                        # still serves from the old one
                        shards, _dw, moves, new = snap
                        s_new = new.shard_of(val)
                        if shards.get(s_new) == "done":
                            targets = [moves[s_new][1]]
                        else:
                            targets = [self._owner_socket(
                                smap,
                                smap.worker_of(smap.shard_of(val)))]
                    else:
                        w = self._owner_socket(
                            smap, smap.worker_of(smap.shard_of(val)))
                        if w in targets:
                            targets = [w]
                from tidb_tpu.utils.metrics import SHARD_SCAN_TOTAL

                pruned = len(targets) < len(self._socks)
                SHARD_SCAN_TOTAL.inc(pruned="yes" if pruned else "no")
            return {"partial_sql": partial_sql, "final_sql": final_sql,
                    "targets": targets, "shuffle": None, "gate": gate}
        except BaseException:
            self._gates.release_read(gate)
            raise

    def _resolve_ename(self, e: A.EName, tables, cols_by_table):
        """Base table an EName belongs to (qualifier match first, else
        the unique table carrying that column name); None = ambiguous
        or unknown."""
        if e.qualifier:
            for t in tables:
                if e.qualifier in (t.name, t.alias):
                    return t.name
            return None
        hits = [t.name for t in {t.name: t for t in tables}.values()
                if e.name in cols_by_table.get(t.name, ())]
        return hits[0] if len(hits) == 1 else None

    def _used_columns(self, st, tables, cols_by_table) -> Dict[str, List[str]]:
        """Per-table column set the query references — what an exchange
        must ship. SELECT * ships everything."""
        if any(isinstance(e, A.EStar) for e in _walk_exprs(st.items)):
            return {t.name: list(cols_by_table[t.name]) for t in tables}
        used: Dict[str, set] = {t.name: set() for t in tables}
        for e in _walk_exprs((st.items, st.where, st.group_by,
                              st.order_by, st.from_)):
            if isinstance(e, A.EName):
                owner = self._resolve_ename(e, tables, cols_by_table)
                if owner is not None \
                        and e.name in cols_by_table.get(owner, ()):
                    used[owner].add(e.name)
        return {n: [c for c in cols_by_table[n] if c in s]
                for n, s in used.items()}

    def _plan_shuffle(self, sql: str, st, tables, placed) -> Dict:
        """Exchange plan for a join of two sharded tables. Per side:
        `local` (hash-placed on its join key with shards % W == 0 —
        already co-located with the shuffle's destinations), `broadcast`
        (replicating the small side costs less than hashing both:
        small*(W-1) < big, under the broadcast byte cap), else
        `shuffle`. The broadcast-vs-shuffle choice is exactly the
        shard-map-cardinality rule ROADMAP item 2 names."""
        if len(placed) != 2:
            raise UnsupportedError(
                "dcn shuffle join supports exactly two sharded tables "
                f"(got {sorted(placed)})")
        W = len(self._socks)
        cols_by_table = {t.name: self._table_columns(t.name)
                        for t in {t.name: t for t in tables}.values()}
        keys: Dict[str, str] = {}
        for le, re_ in _equi_name_pairs(st):
            ta = self._resolve_ename(le, tables, cols_by_table)
            tb = self._resolve_ename(re_, tables, cols_by_table)
            if ta in placed and tb in placed and ta != tb:
                keys = {ta: le.name, tb: re_.name}
                break
        if not keys:
            raise UnsupportedError(
                "dcn shuffle join needs an equality condition between "
                "the two sharded tables")
        used = self._used_columns(st, tables, cols_by_table)
        with self._placement_lock:
            bytes_ = {n: self._placement_bytes.get(n, 1 << 62)
                      for n in placed}
        # plan feedback (ISSUE 15, consumer a): a previous execution of
        # this digest RECORDED each exchanged side's actual wire bytes
        # (scatter acks, summed per side). Observed bytes beat the raw
        # placement sizes — a query shipping two narrow columns of a
        # wide table can broadcast where the table's own size says
        # shuffle. The choice only picks among correct exchange plans.
        fb_digest = ""
        pversions = {n: int(getattr(placed[n], "version", 0))
                     for n in placed}
        try:
            from tidb_tpu.bindinfo import normalize_sql, sql_digest

            from tidb_tpu.planner.feedback import STORE as _fb_store

            fb_digest = sql_digest(normalize_sql(sql))
            hint = _fb_store.shuffle_hint(fb_digest, pversions)
            for n, nb in hint.items():
                if n in bytes_:
                    bytes_[n] = int(nb)
        except Exception:  # noqa: BLE001 — feedback is advisory only
            fb_digest = ""
        names = sorted(placed, key=lambda n: bytes_[n])
        small, big = names[0], names[1]
        modes: Dict[str, str] = {}
        for n in placed:
            # co-location only holds when the map was resolved against
            # the CURRENT fleet width and no shard is mid-flight to a
            # different owner (reshard/drain): otherwise re-shuffle —
            # the scatter sources below cover both placements
            if placed[n].colocated_on(keys[n]) \
                    and placed[n].n_workers == W \
                    and not self._mid_reshard(n):
                modes[n] = "local"
        if len(modes) < 2:
            if not modes and bytes_[small] <= self.BROADCAST_LIMIT_BYTES \
                    and bytes_[small] * max(W - 1, 0) < bytes_[big]:
                modes[small] = "broadcast"
                modes[big] = "local"
            else:
                for n in placed:
                    modes.setdefault(n, "shuffle")
        sid = f"sh{os.getpid()}-{next(_TOKEN_SEQ)}"
        # gather runs on every worker when a side is hash-shuffled
        # (each worker owns a hash range of the key space); with only
        # local+broadcast sides, the placed local side's owners suffice
        # — and the broadcast replicates to exactly that gather set
        if any(m == "shuffle" for m in modes.values()):
            targets = list(range(W))
        else:
            loc = next(n for n in placed if modes[n] == "local")
            targets = self._effective_owner_workers(loc, placed[loc])
        renames: Dict[str, str] = {}
        sides: List[Dict] = []
        scatter: List[Tuple[int, Dict]] = []
        peers = [[h, p] for h, p in self._endpoints]
        for n in placed:
            if modes[n] == "local":
                continue
            cols = sorted(set(used.get(n) or []) | {keys[n]})
            temp = f"__shuffle_{sid.replace('-', '_')}_{n}"
            renames[n] = temp
            sides.append({"table": n, "temp": temp, "side": n,
                          "columns": cols})
            wire_map = {"kind": "hash", "column": keys[n], "shards": W,
                        "n_workers": W, "bounds": [], "version": 0}
            for w in self._effective_owner_workers(n, placed[n]):
                msg = {"cmd": "shuffle_scatter", "shuffle_id": sid,
                       "table": n, "side": n, "columns": cols,
                       "n_workers": W, "self_index": w, "peers": peers}
                if modes[n] == "broadcast":
                    msg.update(mode="broadcast", dests=targets)
                else:
                    msg.update(mode="hash", key=keys[n], map=wire_map)
                scatter.append((w, msg))
        partial_sql, final_sql, _names = partial_rewrite(
            sql, partitioned=self._partitioned, broadcast=self._broadcast,
            renames=renames, co_partitioned=frozenset(placed),
            parsed=[st])
        from tidb_tpu.utils.metrics import SHARD_SCAN_TOTAL

        SHARD_SCAN_TOTAL.inc(
            pruned="yes" if len(targets) < W else "no")
        return {"partial_sql": partial_sql, "final_sql": final_sql,
                "targets": targets,
                "shuffle": {"id": sid, "scatter": scatter,
                            "sides": sides, "digest": fb_digest,
                            "pversions": pversions}}

    def _run_scatter(self, shuffle: Dict, cancel_reason) -> None:
        """Phase A of a shuffle query: every owner of every exchanged
        side partitions + ships its rows, concurrently. The phase is a
        BARRIER — gathers only dispatch after every scatter acked, so
        a worker's inbox provably holds its complete slice."""
        work = shuffle["scatter"]
        if not work:
            return
        with tracing.span(f"dcn.scatter[{len(work)}]"):
            errs: List[Optional[Exception]] = [None] * len(work)
            deadline = getattr(self._tl, "deadline", None)
            rpc_timeout = getattr(self._tl, "rpc_timeout", None)
            # scatter threads carry the statement's trace exactly like
            # the dispatch threads in _query_inner: without the push,
            # _call sees no trace, ships no trace_id, and the worker's
            # peer re-dispatch has no context to propagate (ISSUE 14 —
            # the envelope must survive EVERY fan-out hop)
            tr = tracing.current()
            scatter_parent = tracing.current_span_id()

            def run(j, w, msg):
                self._tl.deadline = deadline
                self._tl.rpc_timeout = rpc_timeout
                sp = None
                if tr is not None:
                    sp = tr.begin(f"dcn.scatter_send[w{w}]",
                                  scatter_parent)
                    tracing.push(tr, sp)
                try:
                    if deadline is not None:
                        # remaining budget rides the scatter twice:
                        # timeout_s bounds the worker's own peer
                        # sockets, deadline_s arms the server-side
                        # budget the worker PROPAGATES into its
                        # shuffle_stage re-sends (ISSUE 14 envelope)
                        rem = max(deadline - time.monotonic(), 1e-3)
                        msg = dict(msg, timeout_s=rem, deadline_s=rem)
                    acks[j] = self._call(w, msg)
                except Exception as e:  # noqa: BLE001
                    errs[j] = e
                    if sp is not None:
                        sp.notes.append(f"error:{type(e).__name__}")
                finally:
                    if tr is not None:
                        tracing.pop()
                        tr.end(sp)

            acks: List[Optional[Dict]] = [None] * len(work)
            threads = [threading.Thread(target=run, args=(j, w, m),
                                        daemon=True)
                       for j, (w, m) in enumerate(work)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            failed = [e for e in errs if e is not None]
            if failed:
                raise failed[0]
            r = cancel_reason()
            if r is not None:
                raise r
            # plan feedback: the scatter acks carry each owner's
            # exchange bytes — shipped wire bytes PLUS the locally
            # staged self-copy (part of the volume even though it never
            # crossed a socket). Summed per side they are what the NEXT
            # planning of this digest sizes broadcast-vs-shuffle with;
            # a broadcast ack covers len(dests) identical copies, so
            # normalize to the one-copy payload.
            digest = shuffle.get("digest")
            if digest:
                side_bytes: Dict[str, int] = {}
                for (w, msg), ack in zip(work, acks):
                    if not isinstance(ack, dict):
                        continue
                    nb = (int(ack.get("bytes") or 0)
                          + int(ack.get("local_bytes") or 0))
                    if msg.get("mode") == "broadcast":
                        nb = nb // max(len(msg.get("dests") or [1]), 1)
                    side = str(msg.get("side"))
                    side_bytes[side] = side_bytes.get(side, 0) + nb
                if side_bytes:
                    from tidb_tpu.planner.feedback import STORE as _fbs

                    _fbs.record_shuffle(digest, side_bytes,
                                        shuffle.get("pversions"))

    def query(self, sql: str, schema_sql: Optional[str] = None,
              session=None, timeout_s: Optional[float] = None,
              cancel=None) -> List[tuple]:
        """Distributed aggregate / TopN: partial on every worker, final
        merge here. schema_sql overrides the staging table DDL; by
        default column types are inferred from the partial rows.

        The merge is streaming: workers compute partials concurrently
        but hold their own result behind a cursor; the coordinator
        drains one worker at a time in PAGE_ROWS pages straight into the
        columnar staging table (bulk insert_rows, no SQL-literal round
        trip), so its transient footprint is one partition's partial —
        not the union of all of them. A worker that dies before its
        partition was ingested fails over to its replica; the final SQL
        then runs on the coordinator's own engine, whose memory tracker/
        spill machinery bounds the merge itself. The coordinator holds
        no state workers depend on, so a replacement coordinator can
        re-attach to the same workers and re-run (see
        test_dcn.py::test_coordinator_restart).

        Failure domain: `session` ties the query to a Session — its
        max_execution_time becomes the statement deadline (shipped to
        workers as each RPC's remaining budget), its
        tidb_tpu_dcn_rpc_timeout bounds each round trip, and a KILL
        QUERY/CONNECTION against it interrupts the coordinator-side
        join AND fans a cancel out to every worker. `timeout_s`
        overrides the deadline; `cancel` is an extra callable polled
        alongside. When a partition's primary AND replica are
        unreachable the query fails fast, unless partial results were
        opted into (constructor flag or tidb_tpu_dcn_partial_results) —
        then reachable partitions are served and a warning is recorded
        in `last_warnings` (and the session's warning area).

        Sharded placement (ISSUE 13): a scan of a SHARD BY table
        dispatches ONLY to the workers owning its shards (one worker
        when the WHERE pins the shard key to a literal — non-owners do
        no work, observable in their `stats` counters); a join of two
        sharded tables runs as a cross-process SHUFFLE (or broadcast,
        when the smaller side is cheaper to replicate) with the partial
        agg computed over each worker's co-partitioned slice."""
        plan = self._plan_query(sql, session)
        partial_sql, final_sql = plan["partial_sql"], plan["final_sql"]

        rpc_timeout = self.rpc_timeout_s
        budget_s = timeout_s
        partial_ok = self.partial_results
        if session is not None:
            # this call is the session's "statement": like
            # _execute_timed, entering it consumes any stale one-shot
            # KILL QUERY aimed at a PREVIOUS query
            session._kill_query = False
            to_ms = int(session.sysvars.get("tidb_tpu_dcn_rpc_timeout"))
            rpc_timeout = to_ms / 1e3 if to_ms > 0 else None
            if budget_s is None:
                met = int(session.sysvars.get("max_execution_time"))
                budget_s = met / 1e3 if met > 0 else None
            partial_ok = partial_ok or bool(
                session.sysvars.get("tidb_tpu_dcn_partial_results"))
        deadline = (time.monotonic() + budget_s
                    if budget_s is not None else None)
        token = f"q{os.getpid()}-{next(_TOKEN_SEQ)}"
        self.last_warnings = []

        def cancel_reason():
            if session is not None:
                r = session.cancel_reason()
                if r is not None:
                    return r
            if cancel is not None and cancel():
                return QueryKilledError(
                    "Query execution was interrupted (KILL)")
            if deadline is not None and time.monotonic() > deadline:
                return QueryTimeoutError(
                    "Query execution was interrupted, maximum statement "
                    "execution time exceeded")
            return None

        # this call is statement-shaped: when no trace is installed on
        # the thread (standalone Python-API use) it owns one, with the
        # same tail rules as Session._execute_timed; inside a session
        # statement it nests into the statement's trace instead
        owns_trace = tracing.current() is None
        tr = tracing.current()
        if owns_trace:
            try:
                from tidb_tpu.bindinfo import normalize_sql, sql_digest

                digest = sql_digest(normalize_sql(sql))
            except Exception:  # noqa: BLE001 — diagnostics only
                digest = ""
            rate = (float(session.sysvars.get("tidb_trace_sample_rate"))
                    if session is not None else 0.0)
            tr = tracing.Trace(tracing.make_trace_id(digest),
                               sampled=tracing.head_sampled(rate))
            tracing.push(tr)
        root_span = tracing.begin("dcn.query")
        t_q = time.perf_counter()
        err: Optional[BaseException] = None
        old_dl = getattr(self._tl, "deadline", None)
        old_to = getattr(self._tl, "rpc_timeout", None)
        self._tl.deadline = deadline
        self._tl.rpc_timeout = rpc_timeout
        shuffle = plan.get("shuffle")
        try:
            if shuffle is not None:
                self._run_scatter(shuffle, cancel_reason)
            return self._query_inner(
                sql, partial_sql, final_sql, schema_sql, session,
                deadline, rpc_timeout, token, cancel_reason, partial_ok,
                targets=plan.get("targets"),
                gather=shuffle,
                failover_ok=shuffle is None)
        except BaseException as e:
            err = e
            raise
        finally:
            if shuffle is not None:
                # release staged exchange state fleet-wide (EVERY
                # worker — a broadcast may have staged onto non-gather
                # workers) — on success the gathers already closed
                # their own; this is the error backstop (chaos grid
                # asserts zero retained)
                self._shuffle_close_all(shuffle["id"],
                                        range(len(self._socks)))
            if plan.get("gate") is not None:
                self._gates.release_read(plan["gate"])
            self._tl.deadline = old_dl
            self._tl.rpc_timeout = old_to
            self._finish_query_trace(tr, root_span, owns_trace, err,
                                     time.perf_counter() - t_q, session)

    @staticmethod
    def _finish_query_trace(tr, root_span, owns: bool, err, dur_s: float,
                            session) -> None:
        """Tail rules for a standalone Cluster.query trace (nested calls
        just close their dcn.query span — the owning statement decides)."""
        try:
            tracing.finish(root_span)
            if not owns or tr is None:
                return
            thresh_ms = (int(session.sysvars.get("tidb_slow_log_threshold"))
                         if session is not None else 300)
            cap = (int(session.sysvars.get("tidb_trace_store_capacity"))
                   if session is not None else None)
            tracing.apply_tail_rules(tr, dur_s, thresh_ms, error=err,
                                     capacity=cap)
        except Exception:  # noqa: BLE001 — diagnostics never fail a query
            pass

    def _query_inner(self, sql, partial_sql, final_sql, schema_sql,
                     session, deadline, rpc_timeout, token,
                     cancel_reason, partial_ok, targets=None,
                     gather=None, failover_ok=True) -> List[tuple]:
        # kick every TARGET worker's partial concurrently (`targets` is
        # the shard-owner set for placed tables — non-owners get NO rpc
        # and do NO work; None = the whole fleet); each returns only
        # its first page (the rest waits behind the worker's cursor).
        # The message carries the statement's REMAINING budget and the
        # cancel token so the worker enforces both server-side. With
        # `gather` set the dispatch is a shuffle_gather (same response
        # shape, cursors, and tokens as partial_paged).
        ws = list(targets) if targets is not None \
            else list(range(len(self._socks)))
        firsts: List = [None] * len(self._socks)
        errs: List = [None] * len(self._socks)
        # coordinator dispatch spans: one per worker, recorded directly
        # on the trace object (the dispatch threads install it on their
        # own thread-local context so _call's rpc spans nest under them)
        tr = tracing.current()
        dispatch_parent = tracing.current_span_id()

        def start(i):
            self._tl.deadline = deadline
            self._tl.rpc_timeout = rpc_timeout
            sp = None
            if tr is not None:
                sp = tr.begin(f"dcn.dispatch[w{i}]", dispatch_parent)
                tracing.push(tr, sp)
            msg = {"cmd": "partial_paged", "sql": partial_sql,
                   "page_rows": self.PAGE_ROWS, "token": token}
            if gather is not None:
                msg["cmd"] = "shuffle_gather"
                msg["shuffle_id"] = gather["id"]
                msg["sides"] = gather["sides"]
            if deadline is not None:
                msg["deadline_s"] = max(deadline - time.monotonic(), 1e-3)
            try:
                firsts[i] = self._call_retry(i, msg)
            except Exception as e:  # noqa: BLE001
                errs[i] = e
                if sp is not None:
                    sp.notes.append(f"error:{type(e).__name__}")
            finally:
                if tr is not None:
                    tracing.pop()
                    tr.end(sp)

        threads = [threading.Thread(target=start, args=(i,), daemon=True)
                   for i in ws]
        for t in threads:
            t.start()
        # interruptible join: a KILL (or deadline expiry) while workers
        # compute must not wait for them to finish — fan the cancel out
        # on fresh connections, then collect the (now aborting) RPCs.
        # Every RPC carries a socket deadline, so this join is bounded.
        tokens = [token]  # grows with failover re-run tokens
        interrupted = None
        cancel_sent = False
        while any(t.is_alive() for t in threads):
            interrupted = cancel_reason()
            if interrupted is not None:
                self.cancel_tokens(tokens)
                cancel_sent = True
                for t in threads:
                    t.join()
                break
            # the last thread may die between the while-check and here
            alive = next((t for t in threads if t.is_alive()), None)
            if alive is not None:
                alive.join(timeout=0.05)
        for t in threads:
            t.join()
        if interrupted is None:
            interrupted = cancel_reason()
        if interrupted is not None:
            # the dispatch may have died on its own (RPC timeouts) the
            # same instant the budget expired: the cancel must STILL fan
            # out, or a worker stalled before execution would run its
            # partial for a coordinator that already gave up
            if not cancel_sent:
                self.cancel_tokens(tokens)
            # release whatever cursors the partials managed to open
            for i, f in enumerate(firsts):
                if f is not None:
                    self._close_cursor(i, f.get("cursor"))
            raise interrupted

        s = self._merge_session
        with self._merge_lock:
            s.execute("drop table if exists __dcn_partial__")
            ddl_done = schema_sql is not None
            if ddl_done:
                s.execute(schema_sql)
            else:
                # infer column types from the union of every partition's
                # FIRST page — one partition may be all-NULL in a column
                # another types (the old all-rows inference saw everything;
                # sampling only partition 0 would mistype such columns)
                sample = [r for f in firsts if f is not None for r in f["rows"]]
                if sample:
                    s.execute(self._infer_staging_ddl(partial_sql, sample))
                    ddl_done = True
            staging = None

            def ingest(rows: List[tuple]) -> None:
                nonlocal ddl_done, staging
                if not rows:
                    return
                if not ddl_done:
                    s.execute(self._infer_staging_ddl(partial_sql, rows))
                    ddl_done = True
                if staging is None:
                    staging = s.catalog.table(s.db, "__dcn_partial__")
                for st in range(0, len(rows), 4096):
                    staging.insert_rows(rows[st: st + 4096])

            # every cursor this query opens — on primaries AND replicas — is
            # tracked here until fully drained; the finally block releases
            # whatever a failure left behind, so no worker pins a partial
            # until the TTL (one worker can hold two entries: its own
            # partition's cursor and a replica partition's)
            open_cursors: List = [[i, f["cursor"]] for i, f in enumerate(firsts)
                                  if f is not None and f.get("cursor") is not None]

            # drain one partition at a time; a partition is ingested only
            # after it arrived completely, so mid-drain failover can re-run
            # it on the replica without duplicating staged rows
            try:
                for i in ws:
                    r = cancel_reason()
                    if r is not None:
                        self.cancel_tokens(tokens)
                        raise r
                    with tracing.span(f"dcn.drain[w{i}]") as drain_sp:
                        self._drain_one(i, firsts, errs, open_cursors, sql,
                                        cancel_reason, tokens, partial_ok,
                                        session, ingest, drain_sp,
                                        failover_ok)
            finally:
                for ent in open_cursors:
                    self._close_cursor(*ent)

            if not ddl_done:
                s.execute(self._infer_staging_ddl(partial_sql, []))
            with tracing.span("dcn.final_merge"):
                return s.query(final_sql)

    def _drain_one(self, i, firsts, errs, open_cursors, sql,
                   cancel_reason, tokens, partial_ok, session, ingest,
                   drain_sp, failover_ok=True) -> None:
        """Drain worker i's partial into the staging table, failing over
        to its replica on a non-typed error (split out of _query_inner
        so each drain can carry its own trace span). `failover_ok=False`
        for shuffle gathers: the rows live only in that worker's inbox,
        so a replica re-run cannot reproduce them — fail typed."""
        try:
            if errs[i] is not None:
                raise errs[i]
            rows = self._drain_pages(i, firsts[i], cancel_reason)
            open_cursors[:] = [e for e in open_cursors if e[0] != i
                               or e[1] != firsts[i].get("cursor")]
        except (ConnectionError, OSError, ExecutionError) as e:
            if isinstance(e, (QueryKilledError, QueryTimeoutError)):
                # the statement's budget is spent / it was killed: a
                # replica re-run cannot help, and the error must keep
                # its type
                self.cancel_tokens(tokens)
                raise
            if not failover_ok:
                self.cancel_tokens(tokens)
                raise
            # the primary may be alive (coordinator-side error):
            # release its cursor before the replica re-run
            for ent in list(open_cursors):
                if firsts[i] is not None and ent[0] == i \
                        and ent[1] == firsts[i].get("cursor"):
                    self._close_cursor(*ent)
                    open_cursors.remove(ent)
            if isinstance(e, DcnRpcTimeoutError):
                # the primary is probably still EXECUTING the abandoned
                # partial: tell it to stop (and, via token poisoning,
                # not to pin a cursor if it already finished) before
                # paying the replica
                self._cancel_endpoint(i, tokens[0], tracing.current(),
                                      drain_sp.span_id
                                      if drain_sp is not None else None)
            try:
                rows = self._failover_partial(
                    i, sql, e, open_cursors, cancel_reason, tokens)
            except (ConnectionError, OSError, ExecutionError) as e2:
                if isinstance(e2, (QueryKilledError,
                                   QueryTimeoutError)):
                    self.cancel_tokens(tokens)
                    raise
                if not partial_ok:
                    raise
                # degraded mode: serve the reachable partitions
                warn = (f"dcn partition {i} unavailable (primary "
                        f"and replica): {e2}; results are PARTIAL")
                self.last_warnings.append(warn)
                if drain_sp is not None:
                    drain_sp.notes.append(f"partial_results:{warn[:120]}")
                if session is not None:
                    session._warnings.append(
                        ("Warning", 1105, warn))
                return
        ingest(rows)

    def _infer_staging_ddl(self, partial_sql: str, rows: List[tuple]) -> str:
        # column names from the partial SELECT's aliases
        items = parse(partial_sql)[0].items
        names = [it.alias for it in items]
        cols = []
        for j, name in enumerate(names):
            cols.append(f"`{name}` {_infer_type(r[j] for r in rows)}")
        return "create table __dcn_partial__ (" + ", ".join(cols) + ")"

    def worker_stats(self) -> List[Optional[Dict]]:
        """Fleet-wide failure-domain counters (executed/cancelled/
        deadline_exceeded/cancel_rpcs/pages/open_cursors per worker) —
        the kill/deadline suites assert remote partials observably
        stopped through this. Idempotent, so it rides the retry path."""
        return self._call_all([{"cmd": "stats"}] * len(self._socks),
                              idempotent=True)

    _STAT_KEYS = ("executed", "cancelled", "deadline_exceeded",
                  "cancel_rpcs", "pages", "open_cursors",
                  "shards_owned", "shard_bytes",
                  "shuffle_bytes_in", "shuffle_bytes_out")

    def worker_stats_rows(self) -> List[tuple]:
        """Row-per-worker form of worker_stats() for
        information_schema.dcn_worker_stats — gathered per worker so one
        unreachable endpoint yields a row with an error instead of
        failing the whole fleet read. Gathered CONCURRENTLY: down
        workers pay connect/rpc timeouts, and a catalog read must cost
        one timeout, not one per dead worker."""
        rows: List = [None] * len(self._endpoints)

        def gather(i: int, host: str, port: int) -> None:
            h = self._health[i]
            base = (i, f"{host}:{port}", h.state)
            try:
                st = self._call_retry(i, {"cmd": "stats"})
                rows[i] = (base
                           + tuple(int(st.get(k, 0))
                                   for k in self._STAT_KEYS)
                           + (h.reconnects, self.replicas.get(i), ""))
            except Exception as e:  # noqa: BLE001 — down worker: a row,
                rows[i] = (base + (None,) * len(self._STAT_KEYS)
                           + (h.reconnects, self.replicas.get(i),
                              f"{type(e).__name__}: {e}"))

        threads = [threading.Thread(target=gather, args=(i, host, port),
                                    daemon=True)
                   for i, (host, port) in enumerate(self._endpoints)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return rows

    def metrics_snapshots(self) -> List[tuple]:
        """(endpoint_label, metrics snapshot | None, error) per worker —
        the fleet half of the ISSUE 16 metrics plane. Gathered
        CONCURRENTLY with the worker_stats_rows discipline: one dead
        worker costs one timeout and contributes an error entry, never
        a failed scrape. Idempotent (a pure read), so it rides the
        retry path."""
        out: List = [None] * len(self._endpoints)

        def gather(i: int, host: str, port: int) -> None:
            label = f"{host}:{port}"
            try:
                snap = self._call_retry(i, {"cmd": "metrics_snapshot"})
                out[i] = (label, snap, "")
            except Exception as e:  # noqa: BLE001 — down worker: an
                out[i] = (label, None,  # error entry, not a failure
                          f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=gather, args=(i, host, port),
                                    daemon=True)
                   for i, (host, port) in enumerate(self._endpoints)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    def health_snapshot(self) -> Dict:
        """JSON-friendly view of the per-worker health machine — the
        /cluster status-port endpoint and tests read this."""
        now = time.monotonic()
        workers = []
        for i, (host, port) in enumerate(self._endpoints):
            h = self._health[i]
            workers.append({
                "index": i,
                "endpoint": f"{host}:{port}",
                "state": h.state,
                "connected": (i < len(self._socks)
                              and self._socks[i] is not None),
                "attempts": h.attempts,
                "reconnects": h.reconnects,
                "retry_in_s": round(max(h.next_retry - now, 0.0), 3),
                "last_error": h.last_error,
                "replica": self.replicas.get(i),
            })
        return {"workers": workers,
                "partitioned": sorted(self._partitioned),
                "broadcast": sorted(self._broadcast),
                "warnings": list(self.last_warnings)}

    def shutdown(self) -> None:
        prev = getattr(self._tl, "reconnect", True)
        self._tl.reconnect = False  # don't resurrect links to say goodbye
        try:
            for i in range(len(self._socks)):
                if self._socks[i] is None:
                    continue
                try:
                    self._call(i, {"cmd": "shutdown"})
                except Exception:  # noqa: BLE001 — goodbye is best
                    pass  # effort; close() below drops the link anyway
        finally:
            self._tl.reconnect = prev
        self.close()

    def close(self) -> None:
        # shutdown+close the fd FIRST, without the lock: an in-flight
        # _call stuck in a blocking recv (rpc timeout 0, no deadline)
        # HOLDS its socket lock, so taking the lock first would
        # deadlock close(). shutdown() is what actually wakes a blocked
        # recv on Linux — close() alone leaves it sleeping (same lesson
        # as the PR 4 worker-kill listener). The slot is then cleared
        # UNDER the lock — which the aborted _call has now released —
        # because the old unlocked `self._socks = []` rebind raced a
        # concurrent _call indexing into the previous list
        # (lock-discipline pass: mixed locked/unlocked mutation).
        self._closed = True  # _call refuses new RPCs/redials from here
        for i in range(len(self._socks)):
            # A _call that passed the _closed check before we set it may
            # still be mid-reconnect: the slot reads None while it dials,
            # then it installs a fresh socket and blocks in recv — all
            # while HOLDING the sock lock. So a single snapshot-then-wait
            # would block on the lock without ever waking that recv.
            # Re-shutdown whatever socket is currently installed until
            # the lock is won; shutdown on an already-dead fd is a no-op.
            while True:
                s = self._socks[i]
                if s is not None:
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        s.close()
                    except OSError:
                        pass
                if self._sock_locks[i].acquire(timeout=0.05):
                    break
            try:
                cur = self._socks[i]
                if cur is not None and cur is not s:
                    # installed between our last shutdown and winning
                    # the lock — no recv can be blocked on it (recv
                    # happens under this lock), just release the fd
                    try:
                        cur.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        cur.close()
                    except OSError:
                        pass
                # lint: disable=lock-discipline -- the lock IS held:
                # acquired above via acquire(timeout=) because a
                # blocking `with` is the close-vs-stuck-recv deadlock
                # this loop exists to break
                self._socks[i] = None
            finally:
                self._sock_locks[i].release()


def _infer_type(values) -> str:
    import re

    for v in values:
        if v is None:
            continue
        if isinstance(v, bool):
            return "boolean"
        if isinstance(v, int):
            return "bigint"
        if isinstance(v, float):
            return "double"
        if isinstance(v, datetime.datetime):
            return "datetime"
        if isinstance(v, datetime.date):
            return "date"
        if isinstance(v, str):
            m = re.fullmatch(r"-?\d+\.(\d+)", v)
            if m:  # decimal partials arrive as exact strings
                return f"decimal(18,{len(m.group(1))})"
            return "varchar(64)"
    return "bigint"


