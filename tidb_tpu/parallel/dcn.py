"""Multi-host tier: coprocessor fan-out over host RPC (ref: distsql's
per-region gRPC fan-out to TiKV coprocessors; SURVEY.md §7.6 "DCN tier +
host RPC after single-slice works").

Architecture (the reference's shape, re-mapped):

    coordinator (this process)          workers (one process per host)
    ───────────────────────────        ─────────────────────────────────
    parse + plan the query              own a row-range PARTITION of
    rewrite agg -> partial form         each table (region analogue)
    fan out partial SQL over RPC   ->   run scan+filter+partial-agg on
    merge partial states by group       their local backend (their own
    key via a final agg (MPP final      chip/mesh — the ICI tier works
    stage on the coordinator)      <-   below this one unchanged)

Partial/final split: COUNT->SUM of counts, SUM->SUM, MIN/MAX->MIN/MAX,
AVG->SUM(sum)/SUM(count). Group keys travel as decoded host values, so
workers' independent string dictionaries never need reconciling — the
same reason the reference's coprocessor returns datums, not its
storage-internal encodings.

Transport: length-prefixed pickles over TCP. Like the reference's
intra-cluster gRPC, this is a CLUSTER-INTERNAL protocol: workers
execute SQL for the coordinator by design, so it must only ever listen
inside the cluster's trust boundary (loopback/private network).

Failure handling mirrors the reference's region-error model: a worker
RPC failure fails the query with a diagnosable error (retry/replica
logic would slot in at Cluster._call)."""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

from tidb_tpu.errors import ExecutionError, UnsupportedError
from tidb_tpu.parser import ast as A
from tidb_tpu.parser import parse
from tidb_tpu.parser.printer import expr_to_sql

__all__ = ["Worker", "Cluster", "partial_rewrite"]

_LEN = struct.Struct(">I")


def _send(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv(sock: socket.socket):
    hdr = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(hdr)
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------


class Worker:
    """One host's coprocessor service: a Session over its partition."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from tidb_tpu.session import Session

        self.session = Session()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(4)
        self._running = False

    def serve_forever(self) -> None:
        self._running = True
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                msg = _recv(conn)
                try:
                    _send(conn, {"ok": True, "result": self._handle(msg)})
                except Exception as e:  # noqa: BLE001 — error travels back
                    _send(conn, {"ok": False,
                                 "error": f"{type(e).__name__}: {e}"})
                if msg.get("cmd") == "shutdown":
                    self._running = False
                    try:
                        # close() alone doesn't wake a thread blocked in
                        # accept() on Linux; shutdown() does
                        self._sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    self._sock.close()
                    return
        except (ConnectionError, OSError):
            pass

    def _handle(self, msg: Dict):
        cmd = msg["cmd"]
        if cmd == "ping":
            return "pong"
        if cmd == "exec":
            rs = self.session.execute(msg["sql"])
            return rs.rows if rs is not None else None
        if cmd == "load_columns":
            table = self.session.catalog.table("test", msg["table"])
            return table.insert_columns(
                msg.get("arrays") or {}, msg.get("valids"),
                strings=msg.get("strings"))
        if cmd == "partial":
            rs = self.session.execute(msg["sql"])
            return rs.rows
        if cmd == "shutdown":
            return "bye"
        raise ExecutionError(f"unknown dcn command {cmd!r}")


def worker_main(argv=None) -> None:  # pragma: no cover - subprocess entry
    """python -m tidb_tpu.parallel.dcn [--port N]; prints the bound port."""
    import argparse
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--device", default=None,
                    help="force a jax platform (e.g. cpu) before serving")
    args = ap.parse_args(argv)
    if args.device:
        import jax

        jax.config.update("jax_platforms", args.device)
    w = Worker(args.host, args.port)
    print(f"DCN_WORKER_PORT={w.port}", flush=True)
    sys.stdout.flush()
    w.serve_forever()


if __name__ == "__main__":  # pragma: no cover
    worker_main()


# ---------------------------------------------------------------------------
# partial/final rewrite
# ---------------------------------------------------------------------------

_DIST_AGGS = {"count", "sum", "min", "max", "avg"}


def partial_rewrite(sql: str) -> Tuple[str, str, List[str]]:
    """One single-table aggregate SELECT -> (partial_sql, final_sql,
    out_names). partial_sql runs on every worker; its result rows are
    unioned into the staging table __dcn_partial__ on the coordinator,
    where final_sql computes the merge (incl. HAVING-free ORDER BY /
    LIMIT from the original)."""
    stmts = parse(sql)
    if len(stmts) != 1 or not isinstance(stmts[0], A.SelectStmt):
        raise UnsupportedError("dcn tier handles a single SELECT")
    st = stmts[0]
    if not isinstance(st.from_, A.TableName) or st.having is not None \
            or st.distinct or st.ctes:
        raise UnsupportedError(
            "dcn tier pushes single-table aggregates (the coprocessor "
            "shape); joins execute above it")

    group_sqls = [expr_to_sql(g) for g in st.group_by]
    part_items: List[str] = []
    final_items: List[str] = []
    out_names: List[str] = []
    gcol: Dict[str, str] = {}
    for i, g in enumerate(group_sqls):
        gname = f"g{i}"
        part_items.append(f"{g} as {gname}")
        gcol[g] = gname

    for i, item in enumerate(st.items):
        e = item.expr
        alias = item.alias or (
            e.name if isinstance(e, A.EName) else f"col{i}")
        out_names.append(alias)
        esql = expr_to_sql(e)
        if esql in gcol:  # a group-by column in output position
            final_items.append(f"{gcol[esql]} as `{alias}`")
            continue
        if not (isinstance(e, A.EFunc) and e.name in _DIST_AGGS):
            raise UnsupportedError(
                f"dcn output must be group columns or plain aggregates, got {esql}")
        if e.distinct:
            raise UnsupportedError("dcn tier: DISTINCT aggregates")
        argsql = expr_to_sql(e.args[0]) if e.args else "*"
        if e.name == "count":
            part_items.append(f"count({argsql}) as p{i}")
            final_items.append(f"sum(p{i}) as `{alias}`")
        elif e.name in ("sum", "min", "max"):
            part_items.append(f"{e.name}({argsql}) as p{i}")
            final_items.append(f"{e.name}(p{i}) as `{alias}`")
        else:  # avg = sum of sums / sum of counts
            part_items.append(f"sum({argsql}) as p{i}s")
            part_items.append(f"count({argsql}) as p{i}c")
            final_items.append(f"sum(p{i}s) / sum(p{i}c) as `{alias}`")

    tname = st.from_.name
    where = f" where {expr_to_sql(st.where)}" if st.where is not None else ""
    groupby = f" group by {', '.join(group_sqls)}" if group_sqls else ""
    partial_sql = (f"select {', '.join(part_items)} from `{tname}`"
                   f"{where}{groupby}")

    fgroup = f" group by {', '.join(gcol.values())}" if gcol else ""
    order = ""
    if st.order_by:
        terms = []
        for o in st.order_by:
            osql = expr_to_sql(o.expr)
            if isinstance(o.expr, A.EName) and o.expr.qualifier is None \
                    and o.expr.name in out_names:
                ref = f"`{o.expr.name}`"
            elif osql in gcol:
                ref = gcol[osql]
            else:
                raise UnsupportedError(
                    "dcn ORDER BY must reference output aliases or group columns")
            terms.append(ref + (" desc" if o.desc else ""))
        order = " order by " + ", ".join(terms)
    limit = f" limit {st.limit}" if st.limit is not None else ""
    offset = f" offset {st.offset}" if st.offset is not None else ""
    final_sql = (f"select {', '.join(final_items)} from `__dcn_partial__`"
                 f"{fgroup}{order}{limit}{offset}")
    return partial_sql, final_sql, out_names


# ---------------------------------------------------------------------------
# coordinator
# ---------------------------------------------------------------------------


class Cluster:
    """Coordinator-side handle on the worker fleet."""

    def __init__(self, endpoints: List[Tuple[str, int]]):
        self._socks: List[socket.socket] = []
        for host, port in endpoints:
            s = socket.create_connection((host, port), timeout=30)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks.append(s)
        from tidb_tpu.session import Session

        self._merge_session = Session()

    def __len__(self):
        return len(self._socks)

    def _call(self, i: int, msg: Dict):
        sock = self._socks[i]
        _send(sock, msg)
        resp = _recv(sock)
        if not resp["ok"]:
            raise ExecutionError(f"dcn worker {i}: {resp['error']}")
        return resp["result"]

    def _call_all(self, msgs: List[Dict]) -> List:
        """One message per worker, dispatched concurrently."""
        results: List = [None] * len(self._socks)
        errors: List = []

        def run(i):
            try:
                results[i] = self._call(i, msgs[i])
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(self._socks))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return results

    def broadcast_exec(self, sql: str) -> None:
        self._call_all([{"cmd": "exec", "sql": sql}] * len(self._socks))

    def load_partition(self, worker: int, table: str, arrays=None,
                       valids=None, strings=None) -> int:
        return self._call(worker, {
            "cmd": "load_columns", "table": table, "arrays": arrays,
            "valids": valids, "strings": strings,
        })

    def query(self, sql: str, schema_sql: Optional[str] = None) -> List[tuple]:
        """Distributed aggregate: partial on every worker, final merge
        here. schema_sql overrides the staging table DDL; by default
        column types are inferred from the partial rows."""
        partial_sql, final_sql, _names = partial_rewrite(sql)
        worker_rows = self._call_all(
            [{"cmd": "partial", "sql": partial_sql}] * len(self._socks))
        all_rows = [r for rows in worker_rows for r in rows]
        s = self._merge_session
        s.execute("drop table if exists __dcn_partial__")
        if schema_sql is not None:
            s.execute(schema_sql)
        else:
            s.execute(self._infer_staging_ddl(partial_sql, all_rows))
        if all_rows:
            # batched inserts through the coordinator's own SQL surface
            for start in range(0, len(all_rows), 512):
                chunk = all_rows[start : start + 512]
                vals = ", ".join(
                    "(" + ", ".join(_sql_literal(v) for v in r) + ")"
                    for r in chunk)
                s.execute(f"insert into __dcn_partial__ values {vals}")
        return s.query(final_sql)

    def _infer_staging_ddl(self, partial_sql: str, rows: List[tuple]) -> str:
        # column names from the partial SELECT's aliases
        items = parse(partial_sql)[0].items
        names = [it.alias for it in items]
        cols = []
        for j, name in enumerate(names):
            cols.append(f"`{name}` {_infer_type(r[j] for r in rows)}")
        return "create table __dcn_partial__ (" + ", ".join(cols) + ")"

    def shutdown(self) -> None:
        for i in range(len(self._socks)):
            try:
                self._call(i, {"cmd": "shutdown"})
            except Exception:  # noqa: BLE001
                pass
        self.close()

    def close(self) -> None:
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        self._socks = []


def _infer_type(values) -> str:
    import datetime
    import re

    for v in values:
        if v is None:
            continue
        if isinstance(v, bool):
            return "boolean"
        if isinstance(v, int):
            return "bigint"
        if isinstance(v, float):
            return "double"
        if isinstance(v, datetime.datetime):
            return "datetime"
        if isinstance(v, datetime.date):
            return "date"
        if isinstance(v, str):
            m = re.fullmatch(r"-?\d+\.(\d+)", v)
            if m:  # decimal partials arrive as exact strings
                return f"decimal(18,{len(m.group(1))})"
            return "varchar(64)"
    return "bigint"


def _sql_literal(v) -> str:
    import datetime

    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, (datetime.date, datetime.datetime)):
        return "'" + str(v) + "'"
    return "'" + str(v).replace("'", "''") + "'"
