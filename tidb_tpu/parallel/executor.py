"""Distributed executors: plug mesh fragments into the Volcano tree.

build_dist_executor mirrors executor/builder.py but intercepts plan
shapes that can run as one collective fragment across the mesh:

  * HashAgg(segment) over fused Selection/Projection stages on one scan
    -> dist_agg_fragment (scan+filter+partial agg per shard, psum merge)
  * HashAgg(segment) over Join(scan-side, scan-side) with int equi-keys
    -> dist_join_agg_fragment (all_to_all repartition + local join)

Anything else falls back to the single-chip executors — exactly how the
reference falls back from coprocessor pushdown to root-task execution
when a subtree isn't pushable (ref: planner "cop task" vs "root task").
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from tidb_tpu.utils.lru import get_or_build, touch


from tidb_tpu.errors import ExecutionError
from tidb_tpu.executor.aggregate import HashAggExec
from tidb_tpu.executor.builder import build_executor, peel_stages, scan_stages_for
from tidb_tpu.executor.base import Executor, raise_if_cancelled
from tidb_tpu.executor.scan import ProjectionExec, SelectionExec
from tidb_tpu.executor.sort import LimitExec, SortExec, TopNExec
from tidb_tpu.parallel.distsql import make_agg_fragment, make_join_agg_fragment
from tidb_tpu.parallel.fragment import BROADCAST_LIMIT, compile_fragment
from tidb_tpu.parallel.mesh import dcn_axis, shard_axis
from tidb_tpu.parallel.partition import ShardedTable, shard_table
from tidb_tpu.planner.physical import (
    PHashAgg,
    PHashJoin,
    PLimit,
    PProjection,
    PScan,
    PSelection,
    PSort,
    PTopN,
    PhysicalPlan,
)

__all__ = ["ShardCache", "build_dist_executor", "DistAggExec", "DistJoinAggExec"]


def _note_fragment(exec_, kind: str, n_parts: int, t0: float) -> None:
    """Record one fragment dispatch: the FRAGMENT_SECONDS collector for
    /metrics (with a trace_id exemplar) and a span on the statement's
    trace that TRACE/the trace store render with a real start offset.
    Wall time covers launch plus any synchronous trace/compile (jax
    dispatch is async — device busy time is not host observable without
    forcing a sync, which tracing must not pay for). One call is one
    fragment execution, so the dispatch counter lives here too — the
    count and the histogram can never desynchronize."""
    from tidb_tpu.utils import tracing
    from tidb_tpu.utils.metrics import FRAGMENT_DISPATCH, FRAGMENT_SECONDS

    dt = time.perf_counter() - t0
    FRAGMENT_DISPATCH.inc(kind=kind)
    tr = tracing.current()
    if tr is not None:
        tr.add_complete(f"fragment.{kind}[parts={n_parts}]", t0, dt,
                        parent_id=tracing.current_span_id())
    FRAGMENT_SECONDS.observe(dt, kind=kind)


def _timed_combine(sig, state, part):
    """Merge two per-shard collective states, timing the host-driven
    merge into COLLECTIVE_MERGE_SECONDS."""
    from tidb_tpu.utils.metrics import COLLECTIVE_MERGE_SECONDS

    t0 = time.perf_counter()
    out = _segment_state_combine(sig)(state, part)
    COLLECTIVE_MERGE_SECONDS.observe(time.perf_counter() - t0)
    return out


class ShardCache:
    """(table identity, version) -> ShardedTable. The region-cache analogue:
    invalidated by table mutation (version bump), not by epoch.

    The entry pins the host table object so a recycled id() can never alias
    a different table; a small LRU bounds how many dead tables' [P,R]
    device copies can stay resident after drops/replacements. Also caches
    compiled collective fragments (keyed by plan signature) — shard_map
    closures recompile per jit identity, and a repeated query must not pay
    XLA compilation twice — and the proven exchange growth per join
    signature so skewed joins don't re-run known-overflowing fragments."""

    MAX_TABLES = 16
    MAX_FRAGMENTS = 128

    def __init__(self, mesh):
        self.mesh = mesh
        self._cache: "OrderedDict[int, Tuple[object, int, ShardedTable]]" = OrderedDict()
        self.fragments: "OrderedDict[object, object]" = OrderedDict()
        # bounded with fragments' LRU discipline: one entry per join
        # signature+data version, pruned opportunistically
        self.growth: "OrderedDict[object, float]" = OrderedDict()

    def get(self, table, encode: bool = False) -> ShardedTable:
        hit = self._cache.get(id(table))
        if hit is not None:
            held, version, enc0, st = hit
            if held is table and version == table.version \
                    and enc0 == encode:
                self._cache.move_to_end(id(table))
                return st
        st = shard_table(table, self.mesh, encode=encode)
        self._cache[id(table)] = (table, table.version, encode, st)
        self._cache.move_to_end(id(table))
        while len(self._cache) > self.MAX_TABLES:
            self._cache.popitem(last=False)
        return st

    def evict(self, table) -> None:
        """Drop a table's resident sharding (e.g. it grew past the
        device-cache budget and the streaming path takes over)."""
        self._cache.pop(id(table), None)

    def get_fragment(self, key, build):
        fn = get_or_build(self.fragments, key, build, self.MAX_FRAGMENTS)
        # fragments trace lazily on first call, under the glue's
        # host-CPU default-device pin — pin the Pallas target to the
        # mesh's real platform for every dispatch (ops.force_platform)
        from tidb_tpu.ops import force_platform

        platform = self.mesh.devices.flat[0].platform

        def dispatch(*args):
            from tidb_tpu.utils import dispatch as dsp

            dsp.record(site="fragment")
            with force_platform(platform):
                return fn(*args)

        return dispatch

    def get_growth(self, gkey) -> float:
        g = self.growth.get(gkey)
        if g is None:
            return 2.0
        self.growth.move_to_end(gkey)
        return g

    def put_growth(self, gkey, growth: float) -> None:
        touch(self.growth, gkey, growth, self.MAX_FRAGMENTS)


def _segment_state_combine(sig):
    """Jitted elementwise merge of two segment-state dicts (sum/min/max
    per key via merge_op_for) — shared by every streaming path."""
    from tidb_tpu.executor.aggregate import merge_op_for
    from tidb_tpu.utils.jitcache import cached_jit

    def build():
        def combine(s1, s2):
            out = {}
            for k, v in s1.items():
                op = merge_op_for(k)
                if op == "sum":
                    out[k] = v + s2[k]
                elif op == "min":
                    out[k] = jnp.minimum(v, s2[k])
                else:
                    out[k] = jnp.maximum(v, s2[k])
            return out

        return combine

    return cached_jit("aggcombine", repr(sig), build)


def _types_sig(st: ShardedTable) -> str:
    """Schema signature of a sharding: the compiled fragments close over
    st.types (column name -> SQLType), so the cache key must distinguish
    shardings by it — but nothing else."""
    return repr(sorted((n, t) for n, t in st.types.items()))


# single-CPU-backend routing threshold: fragments whose largest base
# table is below this run on the device path even without an accelerator
# (XLA fusion amortizes); above it, sort-bound joins/generic aggs go to
# the numpy host engine, which wins 2-3x there
def _collapse_to_scan(plan: PhysicalPlan):
    """Fuse Selection/Projection chain onto a single scan; return
    (scan, stages) or None if the subtree isn't a pushable pipeline."""
    stages, base = peel_stages(plan)
    if not isinstance(base, PScan) or base.table is None:
        return None
    return base, scan_stages_for(base, stages)


def _uid_map(scan: PScan) -> Dict[str, str]:
    return {c.name: c.uid for c in scan.schema}


class DistAggExec(HashAggExec):
    """Segment agg whose input is a sharded scan fragment on the mesh."""

    def __init__(self, plan: PHashAgg, scan: PScan, stages, cache: ShardCache):
        super().__init__(plan.schema, None, plan.group_exprs, plan.group_uids,
                         plan.aggs, "segment",
                         segment_sizes=getattr(plan, "segment_sizes", None))
        self.children = []
        self._scan = scan
        self._stages = stages
        self._cache = cache

    # per-shard staging batch for the >HBM streaming path (rows; the
    # batch buffer is P * this many rows of the scanned columns)
    STREAM_ROWS_PER_PART = 1 << 20

    def _run_segment(self):
        from tidb_tpu.parallel.partition import table_bytes

        sizes = self.segment_sizes or []
        domains = [s + 1 for s in sizes]
        table = self._scan.table
        scan_cols = [c.name for c in self._scan.schema]
        # gate on the FULL table size: the resident path shards every
        # column; streaming then stages only the scanned columns
        if table_bytes(table) > self.ctx.device_cache_bytes:
            self._cache.evict(table)  # drop any stale resident sharding
            self._run_segment_streaming(domains, scan_cols)
            return
        # resident sharding stages ONCE and is dispatched many times:
        # FoR-encoding it would charge the in-program decode to every
        # warm execution (measured 3.5x on warm Q1) for a one-time
        # transfer saving. Encoded staging pays on the STREAMING paths,
        # where the bytes move on every batch.
        st = self._cache.get(table)
        # keyed on schema signature, NOT data identity: the compiled fragment
        # is a pure function of plan + shapes + column types (arrays are
        # arguments), so version bumps with unchanged schema reuse it
        key = ("agg", repr((self._stages, self.group_exprs, self.aggs, domains)),
               st.n_parts, st.rows_per_part, _types_sig(st))
        fn = self._cache.get_fragment(
            key,
            lambda: make_agg_fragment(st, self._stages, self.group_exprs,
                                      self.aggs, domains, uid_map=_uid_map(self._scan)),
        )
        t0 = time.perf_counter()
        state = fn(st.data, st.valid, st.sel, st.refs)
        _note_fragment(self, "scan_agg", st.n_parts, t0)
        self._finalize_segment_state(state, domains)

    def _run_segment_streaming(self, domains, scan_cols):
        """>HBM tables: stream fixed [P, R] staging batches through the
        (once-compiled) partial-agg fragment, combining the replicated
        [G] states on device; one fetch at the end. jax's async dispatch
        overlaps batch k's compute with batch k+1's host staging (the
        IndexLookUp double-pipeline analogue)."""
        from tidb_tpu.parallel.partition import stream_batches

        table = self._scan.table
        mesh = self._cache.mesh
        sig = repr((self._stages, self.group_exprs, self.aggs, domains))
        state = None
        fn = None
        enc = bool(getattr(self.ctx, "stage_encoded", True))
        for st in stream_batches(table, mesh, scan_cols,
                                 self.STREAM_ROWS_PER_PART, encode=enc):
            raise_if_cancelled(self.ctx)  # see _run_fragment_streaming
            if fn is None:
                key = ("agg", sig, st.n_parts, st.rows_per_part,
                       _types_sig(st), "stream")
                fn = self._cache.get_fragment(
                    key,
                    lambda st=st: make_agg_fragment(
                        st, self._stages, self.group_exprs, self.aggs,
                        domains, uid_map=_uid_map(self._scan)),
                )
            t0 = time.perf_counter()
            part = fn(st.data, st.valid, st.sel, st.refs)
            _note_fragment(self, "scan_agg_stream", st.n_parts, t0)
            state = part if state is None else _timed_combine(
                sig, state, part)
        self._finalize_segment_state(state, domains)


class DistJoinAggExec(HashAggExec):
    """Segment agg over a repartition join of two sharded scans."""

    def __init__(self, plan: PHashAgg, join: PHashJoin,
                 probe_scan, probe_stages, build_scan, build_stages,
                 post_stages, cache: ShardCache):
        super().__init__(plan.schema, None, plan.group_exprs, plan.group_uids,
                         plan.aggs, "segment",
                         segment_sizes=getattr(plan, "segment_sizes", None))
        self.children = []
        self._plan = plan
        self._delegate = None
        self._join = join
        self._probe_scan, self._probe_stages = probe_scan, probe_stages
        self._build_scan, self._build_stages = build_scan, build_stages
        self._post_stages = post_stages
        self._cache = cache

    def next(self):
        if self._delegate is not None:
            return self._delegate.next()
        return super().next()

    def close(self):
        if self._delegate is not None:
            self._delegate.close()
            self._delegate = None
        super().close()

    def _run_segment(self):
        from tidb_tpu.parallel.partition import table_bytes

        sizes = self.segment_sizes or []
        domains = [s + 1 for s in sizes]
        join = self._join
        if max(table_bytes(self._probe_scan.table),
               table_bytes(self._build_scan.table)) > self.ctx.device_cache_bytes:
            # >HBM side: the general fragment path streams it in fixed
            # [P, R] batches; this resident fast path cannot
            mesh = self._cache.mesh
            prog = compile_fragment(
                self._plan, mesh, int(np.prod(list(mesh.shape.values()))))
            if prog is not None:
                d = DistFragmentExec(self._plan, prog, self._cache)
            else:
                # never shard an over-budget table resident: the host
                # executors stream chunk-wise within the budget
                d = build_executor(self._plan)
            d.open(self.ctx)
            self._delegate = d
            return
        probe_idx = 1 - join.build_side
        probe_keys = join.eq_left if probe_idx == 0 else join.eq_right
        build_keys = join.eq_right if join.build_side == 1 else join.eq_left
        probe_st = self._cache.get(self._probe_scan.table)
        build_st = self._cache.get(self._build_scan.table)
        sig = repr((self._probe_stages, self._build_stages, probe_keys[0],
                    build_keys[0], self._post_stages, self.group_exprs,
                    self.aggs, domains))
        # start from the growth that last worked for this signature on this
        # data version so a skewed join doesn't replay its known-overflowing
        # fragments; keyed on serials so it resets when the data changes
        gkey = (sig, probe_st.serial, build_st.serial)
        growth = self._cache.get_growth(gkey)
        while growth <= 16.0:
            key = ("joinagg", sig, growth, probe_st.n_parts,
                   probe_st.rows_per_part, build_st.rows_per_part,
                   _types_sig(probe_st), _types_sig(build_st))
            fn = self._cache.get_fragment(
                key,
                lambda: make_join_agg_fragment(
                    probe_st, build_st,
                    self._probe_stages, self._build_stages,
                    probe_keys[0], build_keys[0],
                    _uid_map(self._probe_scan), _uid_map(self._build_scan),
                    self._post_stages, self.group_exprs, self.aggs, domains,
                    growth=growth,
                ),
            )
            t0 = time.perf_counter()
            state, ovf = fn(probe_st.data, probe_st.valid, probe_st.sel,
                            probe_st.refs,
                            build_st.data, build_st.valid, build_st.sel,
                            build_st.refs)
            # host-sync: one scalar per dispatch — the exchange
            # overflow count decides the grow-and-retry loop
            if int(ovf) == 0:
                _note_fragment(self, "join_agg", probe_st.n_parts, t0)
                self._cache.put_growth(gkey, growth)
                break
            growth *= 2  # skewed exchange: retry with bigger buckets
        else:
            raise ExecutionError("join exchange overflow persisted at growth=16x")
        self._finalize_segment_state(state, domains)


class _BroadcastTooLarge(Exception):
    def __init__(self, rows):
        super().__init__(f"broadcast side too large ({rows} rows)")


class DistFragmentExec(HashAggExec):
    """Agg root over a general compiled fragment (parallel/fragment.py):
    join trees, broadcast build sides, segment or generic aggregation —
    one shard_map dispatch per execution, with per-knob capacity retry."""

    # "compact" knobs have no ceiling: their cap is min'd against the
    # static capacity inside the fragment, so growth converges to a no-op
    # in O(log) retries even from a wildly wrong estimate. "expand" jumps
    # to the exact reported factor (never speculative), and a compacted
    # probe side legitimately inflates the factor — the ceiling only
    # guards against compiling absurd buffers for pathological skew.
    MAX_GROWTH = {"exch": 64.0, "expand": 65536.0, "compact": float("inf")}

    def __init__(self, plan: PHashAgg, prog, cache: ShardCache):
        super().__init__(plan.schema, None, plan.group_exprs, plan.group_uids,
                         plan.aggs, plan.strategy,
                         segment_sizes=getattr(plan, "segment_sizes", None))
        self.children = []
        self._plan = plan
        self._prog = prog
        self._cache = cache
        self._delegate = None

    def _run_segment(self):
        self._run_fragment()

    def _run_generic(self):
        self._run_fragment()



    def next(self):
        if self._delegate is not None:
            return self._delegate.next()
        return super().next()

    def close(self):
        if self._delegate is not None:
            self._delegate.close()
            self._delegate = None
        super().close()

    def _fall_back_single_chip(self):
        """Pathological skew blew every capacity retry: run the plan on
        the single-chip executors instead of failing the query (the
        reference's root-task fallback)."""
        root = build_executor(self._plan)
        root.open(self.ctx)
        self._delegate = root

    # ------------------------------------------------------------------

    def _gather_broadcasts(self, prog):
        """Materialize every broadcast subtree; returns (args, shapes).
        A subtree too large to replicate raises _BroadcastTooLarge; the
        fragment runners catch it and fall back to single-chip execution
        like every other unsupported shape (round-2 review weak #6 — it
        used to be a hard error telling the user to flip a sysvar)."""
        args, shapes = [], []
        limit = getattr(self.ctx, "broadcast_rows_limit", BROADCAST_LIMIT)
        for bc in prog.broadcasts:
            data, valid, sel, n = self._materialize_broadcast(bc)
            if n > limit:
                raise _BroadcastTooLarge(n)
            args += [data, valid, sel]
            shapes.append(len(sel))
        return args, shapes

    @staticmethod
    def _iter_host_parts(host):
        """Split a fetched [n_parts * S] group-table dict into per-part
        tables; yields (part_index, table_dict) for non-empty parts."""
        n_per = np.asarray(host["n"]).reshape(-1)
        n_parts = len(n_per)
        for p in range(n_parts):
            if n_per[p] == 0:
                continue
            t = {"n": n_per[p]}
            for name, arr in host.items():
                if name == "n":
                    continue
                S = len(arr) // n_parts
                t[name] = arr[p * S:(p + 1) * S]
            yield p, t

    def _materialize_broadcast(self, bc):
        """Run a non-scan subtree and return replicated (data, valid, sel)
        arrays — the broadcast exchange input. The subtree itself runs
        through the distributed builder, so an agg-rooted build side (a
        HAVING subquery, say) executes as a mesh fragment instead of a
        single-chip pass over the whole table."""
        root = build_dist_executor(bc.plan, self._cache)
        datas = {c.uid: [] for c in bc.schema}
        valids = {c.uid: [] for c in bc.schema}
        n = 0
        try:
            root.open(self.ctx)
            for ch in root.chunks():
                sel = np.asarray(ch.sel)
                live = np.nonzero(sel)[0]
                n += len(live)
                for c in bc.schema:
                    col = ch.columns[c.uid]
                    datas[c.uid].append(np.asarray(col.data)[live])
                    valids[c.uid].append(np.asarray(col.valid)[live])
        finally:
            root.close()
        # pad to pow2 so repeated executions reuse compiled shapes
        cap = 1
        while cap < max(n, 1):
            cap *= 2
        data, valid = {}, {}
        for c in bc.schema:
            d = (np.concatenate(datas[c.uid]) if datas[c.uid]
                 else np.zeros(0, dtype=c.type_.np_dtype))
            v = (np.concatenate(valids[c.uid]) if valids[c.uid]
                 else np.zeros(0, dtype=np.bool_))
            db = np.zeros(cap, dtype=d.dtype)
            vb = np.zeros(cap, dtype=np.bool_)
            db[:n], vb[:n] = d, v
            data[c.uid], valid[c.uid] = db, vb
        sel = np.zeros(cap, dtype=np.bool_)
        sel[:n] = True
        return data, valid, sel, n

    def _pick_stream_source(self, prog):
        """Index of the source to stream, or None. A table above the
        device-cache budget streams in fixed [P, R] batches IF it
        appears exactly once among the fragment's sources — a self-join
        of a streamed table would pair only same-batch rows. Running
        the fragment per batch is otherwise sound: probe rows partition
        across batches (each contributes once), build/broadcast sides
        are identical every batch, and the agg outputs merge (segment:
        state merge; generic: per-part table merge)."""
        from tidb_tpu.parallel.partition import table_bytes

        best, best_bytes = None, 0
        for i, src in enumerate(prog.sources):
            if i in prog.stream_unsafe:
                continue
            b = table_bytes(src.scan.table)
            if b > self.ctx.device_cache_bytes and b > best_bytes:
                best, best_bytes = i, b
        if best is None:
            return None
        t = prog.sources[best].scan.table
        if sum(1 for s in prog.sources if s.scan.table is t) != 1:
            return None  # self-join of the big table: no streaming
        return best

    def _run_fragment(self):
        import jax

        prog = self._prog
        stream_idx = self._pick_stream_source(prog)
        if stream_idx is not None:
            self._run_fragment_streaming(prog, stream_idx)
            return
        args, sts = [], []
        for src in prog.sources:
            # resident shardings stage raw (see DistAggExec._run_segment)
            st = self._cache.get(src.scan.table)
            args += [st.data, st.valid, st.sel, st.refs]
            sts.append(st)
        try:
            bcast_args, bcast_shapes = self._gather_broadcasts(prog)
        except _BroadcastTooLarge:
            self._fall_back_single_chip()
            return
        args += bcast_args

        gkey = (prog.sig,) + tuple(st.serial for st in sts)
        growths = self._cache.growth.get(gkey) or prog.growth_defaults
        shapes_sig = (tuple((st.n_parts, st.rows_per_part) for st in sts),
                      tuple(bcast_shapes))
        types_sig = tuple(_types_sig(st) for st in sts)
        t0 = time.perf_counter()
        out, growths = self._dispatch_retry(prog, args, shapes_sig,
                                            types_sig, growths)
        if out is None:
            self._fall_back_single_chip()
            return
        _note_fragment(self, f"general_{prog.out_kind}",
                       sts[0].n_parts if sts else 0, t0)
        touch(self._cache.growth, gkey, growths, ShardCache.MAX_FRAGMENTS)

        if prog.out_kind == "segment":
            self._finalize_segment_state(out, prog.domains)
        else:
            self._finalize_generic_tables(out)

    def _dispatch_retry(self, prog, args, shapes_sig, types_sig, growths):
        """Run the fragment, growing only blown capacity knobs: "exch"
        knobs double; "expand"/"compact" jump to the reported required
        factor in one recompile (skewed joins can demand 100x+ at once).
        Returns (out, growths) or (None, growths) past the ceilings."""
        # the statement's resolved probe mode becomes a trace-time
        # static of the fragment program: it joins the cache key (a
        # knob flip must not serve a program traced for the other
        # strategy) and rides build_fn instead of the process global
        # that concurrent sessions used to race (ISSUE 12)
        probe_mode = getattr(self.ctx, "join_probe_mode", None)
        while True:
            # each retry pays a recompile: bail between attempts if the
            # statement was killed or ran out of its deadline
            raise_if_cancelled(self.ctx)
            key = ("frag", prog.sig, growths, shapes_sig, types_sig,
                   probe_mode)
            fn = self._cache.get_fragment(
                key, lambda: prog.build_fn(growths, probe_mode=probe_mode))
            out, ovf = fn(*args)
            # host-sync: the per-knob overflow vector (a few int64s)
            # gates the capacity-retry loop — one fetch per dispatch
            ovf = np.asarray(ovf)
            if not (ovf > 0).any():
                return out, growths
            new = []
            for g, o, kind in zip(growths, ovf, prog.growth_kinds):
                if o <= 0:
                    new.append(g)
                elif kind in ("expand", "compact"):
                    factor = int(o) + 1
                    mult = 1
                    while mult < factor:
                        mult *= 2
                    new.append(g * max(mult, 2))
                else:
                    new.append(g * 2)
            growths = tuple(new)
            if any(g > self.MAX_GROWTH[k]
                   for g, k in zip(growths, prog.growth_kinds)):
                return None, growths

    def _run_fragment_streaming(self, prog, stream_idx):
        """>HBM sources: stream the oversized table through the compiled
        fragment in fixed [P, R] batches against resident build sides
        (ref: SURVEY.md:315 hard-part 6 generalized beyond scan-agg;
        VERDICT round-2 item 4). Segment states merge on device across
        batches; generic group tables merge per-part on host (parts stay
        disjoint — the exchange routing is identical every batch)."""
        import jax

        from tidb_tpu.executor.agg_device import table_to_host_partial
        from tidb_tpu.executor.aggregate import merge_op_for
        from tidb_tpu.parallel.partition import stream_batches

        mesh = self._cache.mesh
        if prog.topn is not None:
            # a group's partials span batches: a per-batch top-k would
            # drop state a later batch needed — recompile without it
            # (the root TopNExec still bounds what the user sees)
            prog = compile_fragment(
                prog.agg, mesh,
                mesh.shape[dcn_axis] * mesh.shape[shard_axis])
            if prog is None:
                self._fall_back_single_chip()
                return
        src = prog.sources[stream_idx]
        table = src.scan.table
        self._cache.evict(table)  # its full sharding must not stay resident
        scan_cols = [c.name for c in src.scan.schema]
        n_parts = int(np.prod(list(mesh.shape.values())))
        bytes_per_row = sum(
            table.data[n].dtype.itemsize + 1 for n in scan_cols) + 1
        rows_per_part = max(4096, int(
            self.ctx.device_cache_bytes // (4 * n_parts * bytes_per_row)))

        # the STREAMED source stages encoded (its bytes move every
        # batch); resident co-sources stay raw like every other
        # resident sharding
        enc = bool(getattr(self.ctx, "stage_encoded", True))
        sts = {}
        for i, s2 in enumerate(prog.sources):
            if i != stream_idx:
                sts[i] = self._cache.get(s2.scan.table)
        try:
            bcast_args, bcast_shapes = self._gather_broadcasts(prog)
        except _BroadcastTooLarge:
            self._fall_back_single_chip()
            return

        gkey = ((prog.sig, "stream", rows_per_part)
                + tuple(sts[i].serial for i in sorted(sts)))
        growths = self._cache.growth.get(gkey) or prog.growth_defaults
        types_fixed = tuple(_types_sig(sts[i]) for i in sorted(sts))

        seg_state = None
        gen_parts = None  # part index -> [host partial dicts]
        nk = len(self.group_exprs)
        for batch in stream_batches(table, mesh, scan_cols, rows_per_part,
                                    encode=enc):
            # a KILL or deadline expiry must interrupt a >HBM streamed
            # fragment between batches, not only at the root chunk loop
            # (which never runs until every batch has been merged)
            raise_if_cancelled(self.ctx)
            args = []
            shapes = []
            for i in range(len(prog.sources)):
                st = batch if i == stream_idx else sts[i]
                args += [st.data, st.valid, st.sel, st.refs]
                shapes.append((st.n_parts, st.rows_per_part))
            args += bcast_args
            shapes_sig = (tuple(shapes), tuple(bcast_shapes))
            types_sig = types_fixed + (_types_sig(batch), "stream")
            t0 = time.perf_counter()
            out, growths = self._dispatch_retry(prog, args, shapes_sig,
                                                types_sig, growths)
            if out is None:
                self._fall_back_single_chip()
                return
            _note_fragment(self, f"general_{prog.out_kind}_stream",
                           batch.n_parts, t0)
            if prog.out_kind == "segment":
                if seg_state is None:
                    seg_state = out
                else:
                    seg_state = _timed_combine(prog.sig, seg_state, out)
            else:
                from tidb_tpu.utils import dispatch as dsp

                # host-sync: >HBM generic streaming — per-part group
                # tables must merge on host across batches (parts stay
                # disjoint), one batched fetch per streamed batch
                host = dsp.record_fetch(jax.device_get(out))
                dsp.record(site="fetch")
                if gen_parts is None:
                    n_parts_out = len(np.asarray(host["n"]).reshape(-1))
                    gen_parts = [[] for _ in range(n_parts_out)]
                for pi, t in self._iter_host_parts(host):
                    gen_parts[pi].append(
                        table_to_host_partial(t, nk, self.aggs))
        touch(self._cache.growth, gkey, growths, ShardCache.MAX_FRAGMENTS)

        if prog.out_kind == "segment":
            self._finalize_segment_state(seg_state, prog.domains)
            return
        cap = self.ctx.chunk_capacity
        emitted = False
        merged_parts = []
        for partials in (gen_parts or []):
            if not partials:
                continue
            # same key appears across batches of one part: exact merge
            merged_parts.append(partials[0] if len(partials) == 1
                                else self._merge_partials(partials))
        if merged_parts:
            # parts are disjoint across the exchange: concat, emit once
            if self.group_exprs:
                self._emit_merged(self._concat_partials(merged_parts), cap)
            else:
                self._emit_merged(self._merge_partials(merged_parts), cap)
            emitted = True
        if not emitted:
            self._out = []

    @staticmethod
    def _concat_partials(partials):
        """Concatenate DISJOINT host partials (exchange-routed parts of
        one group space) into a single partial so the root emits ONE
        chunk. Per-part emission made every downstream operator pay a
        device dispatch per part — fatal on a high-latency chip link
        (VERDICT r4 weak #2: ~500 ms/dispatch floor on the tunnel)."""
        if len(partials) == 1:
            return partials[0]
        out = {
            "mat": np.concatenate([p["mat"] for p in partials], axis=0),
            "keys": [np.concatenate(ks)
                     for ks in zip(*(p["keys"] for p in partials))],
            "kvalids": [np.concatenate(ks)
                        for ks in zip(*(p["kvalids"] for p in partials))],
        }
        states = []
        for j in range(len(partials[0]["states"])):
            states.append({
                k: np.concatenate([p["states"][j][k] for p in partials])
                for k in partials[0]["states"][j]
            })
        out["states"] = states
        return out

    def _finalize_generic_tables(self, out):
        """Fetch the sharded per-part group tables (one device_get),
        concatenate the disjoint parts, and emit once. The exchange
        routes every key to exactly one shard and the final on-device
        reduce is EXACT (sorts by hash + full key bits), so parts are
        disjoint and duplicate-free — no cross-part host merge exists at
        any cardinality (the 10^7-group host-merge hotspot the round-2
        review flagged)."""
        import jax

        from tidb_tpu.executor.agg_device import table_to_host_partial
        from tidb_tpu.utils import dispatch as dsp

        host = dsp.record_fetch(jax.device_get(out))
        dsp.record(site="fetch")
        nk = len(self.group_exprs)
        cap = self.ctx.chunk_capacity
        partials = [table_to_host_partial(t, nk, self.aggs)
                    for _p, t in self._iter_host_parts(host)]
        if not partials:
            self._out = []  # no groups anywhere
            return
        if nk == 0:
            # keyless partials are not disjoint — exact merge instead
            self._emit_merged(self._merge_partials(partials), cap)
            return
        self._emit_merged(self._concat_partials(partials), cap)


def _try_dist_agg(plan: PHashAgg, cache: ShardCache) -> Optional[Executor]:
    if plan.strategy != "segment":
        return None
    scan_frag = _collapse_to_scan(plan.child)
    if scan_frag is not None:
        scan, stages = scan_frag
        return DistAggExec(plan, scan, stages, cache)
    # join underneath?
    post_stages, node = peel_stages(plan.child)
    if not isinstance(node, PHashJoin) or node.kind != "inner":
        return None
    if len(node.eq_left) != 1 or node.other_cond is not None:
        return None
    probe_idx = 1 - node.build_side
    probe_frag = _collapse_to_scan(node.children[probe_idx])
    build_frag = _collapse_to_scan(node.children[node.build_side])
    if probe_frag is None or build_frag is None:
        return None
    # unique-build-key requirement: trust the planner only when the build
    # key is the build table's primary key
    build_scan = build_frag[0]
    build_keys = node.eq_right if node.build_side == 1 else node.eq_left
    from tidb_tpu.expression.expr import ColumnRef

    pk = getattr(build_scan.table.schema, "primary_key", None)
    key_ir = build_keys[0]
    key_col = key_ir.name if isinstance(key_ir, ColumnRef) else None
    pk_uids = []
    if pk:
        by_name = {c.name: c.uid for c in build_scan.schema}
        pk_uids = [by_name.get(n) for n in pk]
    if not (len(pk_uids) == 1 and key_col == pk_uids[0]):
        return None
    return DistJoinAggExec(plan, node, probe_frag[0], probe_frag[1],
                           build_frag[0], build_frag[1], post_stages, cache)


def _all_scans_pointy(plan: PhysicalPlan) -> bool:
    """True when every base-table access is a point get (or tiny): the
    whole plan touches a handful of rows, so the O(log n) host path wins.
    A point-get LEAF inside a big join must NOT drag the rest of the
    plan off the mesh — the fragment treats it as a filtered scan."""
    from tidb_tpu.planner.physical import PIndexRangeScan, PPointGet

    found = False
    stack = [plan]
    while stack:
        node = stack.pop()
        if isinstance(node, PPointGet):
            found = True
        elif isinstance(node, PIndexRangeScan):
            # a selective range behaves like a point get (compact
            # row-id set via the sorted cache); a wide one must stay
            # eligible for the mesh like any big scan
            if node.est_rows <= 4096:
                found = True
            else:
                return False
        elif isinstance(node, PScan) and node.table is not None:
            if node.table.n > 4096:
                return False
        stack.extend(getattr(node, "children", ()))
    return found


def _try_dist_topn(plan, cache) -> Optional[Executor]:
    """TopN whose sort keys resolved onto a generic dist agg below
    (planner's resolve_topn_pushdown): compile the fragment with a
    per-shard partial top-k, so only n_parts * k candidate groups ever
    reach the host; the root TopNExec applies the exact ordering over
    that superset (SURVEY.md:93 — the reference pushes TopN into
    coprocessors the same way)."""
    from tidb_tpu.planner.physical import PProjection, PTopN

    if getattr(plan, "pushdown", None) is None:
        return None
    agg, items = plan.pushdown
    k = plan.count + plan.offset  # bounds pre-checked by the resolver
    prog = compile_fragment(
        agg, cache.mesh,
        cache.mesh.shape[dcn_axis] * cache.mesh.shape[shard_axis],
        topn=(tuple(items), k))
    if prog is None:
        return None
    ex: Executor = DistFragmentExec(agg, prog, cache)
    chain = []
    node = plan.child
    while isinstance(node, PProjection):
        chain.append(node)
        node = node.child
    if node is not agg:
        return None  # resolver and builder walked different chains
    for p in reversed(chain):
        ex = ProjectionExec(p.schema, ex, p.exprs)
    return TopNExec(plan.schema, ex, plan.items, plan.count, plan.offset)


def build_dist_executor(plan: PhysicalPlan, cache: ShardCache,
                        full: bool = True) -> Executor:
    """Build an executor tree, running distributable fragments on the mesh.

    full=False (the degenerate single-CPU backend) distributes only
    segment scan-agg fragments — joins and generic aggregation run on
    the vectorized host engine, which beats XLA:CPU's sorts there."""
    if _all_scans_pointy(plan):
        # the whole plan touches a handful of rows; the O(log n) host
        # path beats staging tables onto the mesh
        return build_executor(plan)
    if isinstance(plan, PHashAgg):
        if not full:
            # single-CPU backend: keep segment scan-aggs on device
            # (linear scatter-adds win) but run joins and generic
            # aggregation on the vectorized host engine at EVERY size —
            # XLA:CPU's sort-based join fragments measured 2.7x slower
            # than the host engine even at 75k rows (TPC-DS Q95 SF0.5),
            # and the gap only widens with input size (BASELINE.md).
            if plan.strategy == "segment":
                frag = _collapse_to_scan(plan.child)
                if frag is not None:
                    return DistAggExec(plan, frag[0], frag[1], cache)
            return build_executor(plan)
        ex = _try_dist_agg(plan, cache)  # proven fast paths first
        if ex is not None:
            return ex
        prog = compile_fragment(
            plan, cache.mesh,
            cache.mesh.shape[dcn_axis] * cache.mesh.shape[shard_axis])
        if prog is not None:
            return DistFragmentExec(plan, prog, cache)
        if _collapse_to_scan(plan.child) is None:
            # the agg itself isn't distributable (agg-over-agg, DISTINCT,
            # ...) but its subtree may contain fragmentable aggs/joins —
            # run the root agg on the host over a distributed child
            return HashAggExec(
                plan.schema, build_dist_executor(plan.child, cache, full),
                plan.group_exprs, plan.group_uids, plan.aggs, plan.strategy,
                segment_sizes=getattr(plan, "segment_sizes", None))
        return build_executor(plan)
    if isinstance(plan, (PProjection, PSelection)):
        # a fusible chain over a plain scan has no collective fragment —
        # hand the whole thing to the single-chip builder so it fuses into
        # one scan pipeline instead of per-node executors
        _, base = peel_stages(plan)
        if isinstance(base, PScan):
            return build_executor(plan)
        if isinstance(plan, PProjection):
            return ProjectionExec(plan.schema, build_dist_executor(plan.child, cache, full), plan.exprs)
        return SelectionExec(plan.schema, build_dist_executor(plan.child, cache, full), plan.cond)
    if isinstance(plan, PSort):
        return SortExec(plan.schema, build_dist_executor(plan.child, cache, full), plan.items)
    if isinstance(plan, PTopN):
        if full:
            ex = _try_dist_topn(plan, cache)
            if ex is not None:
                return ex
        return TopNExec(plan.schema, build_dist_executor(plan.child, cache, full), plan.items,
                        plan.count, plan.offset)
    if isinstance(plan, PLimit):
        return LimitExec(plan.schema, build_dist_executor(plan.child, cache, full), plan.count, plan.offset)
    return build_executor(plan)
