"""Elastic topology: the coordinator-side primitives that make a
cluster membership or placement change a SERVED-THROUGH operation
(ISSUE 19) instead of an outage.

Two pieces live here, both pure host logic (no sockets — the
blocking-under-lock pass governs this module, and every RPC belongs to
``parallel/dcn.py``):

* :class:`TableGates` — a per-table readers/writer gate. Statements
  read-acquire the tables they touch (shared); the online-reshard
  driver write-acquires ONE table for the brief per-shard backfill and
  cutover windows, and membership finalize write-acquires the global
  ``CLUSTER_GATE`` entry every statement also holds. Writer-priority
  (a waiting writer blocks NEW readers) so a cutover is never starved
  by a stream of scans, and every wait is BOUNDED — a stuck topology
  change degrades statements typed, never hangs them.

* :func:`rows_fingerprint` — the order-independent row-set hash the
  per-shard cutover validates with: the sum of the sources' fingerprints
  over the moving shard must equal the destination staging table's
  fingerprint, or the shard does not flip. Order-independent because
  the backfill's extract order and the double-write arrival order are
  not the storage order.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Dict, Iterable, List, Optional

__all__ = ["TableGates", "CLUSTER_GATE", "rows_fingerprint"]

# the gate entry EVERY statement read-acquires alongside its tables:
# membership finalize (compacting the socket fleet) write-acquires it,
# so no statement can be mid-flight over a worker index being removed
CLUSTER_GATE = "__cluster__"


class _Gate:
    __slots__ = ("readers", "writer", "writer_waiting")

    def __init__(self) -> None:
        self.readers = 0
        self.writer = False
        self.writer_waiting = 0


class TableGates:
    """Per-table shared/exclusive gate with writer priority and bounded
    waits. One Condition guards every entry: acquisitions over MULTIPLE
    names are atomic (all-or-wait), so a statement's read set and a
    cutover's write never deadlock on ordering."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._gates: Dict[str, _Gate] = {}

    def _gate(self, name: str) -> _Gate:
        g = self._gates.get(name)
        if g is None:
            g = self._gates[name] = _Gate()
        return g

    def acquire_read(self, names: Iterable[str],
                     timeout_s: float = 10.0) -> List[str]:
        """Shared-acquire every name (atomically); returns the token to
        hand back to :meth:`release_read`. A waiting or active writer on
        ANY name blocks the whole set (writer priority). Times out
        TYPED via ``TimeoutError`` — the caller re-raises it as the
        statement-facing error naming what is being cut over."""
        names = sorted(set(names))
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while True:
                gates = [self._gate(n) for n in names]
                if not any(g.writer or g.writer_waiting for g in gates):
                    for g in gates:
                        g.readers += 1
                    return names
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    busy = [n for n, g in zip(names, gates)
                            if g.writer or g.writer_waiting]
                    raise TimeoutError(
                        f"gate(s) {busy} held for topology change")
                self._cond.wait(remaining)

    def release_read(self, token: List[str]) -> None:
        with self._cond:
            for n in token:
                g = self._gates.get(n)
                if g is not None and g.readers > 0:
                    g.readers -= 1
            self._cond.notify_all()

    def acquire_write(self, name: str,
                      timeout_s: float = 60.0) -> None:
        """Exclusive-acquire one name: waits out current readers while
        `writer_waiting` holds new ones at the door."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            g = self._gate(name)
            g.writer_waiting += 1
            try:
                while g.readers > 0 or g.writer:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"gate {name!r}: statements still hold it")
                    self._cond.wait(remaining)
                g.writer = True
            finally:
                g.writer_waiting -= 1
                self._cond.notify_all()

    def release_write(self, name: str) -> None:
        with self._cond:
            g = self._gates.get(name)
            if g is not None:
                g.writer = False
            self._cond.notify_all()


def rows_fingerprint(arrays: Dict, valids: Dict, strings: Dict,
                     columns: Iterable[str],
                     sel: Optional[object] = None) -> tuple:
    """(row_count, fingerprint) of an extracted row set — the
    ``shuffle.extract_live_columns`` shape, optionally restricted by a
    boolean ``sel`` mask. Order-independent: each row canonicalizes to
    a tuple repr (NULL-aware, numpy scalars unboxed so the same value
    fingerprints identically whatever dtype carried it), crc32s, and
    the fingerprints SUM mod 2**64 — so source shards hashed separately
    add up to the destination staging table hashed whole."""
    import numpy as np

    columns = list(columns)
    if sel is not None:
        idx = np.nonzero(np.asarray(sel, dtype=bool))[0]
    else:
        probe = next(iter(columns), None)
        if probe is None:
            return 0, 0
        n = (len(strings[probe]) if probe in strings
             else len(arrays[probe]))
        idx = np.arange(n)
    fp = 0
    for i in idx:
        vals = []
        for c in columns:
            if c in strings:
                vals.append(strings[c][int(i)])
            else:
                if not bool(valids[c][i]):
                    vals.append(None)
                else:
                    v = arrays[c][i]
                    vals.append(v.item() if hasattr(v, "item") else v)
        fp = (fp + zlib.crc32(repr(tuple(vals)).encode())) % (1 << 64)
    return int(len(idx)), fp
