"""Multi-chip execution: the distsql/coprocessor tier rebuilt on jax.sharding.

Reference counterparts (SURVEY.md §2 parallelism inventory):
  * distsql/ + store/copr/  -> sharded partitions + shard_map scan fragments
  * HashAggExec partial/final worker pipeline -> per-shard partial segment agg
    merged with lax.psum/pmin/pmax over the mesh axis
  * HashJoinExec build/probe workers + MPP exchange -> hash repartition via
    lax.all_to_all, local sort-probe join per shard
  * gRPC/region-cache routing -> NamedSharding placement on a Mesh; ICI
    carries every exchange, DCN modeled as an outer mesh axis
"""

from tidb_tpu.parallel.mesh import make_mesh, shard_axis, dcn_axis
from tidb_tpu.parallel.partition import ShardedTable, shard_table
from tidb_tpu.parallel.distsql import (
    dist_agg_fragment,
    dist_join_agg_fragment,
    make_agg_fragment,
    make_join_agg_fragment,
    merge_state,
    repartition_by_key,
)

__all__ = [
    "make_mesh",
    "shard_axis",
    "dcn_axis",
    "ShardedTable",
    "shard_table",
    "dist_agg_fragment",
    "make_agg_fragment",
    "make_join_agg_fragment",
    "dist_join_agg_fragment",
    "merge_state",
    "repartition_by_key",
]
