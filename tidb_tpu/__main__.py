"""tidb-server equivalent: boot the MySQL-protocol server from the CLI.

    python -m tidb_tpu [--host H] [--port P] [--config file.toml]
                       [--mesh {auto,none}] [--load-tpch SF]
                       [--root-password PW]

Ref: tidb-server/main.go (flag parsing -> config merge -> bootstrap ->
Server.Run). Config file keys mirror the flags; explicit flags win.
"""

from __future__ import annotations

import argparse
import sys


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="tidb_tpu", description=__doc__)
    ap.add_argument("--host", default=None, help="listen address (default 127.0.0.1)")
    ap.add_argument("--port", type=int, default=None, help="listen port (default 4000)")
    ap.add_argument("--status-port", type=int, default=None,
                    help="HTTP status/metrics port (default 10080; -1 disables)")
    ap.add_argument("--config", default=None, help="TOML config file")
    ap.add_argument("--mesh", choices=["auto", "none"], default=None,
                    help="auto: shard tables over all visible devices")
    ap.add_argument("--load-tpch", type=float, default=None, metavar="SF",
                    help="preload TPC-H tables at scale factor SF")
    ap.add_argument("--root-password", default=None,
                    help="set the root account password at boot")
    ap.add_argument("--plugin-modules", default=None,
                    help="comma-separated module path prefixes INSTALL "
                         "PLUGIN may import (default: none — SQL plugin "
                         "loading disabled on the server)")
    ap.add_argument("--device", choices=["default", "cpu"], default=None,
                    help="force the jax platform (cpu bypasses a broken/"
                         "absent accelerator; the env pin alone is not "
                         "enough when a sitecustomize overrides it)")
    return ap.parse_args(argv)


def load_config(path):
    import tomllib

    with open(path, "rb") as f:
        return tomllib.load(f)


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])
    cfg = load_config(args.config) if args.config else {}
    host = args.host or cfg.get("host", "127.0.0.1")
    port = args.port if args.port is not None else int(cfg.get("port", 4000))
    status_port = (args.status_port if args.status_port is not None
                   else int(cfg.get("status_port", 10080)))
    if status_port < 0:
        status_port = None
    mesh_mode = args.mesh or cfg.get("mesh", "auto")
    sf = args.load_tpch if args.load_tpch is not None else cfg.get("load_tpch")
    root_pw = (args.root_password if args.root_password is not None
               else cfg.get("root_password"))
    plugin_mods = (args.plugin_modules if args.plugin_modules is not None
                   else cfg.get("plugin_modules", ""))

    import tidb_tpu  # noqa: F401  (x64 config before jax backend init)

    device = args.device or cfg.get("device", "default")
    if device != "default":
        import jax

        jax.config.update("jax_platforms", device)

    from tidb_tpu.server.server import Server
    from tidb_tpu.storage.catalog import Catalog

    mesh = None
    if mesh_mode == "auto":
        try:
            from tidb_tpu.parallel import make_mesh

            mesh = make_mesh()
        except Exception as e:  # noqa: BLE001 — boot headless without a mesh
            print(f"# mesh unavailable ({e}); single-chip execution", file=sys.stderr)

    catalog = Catalog()
    # SQL-reachable plugin imports are allowlisted on the wire server
    catalog.plugins.allowed_prefixes = tuple(
        p.strip() for p in str(plugin_mods).split(",") if p.strip())
    if root_pw:
        catalog.set_password("root", root_pw)
    if sf:
        from tidb_tpu.storage.tpch import load_tpch

        counts = load_tpch(catalog, sf=float(sf))
        print(f"# loaded TPC-H sf={sf}: {counts}", file=sys.stderr)

    server = Server(catalog=catalog, host=host, port=port, mesh=mesh,
                    status_port=status_port)
    server.start()
    if server.status_port is not None:
        print(f"# status port http://{server.host}:{server.status_port}"
              "/metrics /status /schema", file=sys.stderr)
    print(f"# tidb_tpu server listening on {server.host}:{server.port}",
          file=sys.stderr)
    try:
        server._accept_thread.join()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
