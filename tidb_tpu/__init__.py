"""tidb_tpu — a TPU-native relational execution framework.

A from-scratch rebuild of the capabilities of the reference SQL layer
(PiotrNewt/tidb, a TiDB fork): MySQL-dialect SQL front-end, rule-based
planner, columnar vectorized executor, hash aggregation/join, distributed
execution — redesigned for TPU hardware on JAX/XLA/Pallas rather than
ported from the reference's Go/goroutine architecture.

Layer map (mirrors SURVEY.md section 1's layer map of the reference):

  session/      -- Session.execute() parse->plan->run loop, sysvars
  parser/       -- MySQL-dialect SQL -> AST          (ref: parser/)
  planner/      -- logical/physical plans, rules     (ref: planner/core)
  expression/   -- expr trees -> jitted columnar fns (ref: expression/ VecEval*)
  executor/     -- pull-based operators over chunks  (ref: executor/)
  ops/          -- device kernels: filter/agg/join   (ref: hot loops of executor/)
  chunk/        -- columnar batch format             (ref: util/chunk)
  storage/      -- host columnar partitions, catalog (ref: store/mockstore, kv/)
  parallel/     -- mesh, shard_map fragments, exchange (ref: distsql/, store/copr)
  utils/        -- memory tracking, tracing          (ref: util/memory, util/execdetails)

Design rules (TPU-first):
  * all device shapes are static; row liveness is a selection mask
  * strings are sorted-dictionary int32 codes (order-preserving)
  * decimals are scaled int64
  * no data-dependent Python control flow under jit
"""

import os

import jax

# 64-bit types are required for decimal (scaled int64) and SUM accumulators.
# Must run before any jnp array is created anywhere in the package.
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: fragment compiles on the tunneled TPU
# backend here run through a remote AOT helper at ~60s+ per program, so
# re-compiling known shapes across processes (tests, bench, server
# restarts) is the single largest latency source. Degrades gracefully if
# the backend can't serialize executables. The 10s threshold keeps fast
# CPU compiles out of the cache: XLA:CPU AOT artifacts embed the compile
# process's host-feature flags, and processes with/without the TPU
# plugin loaded detect different CPU features — sharing those entries
# risks SIGILL on load.
_cache_dir = os.environ.get("TIDB_TPU_COMPILE_CACHE",
                            os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
if _cache_dir != "0":
    jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 10.0)

__version__ = "0.1.0"
