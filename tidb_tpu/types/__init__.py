"""SQL type system (the `types/` + `util/codec` role of the reference).

Every SQL type maps onto a fixed-width device representation so that all
columns are dense jnp arrays with static shapes:

  SQL type            device repr            notes
  ------------------  ---------------------  ----------------------------------
  BIGINT/INT/...      int64                  all integer widths widen to int64
  DOUBLE/FLOAT        float64                float32 opt-in per column
  DECIMAL(p,s)        int64 scaled by 10^s   p<=18; sums widen on host
  CHAR/VARCHAR/TEXT   int32 dict code        per-column *sorted* dictionary, so
                                             code order == lexicographic order
  DATE                int32 days since epoch
  DATETIME/TIMESTAMP  int64 microseconds since epoch
  BOOLEAN             bool_
  NULL                carried in validity mask, never in data

The host-side scalar view of a value is a `Datum` (Python object), used by
the parser/planner for literals and by result sets; the device never sees
Datums.
"""

from __future__ import annotations

import datetime
import enum
from dataclasses import dataclass
from typing import Any

import numpy as np

__all__ = [
    "TypeKind",
    "SQLType",
    "Datum",
    "INT64",
    "FLOAT64",
    "BOOL",
    "DATE",
    "DATETIME",
    "STRING",
    "NULLTYPE",
    "decimal_type",
    "EPOCH",
    "date_to_days",
    "days_to_date",
    "datetime_to_micros",
    "micros_to_datetime",
    "decimal_to_scaled",
    "scaled_to_decimal_str",
    "common_type",
    "parse_type_name",
    "TIME",
    "JSONTYPE",
    "enum_type",
    "set_type",
    "time_to_micros",
    "micros_to_time_str",
    "set_to_mask",
    "mask_to_set_str",
]


class TypeKind(enum.Enum):
    INT = "int"
    FLOAT = "float"
    DECIMAL = "decimal"
    STRING = "string"
    DATE = "date"
    DATETIME = "datetime"
    TIME = "time"      # int64 signed microseconds (MySQL TIME is a duration)
    ENUM = "enum"      # int32 1-based member index (definition order == sort order)
    SET = "set"        # int64 member bitmask
    JSON = "json"      # int32 dictionary code over document texts (like STRING)
    BOOL = "bool"
    NULL = "null"


# device representation per kind, built once (np_dtype sits on the
# per-chunk hot path; rebuilding the mapping per call measurably cost)
_NP_DTYPES = {
    TypeKind.INT: np.dtype(np.int64),
    TypeKind.FLOAT: np.dtype(np.float64),
    TypeKind.DECIMAL: np.dtype(np.int64),
    TypeKind.STRING: np.dtype(np.int32),
    TypeKind.DATE: np.dtype(np.int32),
    TypeKind.DATETIME: np.dtype(np.int64),
    TypeKind.TIME: np.dtype(np.int64),
    TypeKind.ENUM: np.dtype(np.int32),
    TypeKind.SET: np.dtype(np.int64),
    TypeKind.JSON: np.dtype(np.int32),
    TypeKind.BOOL: np.dtype(np.bool_),
    TypeKind.NULL: np.dtype(np.bool_),
}


@dataclass(frozen=True)
class SQLType:
    """Static (trace-time) type descriptor for a column or expression."""

    kind: TypeKind
    # decimal precision/scale; scale is the power-of-ten fixed-point shift
    precision: int = 0
    scale: int = 0
    # ENUM/SET member list, in definition order (tuple: hashable)
    members: tuple = ()

    @property
    def np_dtype(self) -> np.dtype:
        return _NP_DTYPES[self.kind]

    @property
    def is_numeric(self) -> bool:
        return self.kind in (TypeKind.INT, TypeKind.FLOAT, TypeKind.DECIMAL, TypeKind.BOOL)

    @property
    def is_string(self) -> bool:
        return self.kind == TypeKind.STRING

    @property
    def is_dict_encoded(self) -> bool:
        """Stored as codes into a per-column host dictionary."""
        return self.kind in (TypeKind.STRING, TypeKind.JSON)

    @property
    def is_temporal(self) -> bool:
        return self.kind in (TypeKind.DATE, TypeKind.DATETIME)

    def __str__(self) -> str:
        if self.kind == TypeKind.DECIMAL:
            return f"decimal({self.precision},{self.scale})"
        if self.kind in (TypeKind.ENUM, TypeKind.SET):
            inner = ",".join(f"'{m}'" for m in self.members)
            return f"{self.kind.value}({inner})"
        return self.kind.value


INT64 = SQLType(TypeKind.INT)
FLOAT64 = SQLType(TypeKind.FLOAT)
BOOL = SQLType(TypeKind.BOOL)
DATE = SQLType(TypeKind.DATE)
DATETIME = SQLType(TypeKind.DATETIME)
TIME = SQLType(TypeKind.TIME)
STRING = SQLType(TypeKind.STRING)
JSONTYPE = SQLType(TypeKind.JSON)
NULLTYPE = SQLType(TypeKind.NULL)


def enum_type(members) -> SQLType:
    return SQLType(TypeKind.ENUM, members=tuple(members))


def set_type(members) -> SQLType:
    members = tuple(members)
    if len(members) > 63:
        # bit 63 of the int64 mask is the sign bit; uint64 storage would
        # buy one more member at the cost of special-casing everywhere
        raise ValueError("SET supports at most 63 members")
    return SQLType(TypeKind.SET, members=members)


def decimal_type(precision: int, scale: int) -> SQLType:
    if precision > 18:
        # int64 holds 18 full decimal digits; larger precisions would need a
        # two-limb representation (future work), reject loudly for now.
        raise ValueError(f"decimal precision {precision} > 18 unsupported")
    return SQLType(TypeKind.DECIMAL, precision=precision, scale=scale)


# ---------------------------------------------------------------------------
# host-side scalar conversions
# ---------------------------------------------------------------------------

EPOCH = datetime.date(1970, 1, 1)


def date_to_days(d: datetime.date) -> int:
    return (d - EPOCH).days


def days_to_date(days: int) -> datetime.date:
    return EPOCH + datetime.timedelta(days=int(days))


def datetime_to_micros(dt: datetime.datetime) -> int:
    # integer arithmetic: float seconds lose microsecond exactness and int()
    # truncates toward zero for pre-epoch values
    epoch = (
        datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
        if dt.tzinfo
        else datetime.datetime(1970, 1, 1)
    )
    return (dt - epoch) // datetime.timedelta(microseconds=1)


def micros_to_datetime(us: int) -> datetime.datetime:
    return datetime.datetime(1970, 1, 1) + datetime.timedelta(microseconds=int(us))


_TIME_MAX = (838 * 3600 + 59 * 60 + 59) * 1_000_000  # MySQL TIME range


def time_to_micros(v) -> int:
    """'[-]HH:MM:SS[.ffffff]' / '[-]HHMMSS' / timedelta -> signed micros."""
    if isinstance(v, datetime.timedelta):
        return v // datetime.timedelta(microseconds=1)
    if isinstance(v, datetime.time):
        return ((v.hour * 60 + v.minute) * 60 + v.second) * 1_000_000 + v.microsecond
    s = str(v).strip()
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    frac = 0
    if "." in s:
        s, f = s.split(".", 1)
        frac = int((f + "000000")[:6])
    if ":" in s:
        parts = [int(p) for p in s.split(":")]
        if len(parts) == 2:
            parts.append(0)  # MySQL: 'HH:MM' means HH:MM:00
        h, m, sec = parts
    else:  # HHMMSS integer form
        n = int(s)
        h, m, sec = n // 10000, n // 100 % 100, n % 100
    if m >= 60 or sec >= 60:
        raise ValueError(f"bad TIME value: {v!r}")
    us = ((h * 60 + m) * 60 + sec) * 1_000_000 + frac
    if us > _TIME_MAX:
        raise ValueError(f"TIME value out of range: {v!r}")
    return -us if neg else us


def micros_to_time_str(us: int) -> str:
    us = int(us)
    sign = "-" if us < 0 else ""
    mag = abs(us)
    frac = mag % 1_000_000
    sec = mag // 1_000_000
    h, m, s = sec // 3600, sec // 60 % 60, sec % 60
    base = f"{sign}{h:02d}:{m:02d}:{s:02d}"
    return f"{base}.{frac:06d}".rstrip("0").rstrip(".") if frac else base


def set_to_mask(v, members) -> int:
    """'a,b' / iterable / int mask -> bitmask over definition order."""
    if isinstance(v, int):
        if not 0 <= v < (1 << len(members)):
            raise ValueError(f"SET mask {v} out of range")
        return v
    items = [p for p in str(v).split(",") if p] if isinstance(v, str) else list(v)
    mask = 0
    for it in items:
        try:
            mask |= 1 << members.index(it)
        except ValueError:
            raise ValueError(f"unknown SET member {it!r}")
    return mask


def mask_to_set_str(mask: int, members) -> str:
    return ",".join(m for i, m in enumerate(members) if int(mask) >> i & 1)


def decimal_to_scaled(value, scale: int) -> int:
    """Parse a decimal literal (str/float/int/Decimal) to scaled int64."""
    import decimal as _dec

    d = _dec.Decimal(str(value))
    q = d.scaleb(scale).to_integral_value(rounding=_dec.ROUND_HALF_UP)
    return int(q)


def scaled_to_decimal_str(scaled: int, scale: int) -> str:
    if scale == 0:
        return str(int(scaled))
    sign = "-" if scaled < 0 else ""
    mag = abs(int(scaled))
    intpart, frac = divmod(mag, 10**scale)
    return f"{sign}{intpart}.{frac:0{scale}d}"


# ---------------------------------------------------------------------------
# type inference helpers
# ---------------------------------------------------------------------------


def common_type(a: SQLType, b: SQLType) -> SQLType:
    """Result type of a binary arithmetic/comparison over (a, b).

    Follows MySQL's widening order: int < decimal < float; temporal types
    compare among themselves; strings compare as dictionary codes.
    """
    if a.kind == TypeKind.NULL:
        return b
    if b.kind == TypeKind.NULL:
        return a
    if a.kind == b.kind:
        if a.kind == TypeKind.DECIMAL:
            scale = max(a.scale, b.scale)
            prec = min(18, max(a.precision - a.scale, b.precision - b.scale) + scale + 1)
            return decimal_type(prec, scale)
        return a
    order = {
        TypeKind.BOOL: 0,
        TypeKind.INT: 1,
        TypeKind.DECIMAL: 2,
        TypeKind.FLOAT: 3,
    }
    if a.kind in order and b.kind in order:
        hi = a if order[a.kind] >= order[b.kind] else b
        if hi.kind == TypeKind.DECIMAL:
            return decimal_type(min(18, hi.precision + 1), hi.scale)
        return SQLType(hi.kind)
    if a.is_temporal and b.is_temporal:
        return DATETIME if TypeKind.DATETIME in (a.kind, b.kind) else DATE
    # string vs temporal / numeric: compare as strings is wrong for TPU codes;
    # widen to float for numeric-vs-string like MySQL does.
    if a.kind == TypeKind.STRING and b.is_numeric:
        return FLOAT64
    if b.kind == TypeKind.STRING and a.is_numeric:
        return FLOAT64
    if a.kind == TypeKind.STRING and b.is_temporal:
        return b
    if b.kind == TypeKind.STRING and a.is_temporal:
        return a
    raise TypeError(f"no common type for {a} and {b}")


_TYPE_NAMES = {
    "tinyint": INT64,
    "smallint": INT64,
    "mediumint": INT64,
    "int": INT64,
    "integer": INT64,
    "bigint": INT64,
    "float": FLOAT64,
    "double": FLOAT64,
    "real": FLOAT64,
    "char": STRING,
    "varchar": STRING,
    "text": STRING,
    "tinytext": STRING,
    "mediumtext": STRING,
    "longtext": STRING,
    "string": STRING,
    "date": DATE,
    "datetime": DATETIME,
    "timestamp": DATETIME,
    "time": TIME,
    "year": INT64,
    "bit": INT64,
    "json": JSONTYPE,
    "bool": BOOL,
    "boolean": BOOL,
}


def parse_type_name(name: str, args: tuple = ()) -> SQLType:
    """Map a SQL column type name (+ optional length/scale/member args)
    to SQLType."""
    low = name.lower()
    if low in ("decimal", "numeric"):
        prec = int(args[0]) if args else 10
        scale = int(args[1]) if len(args) > 1 else 0
        return decimal_type(prec, scale)
    if low == "enum":
        return enum_type(str(a) for a in args)
    if low == "set":
        return set_type([str(a) for a in args])
    if low in _TYPE_NAMES:
        return _TYPE_NAMES[low]
    raise ValueError(f"unknown type name {name!r}")


# ---------------------------------------------------------------------------
# Datum: host-side boxed scalar (parser literals, result rows)
# ---------------------------------------------------------------------------


@dataclass
class Datum:
    """A typed host scalar. `value` is the *logical* Python value (Decimal
    values are python ints already scaled per `type_.scale`)."""

    type_: SQLType
    value: Any  # None means SQL NULL

    @property
    def is_null(self) -> bool:
        return self.value is None
