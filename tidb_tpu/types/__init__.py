"""SQL type system (the `types/` + `util/codec` role of the reference).

Every SQL type maps onto a fixed-width device representation so that all
columns are dense jnp arrays with static shapes:

  SQL type            device repr            notes
  ------------------  ---------------------  ----------------------------------
  BIGINT/INT/...      int64                  all integer widths widen to int64
  DOUBLE/FLOAT        float64                float32 opt-in per column
  DECIMAL(p,s)        int64 scaled by 10^s   p<=18; sums widen on host
  CHAR/VARCHAR/TEXT   int32 dict code        per-column *sorted* dictionary, so
                                             code order == lexicographic order
  DATE                int32 days since epoch
  DATETIME/TIMESTAMP  int64 microseconds since epoch
  BOOLEAN             bool_
  NULL                carried in validity mask, never in data

The host-side scalar view of a value is a `Datum` (Python object), used by
the parser/planner for literals and by result sets; the device never sees
Datums.
"""

from __future__ import annotations

import datetime
import enum
from dataclasses import dataclass
from typing import Any

import numpy as np

__all__ = [
    "TypeKind",
    "SQLType",
    "Datum",
    "INT64",
    "FLOAT64",
    "BOOL",
    "DATE",
    "DATETIME",
    "STRING",
    "NULLTYPE",
    "decimal_type",
    "EPOCH",
    "date_to_days",
    "days_to_date",
    "datetime_to_micros",
    "micros_to_datetime",
    "decimal_to_scaled",
    "scaled_to_decimal_str",
    "common_type",
    "parse_type_name",
]


class TypeKind(enum.Enum):
    INT = "int"
    FLOAT = "float"
    DECIMAL = "decimal"
    STRING = "string"
    DATE = "date"
    DATETIME = "datetime"
    BOOL = "bool"
    NULL = "null"


@dataclass(frozen=True)
class SQLType:
    """Static (trace-time) type descriptor for a column or expression."""

    kind: TypeKind
    # decimal precision/scale; scale is the power-of-ten fixed-point shift
    precision: int = 0
    scale: int = 0

    @property
    def np_dtype(self) -> np.dtype:
        return {
            TypeKind.INT: np.dtype(np.int64),
            TypeKind.FLOAT: np.dtype(np.float64),
            TypeKind.DECIMAL: np.dtype(np.int64),
            TypeKind.STRING: np.dtype(np.int32),
            TypeKind.DATE: np.dtype(np.int32),
            TypeKind.DATETIME: np.dtype(np.int64),
            TypeKind.BOOL: np.dtype(np.bool_),
            TypeKind.NULL: np.dtype(np.bool_),
        }[self.kind]

    @property
    def is_numeric(self) -> bool:
        return self.kind in (TypeKind.INT, TypeKind.FLOAT, TypeKind.DECIMAL, TypeKind.BOOL)

    @property
    def is_string(self) -> bool:
        return self.kind == TypeKind.STRING

    @property
    def is_temporal(self) -> bool:
        return self.kind in (TypeKind.DATE, TypeKind.DATETIME)

    def __str__(self) -> str:
        if self.kind == TypeKind.DECIMAL:
            return f"decimal({self.precision},{self.scale})"
        return self.kind.value


INT64 = SQLType(TypeKind.INT)
FLOAT64 = SQLType(TypeKind.FLOAT)
BOOL = SQLType(TypeKind.BOOL)
DATE = SQLType(TypeKind.DATE)
DATETIME = SQLType(TypeKind.DATETIME)
STRING = SQLType(TypeKind.STRING)
NULLTYPE = SQLType(TypeKind.NULL)


def decimal_type(precision: int, scale: int) -> SQLType:
    if precision > 18:
        # int64 holds 18 full decimal digits; larger precisions would need a
        # two-limb representation (future work), reject loudly for now.
        raise ValueError(f"decimal precision {precision} > 18 unsupported")
    return SQLType(TypeKind.DECIMAL, precision=precision, scale=scale)


# ---------------------------------------------------------------------------
# host-side scalar conversions
# ---------------------------------------------------------------------------

EPOCH = datetime.date(1970, 1, 1)


def date_to_days(d: datetime.date) -> int:
    return (d - EPOCH).days


def days_to_date(days: int) -> datetime.date:
    return EPOCH + datetime.timedelta(days=int(days))


def datetime_to_micros(dt: datetime.datetime) -> int:
    # integer arithmetic: float seconds lose microsecond exactness and int()
    # truncates toward zero for pre-epoch values
    epoch = (
        datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
        if dt.tzinfo
        else datetime.datetime(1970, 1, 1)
    )
    return (dt - epoch) // datetime.timedelta(microseconds=1)


def micros_to_datetime(us: int) -> datetime.datetime:
    return datetime.datetime(1970, 1, 1) + datetime.timedelta(microseconds=int(us))


def decimal_to_scaled(value, scale: int) -> int:
    """Parse a decimal literal (str/float/int/Decimal) to scaled int64."""
    import decimal as _dec

    d = _dec.Decimal(str(value))
    q = d.scaleb(scale).to_integral_value(rounding=_dec.ROUND_HALF_UP)
    return int(q)


def scaled_to_decimal_str(scaled: int, scale: int) -> str:
    if scale == 0:
        return str(int(scaled))
    sign = "-" if scaled < 0 else ""
    mag = abs(int(scaled))
    intpart, frac = divmod(mag, 10**scale)
    return f"{sign}{intpart}.{frac:0{scale}d}"


# ---------------------------------------------------------------------------
# type inference helpers
# ---------------------------------------------------------------------------


def common_type(a: SQLType, b: SQLType) -> SQLType:
    """Result type of a binary arithmetic/comparison over (a, b).

    Follows MySQL's widening order: int < decimal < float; temporal types
    compare among themselves; strings compare as dictionary codes.
    """
    if a.kind == TypeKind.NULL:
        return b
    if b.kind == TypeKind.NULL:
        return a
    if a.kind == b.kind:
        if a.kind == TypeKind.DECIMAL:
            scale = max(a.scale, b.scale)
            prec = min(18, max(a.precision - a.scale, b.precision - b.scale) + scale + 1)
            return decimal_type(prec, scale)
        return a
    order = {
        TypeKind.BOOL: 0,
        TypeKind.INT: 1,
        TypeKind.DECIMAL: 2,
        TypeKind.FLOAT: 3,
    }
    if a.kind in order and b.kind in order:
        hi = a if order[a.kind] >= order[b.kind] else b
        if hi.kind == TypeKind.DECIMAL:
            return decimal_type(min(18, hi.precision + 1), hi.scale)
        return SQLType(hi.kind)
    if a.is_temporal and b.is_temporal:
        return DATETIME if TypeKind.DATETIME in (a.kind, b.kind) else DATE
    # string vs temporal / numeric: compare as strings is wrong for TPU codes;
    # widen to float for numeric-vs-string like MySQL does.
    if a.kind == TypeKind.STRING and b.is_numeric:
        return FLOAT64
    if b.kind == TypeKind.STRING and a.is_numeric:
        return FLOAT64
    if a.kind == TypeKind.STRING and b.is_temporal:
        return b
    if b.kind == TypeKind.STRING and a.is_temporal:
        return a
    raise TypeError(f"no common type for {a} and {b}")


_TYPE_NAMES = {
    "tinyint": INT64,
    "smallint": INT64,
    "mediumint": INT64,
    "int": INT64,
    "integer": INT64,
    "bigint": INT64,
    "float": FLOAT64,
    "double": FLOAT64,
    "real": FLOAT64,
    "char": STRING,
    "varchar": STRING,
    "text": STRING,
    "tinytext": STRING,
    "mediumtext": STRING,
    "longtext": STRING,
    "string": STRING,
    "date": DATE,
    "datetime": DATETIME,
    "timestamp": DATETIME,
    "bool": BOOL,
    "boolean": BOOL,
}


def parse_type_name(name: str, args: tuple = ()) -> SQLType:
    """Map a SQL column type name (+ optional length/scale args) to SQLType."""
    low = name.lower()
    if low in ("decimal", "numeric"):
        prec = int(args[0]) if args else 10
        scale = int(args[1]) if len(args) > 1 else 0
        return decimal_type(prec, scale)
    if low in _TYPE_NAMES:
        return _TYPE_NAMES[low]
    raise ValueError(f"unknown type name {name!r}")


# ---------------------------------------------------------------------------
# Datum: host-side boxed scalar (parser literals, result rows)
# ---------------------------------------------------------------------------


@dataclass
class Datum:
    """A typed host scalar. `value` is the *logical* Python value (Decimal
    values are python ints already scaled per `type_.scale`)."""

    type_: SQLType
    value: Any  # None means SQL NULL

    @property
    def is_null(self) -> bool:
        return self.value is None
