"""Threaded MySQL-protocol server over Session (ref: server/server.go
Server.Run + clientConn.Run: accept, handshake, command dispatch loop).

One Session per connection, all sharing one Catalog — the same shape as
the reference's one-process-many-connections SQL node. The executor tier
underneath (single-chip or mesh) is whatever the Session was built with.

Connection threads do protocol I/O only; statements execute on the
serving tier's bounded worker pool (tidb_tpu/serving — admission
control, typed busy/timeout rejections, cross-session micro-batching of
plan-cache-hit point reads). The accept loop itself is capped by
tidb_max_connections: over-limit handshakes get MySQL error 1040
instead of an unbounded daemon thread.
"""

from __future__ import annotations

import os
import socket
import threading
import traceback
from typing import Optional

from tidb_tpu.errors import TiDBTPUError as TidbError
from tidb_tpu.server import protocol as P
from tidb_tpu.session import Session
from tidb_tpu.session.sysvars import SysVarStore
from tidb_tpu.storage.catalog import Catalog

__all__ = ["Server"]

ER_CON_COUNT_ERROR = 1040  # MySQL "Too many connections"

COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_CLOSE = 0x19
COM_STMT_RESET = 0x1A


class Server:
    def __init__(self, catalog: Optional[Catalog] = None, host: str = "127.0.0.1",
                 port: int = 4000, mesh=None, status_port: Optional[int] = None):
        self.catalog = catalog or Catalog()
        self.host = host
        self.port = port
        self.mesh = mesh
        self.status_port = status_port  # None disables the HTTP status tier
        self._status_server = None
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_id = 0
        self._running = False
        # server-scope view of the GLOBAL sysvars (tidb_max_connections,
        # scheduler knobs) — the accept loop has no session of its own
        self.sysvars = SysVarStore(self.catalog.global_vars)
        # the serving tier: bounded execution + admission control +
        # micro-batching (created in start(), drained in shutdown())
        self.scheduler = None
        self._active_conns = 0
        self._conn_lock = threading.Lock()

    # ------------------------------------------------------------------

    def start(self) -> None:
        # Initialize the jax backend NOW, in the caller's (main) thread:
        # lazy init from a connection handler thread can wedge inside the
        # TPU plugin (observed with the tunneled axon backend), hanging
        # every query. A failed init is fine — queries fall back per
        # host_eager()'s probing.
        try:
            import jax

            jax.default_backend()
            jax.local_devices(backend="cpu")
        except Exception:  # noqa: BLE001 — probe is best-effort:
            pass  # a failed backend init falls back per host_eager()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]  # resolves port 0
        self._sock.listen(16)
        self._running = True
        if self.status_port is not None:
            from tidb_tpu.server.status import StatusServer
            from tidb_tpu.session.sysvars import SYSVARS

            self._status_server = StatusServer(
                self.catalog, host=self.host, port=self.status_port,
                version=str(SYSVARS["version"].default))
            self._status_server.start()
            self.status_port = self._status_server.port
        # each server instance runs a DDL worker; the elected owner
        # executes queued DDL for every instance (ref: owner/ + ddl/)
        from tidb_tpu.owner import DDLWorker

        self._ddl_worker = DDLWorker(self.catalog, f"server-{id(self):x}")
        self._ddl_worker.start()
        from tidb_tpu.serving import StatementScheduler

        self.scheduler = StatementScheduler(self.catalog)
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful stop: close the accept socket (no new connections),
        drain the scheduler pool deterministically (queued statements
        finish — or are rejected typed with drain=False — and workers
        join), then stop the auxiliary tiers."""
        self._running = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self.scheduler is not None:
            self.scheduler.shutdown(drain=drain, timeout=timeout)
        if getattr(self, "_ddl_worker", None) is not None:
            self._ddl_worker.stop()
            self._ddl_worker = None
        if self._status_server is not None:
            self._status_server.stop()
            self._status_server = None

    def stop(self) -> None:
        self.shutdown(drain=True)

    def serve_forever(self) -> None:
        self.start()
        try:
            self._accept_thread.join()
        except KeyboardInterrupt:
            self.stop()

    # ------------------------------------------------------------------

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            # connection cap (ref: server.go's checkConnectionCount):
            # over-limit clients get MySQL 1040 as the FIRST packet and
            # the socket closes — no daemon thread, no session
            limit = int(self.sysvars.get("tidb_max_connections"))
            with self._conn_lock:
                if limit and self._active_conns >= limit:
                    over = True
                else:
                    over = False
                    self._active_conns += 1
            if over:
                try:
                    P.write_packet(conn, 0, P.err_packet(
                        ER_CON_COUNT_ERROR, "Too many connections", "08004"))
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            self._conn_id += 1
            t = threading.Thread(
                target=self._serve_conn, args=(conn, self._conn_id), daemon=True
            )
            t.start()

    def _serve_conn(self, conn: socket.socket, conn_id: int) -> None:
        from tidb_tpu.utils.metrics import CONN_GAUGE

        CONN_GAUGE.inc()
        sess = None
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sess = Session(catalog=self.catalog, mesh=self.mesh)
            if self.scheduler is not None:
                self.scheduler.attach_session(sess)
            salt = os.urandom(20).replace(b"\x00", b"\x01")
            version = str(sess.sysvars.get("version"))
            P.write_packet(conn, 0, P.handshake_v10(conn_id, version, salt))
            _seq, payload = P.read_packet(conn)
            hello = P.parse_handshake_response(payload)
            # auth plugins first (ref: plugin/ authentication hook);
            # builtin mysql_native_password scramble otherwise
            verdict = self.catalog.plugins.authenticate(
                hello["user"], hello["auth"], salt)
            if verdict is None:
                verdict = self.catalog.verify_user(hello["user"], hello["auth"], salt)
            if not verdict:
                P.write_packet(conn, 2, P.err_packet(
                    1045, f"Access denied for user '{hello['user']}'", "28000"))
                return
            sess.user = hello["user"]
            if hello["db"]:
                try:
                    sess.execute(f"use {hello['db']}")
                except TidbError:
                    pass
            P.write_packet(conn, 2, P.ok_packet())
            self._command_loop(conn, sess)
        except (ConnectionError, OSError):
            pass
        except Exception:
            traceback.print_exc()
        finally:
            CONN_GAUGE.dec()
            with self._conn_lock:
                self._active_conns -= 1
            try:
                # connection end: the session's TEMPORARY tables vanish
                if sess is not None:
                    sess.catalog.drop_temp_tables()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _command_loop(self, conn: socket.socket, sess: Session) -> None:
        while True:
            _seq, payload = P.read_packet(conn)
            if not payload:
                return
            cmd, body = payload[0], payload[1:]
            if cmd == COM_QUIT:
                return
            if cmd == COM_PING:
                P.write_packet(conn, 1, P.ok_packet())
                continue
            if cmd == COM_INIT_DB:
                self._run_sql(conn, sess, f"use {body.decode()}")
                continue
            if cmd == COM_QUERY:
                self._run_sql(conn, sess, body.decode("utf-8"))
                continue
            if cmd == COM_FIELD_LIST:
                P.write_packet(conn, 1, P.eof_packet())
                continue
            if cmd == COM_STMT_PREPARE:
                self._stmt_prepare(conn, sess, body.decode("utf-8"))
                continue
            if cmd == COM_STMT_EXECUTE:
                self._stmt_execute(conn, sess, body)
                continue
            if cmd == COM_STMT_CLOSE:
                if len(body) >= 4:
                    sess.close_prepared(int.from_bytes(body[:4], "little"))
                continue  # no response, per protocol
            if cmd == COM_STMT_RESET:
                P.write_packet(conn, 1, P.ok_packet())
                continue
            P.write_packet(conn, 1, P.err_packet(1047, f"unknown command {cmd:#x}"))

    def _stmt_prepare(self, conn, sess: Session, sql: str) -> None:
        try:
            stmt_id, n_params = sess.prepare(sql)
        except TidbError as e:
            P.write_packet(conn, 1, P.err_packet(getattr(e, "code", 1105), str(e)))
            return
        # num_columns=0: clients read the actual column defs from the
        # execute response's result-set header
        seq = P.write_packet(conn, 1, P.stmt_prepare_ok(stmt_id, 0, n_params))
        for i in range(n_params):
            seq = P.write_packet(conn, seq, P.column_def41(f"?{i}", P.MYSQL_TYPE_VAR_STRING))
        if n_params:
            P.write_packet(conn, seq, P.eof_packet())

    def _stmt_execute(self, conn, sess: Session, body: bytes) -> None:
        try:
            stmt_id = int.from_bytes(body[:4], "little")
            ent = sess._prepared.get(stmt_id)
            if ent is None:
                P.write_packet(conn, 1, P.err_packet(1243, f"unknown statement {stmt_id}"))
                return
            n_params = ent[1]
            # param types arrive only on the first execute; cache them
            # per statement for re-executions (per protocol)
            if not hasattr(sess, "_stmt_types"):
                sess._stmt_types = {}
            stmt_id, params, types = P.parse_stmt_execute(
                body, n_params, sess._stmt_types.get(stmt_id))
            sess._stmt_types[stmt_id] = types
            # serving tier: admission control + micro-batching; the
            # worker takes the catalog statement lock (this thread only
            # parks on the result)
            rs = self.scheduler.submit_prepared(sess, stmt_id, params)
        except TidbError as e:
            P.write_packet(conn, 1, P.err_packet(getattr(e, "code", 1105), str(e)))
            return
        except Exception as e:  # engine bug — surface, don't kill the conn
            traceback.print_exc()
            P.write_packet(conn, 1, P.err_packet(1105, f"internal error: {e}"))
            return
        status = self._status(sess)
        if rs is None:
            P.write_packet(conn, 1, P.ok_packet(status=status))
            return
        types = rs.types or [None] * len(rs.names)
        seq = P.write_packet(conn, 1, P.lenc_int(len(rs.names)))
        for name, kind in zip(rs.names, types):
            seq = P.write_packet(conn, seq, P.column_def41(name, P.binary_kind(kind)))
        seq = P.write_packet(conn, seq, P.eof_packet(status=status))
        for row in rs.rows:
            seq = P.write_packet(conn, seq, P.binary_row(list(row), types))
        P.write_packet(conn, seq, P.eof_packet(status=status))

    @staticmethod
    def _status(sess: Session) -> int:
        status = 0
        if sess.sysvars.get("autocommit"):
            status |= P.SERVER_STATUS_AUTOCOMMIT
        if sess.txn is not None:
            status |= P.SERVER_STATUS_IN_TRANS
        return status

    def _run_sql(self, conn: socket.socket, sess: Session, sql: str) -> None:
        try:
            # serving tier: bounded workers execute (and serialize on
            # the catalog lock there); this thread does protocol I/O only
            rs = self.scheduler.submit_query(sess, sql)
        except TidbError as e:
            P.write_packet(conn, 1, P.err_packet(getattr(e, "code", 1105), str(e)))
            return
        except Exception as e:  # engine bug — surface, don't kill the conn
            traceback.print_exc()
            P.write_packet(conn, 1, P.err_packet(1105, f"internal error: {e}"))
            return
        status = self._status(sess)
        if rs is None:
            P.write_packet(conn, 1, P.ok_packet(status=status))
            return
        types = rs.types or [None] * len(rs.names)
        seq = P.write_packet(conn, 1, P.lenc_int(len(rs.names)))
        for name, kind in zip(rs.names, types):
            seq = P.write_packet(conn, seq, P.column_def41(name, P.mysql_type_of(kind)))
        seq = P.write_packet(conn, seq, P.eof_packet(status=status))
        for row in rs.rows:
            seq = P.write_packet(conn, seq, P.text_row(list(row)))
        P.write_packet(conn, seq, P.eof_packet(status=status))
