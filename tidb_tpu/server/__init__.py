"""MySQL wire protocol server (ref: server/ — conn handling, handshake,
COM_QUERY dispatch, resultset writing)."""

from tidb_tpu.server.server import Server

__all__ = ["Server"]
