"""MySQL client/server protocol encoding (ref: server/packetio.go +
server/conn.go's handshake and resultset writers).

Implements the v10 handshake, CLIENT_PROTOCOL_41 packets, length-encoded
integers/strings, OK/ERR/EOF, column definitions, and text-protocol rows
— the subset a standard MySQL client needs to connect and run queries.
"""

from __future__ import annotations

import datetime
import struct
from typing import List, Optional, Tuple

from tidb_tpu.types import TypeKind

__all__ = [
    "CAPABILITIES", "read_packet", "write_packet", "lenc_int", "lenc_str",
    "read_lenc_int", "ok_packet", "err_packet", "eof_packet",
    "handshake_v10", "parse_handshake_response", "column_def41",
    "text_row", "render_value", "mysql_type_of",
]

# capability flags
CLIENT_LONG_PASSWORD = 1 << 0
CLIENT_FOUND_ROWS = 1 << 1
CLIENT_LONG_FLAG = 1 << 2
CLIENT_CONNECT_WITH_DB = 1 << 3
CLIENT_PROTOCOL_41 = 1 << 9
CLIENT_TRANSACTIONS = 1 << 13
CLIENT_SECURE_CONNECTION = 1 << 15
CLIENT_PLUGIN_AUTH = 1 << 19
CLIENT_DEPRECATE_EOF = 1 << 24

CAPABILITIES = (
    CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS | CLIENT_LONG_FLAG
    | CLIENT_CONNECT_WITH_DB | CLIENT_PROTOCOL_41 | CLIENT_TRANSACTIONS
    | CLIENT_SECURE_CONNECTION | CLIENT_PLUGIN_AUTH
)

# text protocol column types
MYSQL_TYPE_TINY = 0x01
MYSQL_TYPE_LONGLONG = 0x08
MYSQL_TYPE_DOUBLE = 0x05
MYSQL_TYPE_NEWDECIMAL = 0xF6
MYSQL_TYPE_VAR_STRING = 0xFD
MYSQL_TYPE_DATE = 0x0A
MYSQL_TYPE_DATETIME = 0x0C

SERVER_STATUS_IN_TRANS = 0x0001
SERVER_STATUS_AUTOCOMMIT = 0x0002

MAX_PACKET = 0xFFFFFF  # payloads split at 16MB-1 per the protocol


# ---------------------------------------------------------------------------
# packet framing: [3-byte little-endian length][1-byte sequence][payload]
# ---------------------------------------------------------------------------

def read_packet(sock) -> Tuple[int, bytes]:
    """Read one logical packet, reassembling 16MB continuation frames."""
    payload = b""
    while True:
        header = _read_exact(sock, 4)
        length = header[0] | (header[1] << 8) | (header[2] << 16)
        seq = header[3]
        payload += _read_exact(sock, length)
        if length < MAX_PACKET:
            return seq, payload


def _read_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("peer closed connection")
        buf += part
    return buf


def write_packet(sock, seq: int, payload: bytes) -> int:
    """Send a logical packet, splitting at the 16MB frame limit; returns
    the next sequence id."""
    pos = 0
    while True:
        frame = payload[pos:pos + MAX_PACKET]
        n = len(frame)
        sock.sendall(
            bytes([n & 0xFF, (n >> 8) & 0xFF, (n >> 16) & 0xFF, seq & 0xFF]) + frame
        )
        seq += 1
        pos += n
        # a payload that is an exact multiple of MAX_PACKET needs a
        # trailing empty frame as the terminator
        if n < MAX_PACKET:
            return seq


# ---------------------------------------------------------------------------
# length-encoded primitives
# ---------------------------------------------------------------------------

def lenc_int(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def lenc_str(s: bytes) -> bytes:
    return lenc_int(len(s)) + s


def read_lenc_int(buf: bytes, pos: int) -> Tuple[int, int]:
    first = buf[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return int.from_bytes(buf[pos + 1:pos + 4], "little"), pos + 4
    return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9


# ---------------------------------------------------------------------------
# generic packets
# ---------------------------------------------------------------------------

def ok_packet(affected: int = 0, last_insert_id: int = 0,
              status: int = SERVER_STATUS_AUTOCOMMIT, warnings: int = 0) -> bytes:
    return (b"\x00" + lenc_int(affected) + lenc_int(last_insert_id)
            + struct.pack("<HH", status, warnings))


def err_packet(code: int, message: str, state: str = "HY000") -> bytes:
    return (b"\xff" + struct.pack("<H", code) + b"#" + state.encode()
            + message.encode("utf-8")[:512])


def eof_packet(status: int = SERVER_STATUS_AUTOCOMMIT, warnings: int = 0) -> bytes:
    return b"\xfe" + struct.pack("<HH", warnings, status)


# ---------------------------------------------------------------------------
# handshake
# ---------------------------------------------------------------------------

def handshake_v10(conn_id: int, server_version: str, salt: bytes) -> bytes:
    assert len(salt) == 20
    caps = CAPABILITIES
    return (
        b"\x0a"
        + server_version.encode() + b"\x00"
        + struct.pack("<I", conn_id)
        + salt[:8] + b"\x00"
        + struct.pack("<H", caps & 0xFFFF)
        + bytes([0x21])                      # charset utf8_general_ci
        + struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
        + struct.pack("<H", (caps >> 16) & 0xFFFF)
        + bytes([21])                        # auth plugin data length
        + b"\x00" * 10
        + salt[8:] + b"\x00"
        + b"mysql_native_password\x00"
    )


def parse_handshake_response(payload: bytes) -> dict:
    caps = struct.unpack_from("<I", payload, 0)[0]
    pos = 4 + 4 + 1 + 23  # caps, max packet, charset, reserved
    end = payload.index(b"\x00", pos)
    user = payload[pos:end].decode()
    pos = end + 1
    if caps & CLIENT_SECURE_CONNECTION:
        alen = payload[pos]
        pos += 1
        auth = payload[pos:pos + alen]
        pos += alen
    else:
        end = payload.index(b"\x00", pos)
        auth = payload[pos:end]
        pos = end + 1
    db = None
    if caps & CLIENT_CONNECT_WITH_DB and pos < len(payload):
        end = payload.find(b"\x00", pos)
        if end >= 0:
            db = payload[pos:end].decode() or None
            pos = end + 1
    return {"capabilities": caps, "user": user, "auth": auth, "db": db}


# ---------------------------------------------------------------------------
# result sets
# ---------------------------------------------------------------------------

def mysql_type_of(kind: Optional[TypeKind]) -> int:
    return {
        TypeKind.INT: MYSQL_TYPE_LONGLONG,
        TypeKind.FLOAT: MYSQL_TYPE_DOUBLE,
        TypeKind.DECIMAL: MYSQL_TYPE_NEWDECIMAL,
        TypeKind.STRING: MYSQL_TYPE_VAR_STRING,
        TypeKind.DATE: MYSQL_TYPE_DATE,
        TypeKind.DATETIME: MYSQL_TYPE_DATETIME,
        TypeKind.BOOL: MYSQL_TYPE_TINY,
        None: MYSQL_TYPE_VAR_STRING,
    }.get(kind, MYSQL_TYPE_VAR_STRING)


def column_def41(name: str, mysql_type: int, db: str = "", table: str = "") -> bytes:
    return (
        lenc_str(b"def")
        + lenc_str(db.encode())
        + lenc_str(table.encode()) + lenc_str(table.encode())
        + lenc_str(name.encode()) + lenc_str(name.encode())
        + bytes([0x0C])                       # fixed-length fields marker
        + struct.pack("<H", 0x21)             # charset
        + struct.pack("<I", 255)              # column length
        + bytes([mysql_type])
        + struct.pack("<H", 0)                # flags
        + bytes([0])                          # decimals
        + b"\x00\x00"
    )


def render_value(v) -> Optional[bytes]:
    """Python result value -> text-protocol bytes (None stays NULL)."""
    if v is None:
        return None
    if isinstance(v, bool):
        return b"1" if v else b"0"
    if isinstance(v, float):
        return repr(v).encode()
    if isinstance(v, datetime.datetime):
        return v.isoformat(sep=" ").encode()
    if isinstance(v, datetime.date):
        return v.isoformat().encode()
    out = v if isinstance(v, bytes) else str(v)
    if isinstance(out, str):
        out = out.encode("utf-8")
    return out


def text_row(values: List) -> bytes:
    out = b""
    for v in values:
        r = render_value(v)
        if r is None:
            out += b"\xfb"
        else:
            if isinstance(r, str):
                r = r.encode()
            out += lenc_str(r)
    return out


# ---------------------------------------------------------------------------
# binary protocol (COM_STMT_* — ref: server/conn_stmt.go)
# ---------------------------------------------------------------------------

def stmt_prepare_ok(stmt_id: int, num_columns: int, num_params: int) -> bytes:
    return (b"\x00" + struct.pack("<I", stmt_id)
            + struct.pack("<H", num_columns) + struct.pack("<H", num_params)
            + b"\x00" + struct.pack("<H", 0))


def binary_kind(kind: Optional[TypeKind]) -> int:
    """Column type declared in binary result sets. DATE/DATETIME values
    are already rendered to ISO strings by result materialization, so
    they are declared (and sent) as strings."""
    return {
        TypeKind.INT: MYSQL_TYPE_LONGLONG,
        TypeKind.BOOL: MYSQL_TYPE_TINY,
        TypeKind.FLOAT: MYSQL_TYPE_DOUBLE,
        TypeKind.DECIMAL: MYSQL_TYPE_NEWDECIMAL,
    }.get(kind, MYSQL_TYPE_VAR_STRING)


def binary_row(values: List, kinds: List[Optional[TypeKind]]) -> bytes:
    """One binary-protocol resultset row: 0x00 header, NULL bitmap
    (offset 2), then values encoded per their declared binary type."""
    n = len(values)
    bitmap = bytearray((n + 7 + 2) // 8)
    body = b""
    for i, (v, kind) in enumerate(zip(values, kinds)):
        if v is None:
            pos = i + 2
            bitmap[pos // 8] |= 1 << (pos % 8)
            continue
        bt = binary_kind(kind)
        if bt == MYSQL_TYPE_LONGLONG:
            body += struct.pack("<q", int(v))
        elif bt == MYSQL_TYPE_TINY:
            body += struct.pack("<b", 1 if v else 0)
        elif bt == MYSQL_TYPE_DOUBLE:
            body += struct.pack("<d", float(v))
        else:
            r = render_value(v) or b""
            body += lenc_str(r)
    return b"\x00" + bytes(bitmap) + body


def parse_stmt_execute(body: bytes, n_params: int,
                       known_types: Optional[list] = None) -> Tuple[int, list, list]:
    """COM_STMT_EXECUTE payload (after the command byte) -> (stmt_id,
    bound parameter values, param types). Standard clients send the type
    block only on the FIRST execute (new_params_bound_flag=1); later
    executions reuse `known_types` cached by the connection."""
    stmt_id = struct.unpack_from("<I", body, 0)[0]
    pos = 4 + 1 + 4  # stmt_id, flags, iteration count
    params: list = []
    if n_params == 0:
        return stmt_id, params, []
    nb = (n_params + 7) // 8
    null_bitmap = body[pos:pos + nb]
    pos += nb
    new_bound = body[pos]
    pos += 1
    if new_bound:
        types = []
        for _ in range(n_params):
            t, flags = body[pos], body[pos + 1]
            types.append((t, bool(flags & 0x80)))
            pos += 2
    elif known_types is not None:
        types = known_types
    else:
        raise ValueError("re-execution without parameter types bound")
    for i, (t, unsigned) in enumerate(types):
        if null_bitmap[i // 8] & (1 << (i % 8)):
            params.append(None)
            continue
        v, pos = _read_binary_value(body, pos, t, unsigned)
        params.append(v)
    return stmt_id, params, types


def _read_binary_value(buf: bytes, pos: int, mysql_type: int, unsigned: bool):
    import datetime

    t = mysql_type
    if t == 0x01:  # TINY
        v = buf[pos] if unsigned else struct.unpack_from("<b", buf, pos)[0]
        return v, pos + 1
    if t == 0x02:  # SHORT
        fmt = "<H" if unsigned else "<h"
        return struct.unpack_from(fmt, buf, pos)[0], pos + 2
    if t in (0x03, 0x09):  # LONG / INT24
        fmt = "<I" if unsigned else "<i"
        return struct.unpack_from(fmt, buf, pos)[0], pos + 4
    if t == 0x08:  # LONGLONG
        fmt = "<Q" if unsigned else "<q"
        return struct.unpack_from(fmt, buf, pos)[0], pos + 8
    if t == 0x04:  # FLOAT
        return struct.unpack_from("<f", buf, pos)[0], pos + 4
    if t == 0x05:  # DOUBLE
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if t == 0x06:  # NULL
        return None, pos
    if t in (0x0A, 0x0C, 0x07):  # DATE / DATETIME / TIMESTAMP
        length = buf[pos]
        pos += 1
        if length == 0:
            return datetime.date(1970, 1, 1) if t == 0x0A else datetime.datetime(1970, 1, 1), pos
        y, mo, d = struct.unpack_from("<HBB", buf, pos)
        if t == 0x0A and length == 4:
            return datetime.date(y, mo, d), pos + length
        h = mi = s = us = 0
        if length >= 7:
            h, mi, s = buf[pos + 4], buf[pos + 5], buf[pos + 6]
        if length >= 11:
            us = struct.unpack_from("<I", buf, pos + 7)[0]
        if t == 0x0A:
            return datetime.date(y, mo, d), pos + length
        return datetime.datetime(y, mo, d, h, mi, s, us), pos + length
    # strings / decimals / blobs: length-encoded
    n, pos = read_lenc_int(buf, pos)
    raw = buf[pos:pos + n]
    if t == 0xF6:  # NEWDECIMAL arrives as text
        return raw.decode(), pos + n
    try:
        return raw.decode("utf-8"), pos + n
    except UnicodeDecodeError:
        return raw, pos + n
