"""Minimal MySQL text-protocol client.

Speaks the standard protocol (handshake v10 + COM_QUERY + text result
sets), so it works against this package's Server or any MySQL-compatible
server. Used by the test suite (no third-party MySQL driver ships in the
environment) and as a tiny CLI: python -m tidb_tpu.server.client.
"""

from __future__ import annotations

import socket
import struct
from typing import List, Optional, Tuple

from tidb_tpu.server import protocol as P

__all__ = ["Client", "ServerError"]


class ServerError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"({code}) {message}")
        self.code = code
        self.message = message


class Client:
    def __init__(self, host: str = "127.0.0.1", port: int = 4000,
                 user: str = "root", db: Optional[str] = None, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        _seq, payload = P.read_packet(self.sock)
        if payload and payload[0] == 0xFF:
            raise self._err(payload)
        caps = P.CLIENT_PROTOCOL_41 | P.CLIENT_SECURE_CONNECTION | P.CLIENT_PLUGIN_AUTH
        if db:
            caps |= P.CLIENT_CONNECT_WITH_DB
        resp = (
            struct.pack("<I", caps)
            + struct.pack("<I", 1 << 24)
            + bytes([0x21])
            + b"\x00" * 23
            + user.encode() + b"\x00"
            + bytes([0])  # empty auth response
            + ((db.encode() + b"\x00") if db else b"")
            + b"mysql_native_password\x00"
        )
        P.write_packet(self.sock, 1, resp)
        _seq, payload = P.read_packet(self.sock)
        if payload and payload[0] == 0xFF:
            raise self._err(payload)

    # ------------------------------------------------------------------

    def query(self, sql: str) -> Tuple[List[str], List[tuple]]:
        """Run one statement; returns (column names, rows). Non-queries
        return ([], [])."""
        P.write_packet(self.sock, 0, b"\x03" + sql.encode("utf-8"))
        _seq, payload = P.read_packet(self.sock)
        if not payload:
            raise ConnectionError("empty response")
        if payload[0] == 0xFF:
            raise self._err(payload)
        if payload[0] == 0x00:
            return [], []
        ncols, _ = P.read_lenc_int(payload, 0)
        names = []
        for _ in range(ncols):
            _seq, col = P.read_packet(self.sock)
            names.append(self._column_name(col))
        _seq, eof = P.read_packet(self.sock)  # EOF after column defs
        rows = []
        while True:
            _seq, pkt = P.read_packet(self.sock)
            if pkt and pkt[0] == 0xFE and len(pkt) < 9:
                break
            if pkt and pkt[0] == 0xFF:
                raise self._err(pkt)
            rows.append(self._parse_row(pkt, ncols))
        return names, rows

    def execute(self, sql: str) -> None:
        self.query(sql)

    def ping(self) -> bool:
        P.write_packet(self.sock, 0, b"\x0e")
        _seq, payload = P.read_packet(self.sock)
        return bool(payload) and payload[0] == 0x00

    def close(self) -> None:
        try:
            P.write_packet(self.sock, 0, b"\x01")
        except OSError:
            pass
        self.sock.close()

    # ------------------------------------------------------------------

    @staticmethod
    def _err(payload: bytes) -> ServerError:
        code = struct.unpack_from("<H", payload, 1)[0]
        msg = payload[3:].decode("utf-8", "replace")
        if msg.startswith("#"):
            msg = msg[6:]
        return ServerError(code, msg)

    @staticmethod
    def _column_name(payload: bytes) -> str:
        pos = 0
        out = []
        for _ in range(5):  # catalog, schema, table, org_table, name
            n, pos = P.read_lenc_int(payload, pos)
            out.append(payload[pos:pos + n])
            pos += n
        return out[4].decode()

    @staticmethod
    def _parse_row(payload: bytes, ncols: int) -> tuple:
        pos = 0
        vals = []
        for _ in range(ncols):
            if payload[pos] == 0xFB:
                vals.append(None)
                pos += 1
            else:
                n, pos = P.read_lenc_int(payload, pos)
                vals.append(payload[pos:pos + n].decode("utf-8"))
                pos += n
        return tuple(vals)


def _main():  # pragma: no cover - interactive CLI
    import sys

    host, port = "127.0.0.1", 4000
    if len(sys.argv) > 1:
        host, _, p = sys.argv[1].partition(":")
        port = int(p or 4000)
    c = Client(host, port)
    print(f"connected to {host}:{port}; enter SQL, empty line to quit")
    while True:
        try:
            sql = input("sql> ").strip()
        except EOFError:
            break
        if not sql:
            break
        try:
            names, rows = c.query(sql)
        except ServerError as e:
            print("ERROR:", e)
            continue
        if names:
            print("\t".join(names))
            for r in rows:
                print("\t".join("NULL" if v is None else str(v) for v in r))
        else:
            print("OK")
    c.close()


if __name__ == "__main__":
    _main()
