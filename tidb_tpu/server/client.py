"""Minimal MySQL text-protocol client.

Speaks the standard protocol (handshake v10 + COM_QUERY + text result
sets), so it works against this package's Server or any MySQL-compatible
server. Used by the test suite (no third-party MySQL driver ships in the
environment) and as a tiny CLI: python -m tidb_tpu.server.client.
"""

from __future__ import annotations

import socket
import struct
from typing import List, Optional, Tuple

from tidb_tpu.server import protocol as P

__all__ = ["Client", "ServerError"]


class ServerError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"({code}) {message}")
        self.code = code
        self.message = message


class Client:
    def __init__(self, host: str = "127.0.0.1", port: int = 4000,
                 user: str = "root", password: str = "",
                 db: Optional[str] = None, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        _seq, payload = P.read_packet(self.sock)
        if payload and payload[0] == 0xFF:
            raise self._err(payload)
        salt = self._parse_salt(payload)
        caps = P.CLIENT_PROTOCOL_41 | P.CLIENT_SECURE_CONNECTION | P.CLIENT_PLUGIN_AUTH
        if db:
            caps |= P.CLIENT_CONNECT_WITH_DB
        token = self._scramble(password, salt)
        resp = (
            struct.pack("<I", caps)
            + struct.pack("<I", 1 << 24)
            + bytes([0x21])
            + b"\x00" * 23
            + user.encode() + b"\x00"
            + bytes([len(token)]) + token
            + ((db.encode() + b"\x00") if db else b"")
            + b"mysql_native_password\x00"
        )
        P.write_packet(self.sock, 1, resp)
        _seq, payload = P.read_packet(self.sock)
        if payload and payload[0] == 0xFF:
            raise self._err(payload)

    @staticmethod
    def _parse_salt(payload: bytes) -> bytes:
        # protocol v10: 0x0a, version\0, conn_id(4), salt1(8), 0,
        # caps_lo(2), charset, status(2), caps_hi(2), auth_len, 10 zeros,
        # salt2(12)\0
        pos = payload.index(b"\x00", 1) + 1
        salt1 = payload[pos + 4:pos + 12]
        pos2 = pos + 12 + 1 + 2 + 1 + 2 + 2 + 1 + 10
        salt2 = payload[pos2:pos2 + 12]
        return salt1 + salt2

    @staticmethod
    def _scramble(password: str, salt: bytes) -> bytes:
        import hashlib

        if not password:
            return b""
        stage1 = hashlib.sha1(password.encode()).digest()
        stage2 = hashlib.sha1(stage1).digest()
        mix = hashlib.sha1(salt + stage2).digest()
        return bytes(a ^ b for a, b in zip(stage1, mix))

    # ------------------------------------------------------------------

    def query(self, sql: str) -> Tuple[List[str], List[tuple]]:
        """Run one statement; returns (column names, rows). Non-queries
        return ([], [])."""
        P.write_packet(self.sock, 0, b"\x03" + sql.encode("utf-8"))
        _seq, payload = P.read_packet(self.sock)
        if not payload:
            raise ConnectionError("empty response")
        if payload[0] == 0xFF:
            raise self._err(payload)
        if payload[0] == 0x00:
            return [], []
        ncols, _ = P.read_lenc_int(payload, 0)
        names = []
        for _ in range(ncols):
            _seq, col = P.read_packet(self.sock)
            names.append(self._column_name(col))
        _seq, eof = P.read_packet(self.sock)  # EOF after column defs
        rows = []
        while True:
            _seq, pkt = P.read_packet(self.sock)
            if pkt and pkt[0] == 0xFE and len(pkt) < 9:
                break
            if pkt and pkt[0] == 0xFF:
                raise self._err(pkt)
            rows.append(self._parse_row(pkt, ncols))
        return names, rows

    def execute(self, sql: str) -> None:
        self.query(sql)

    def ping(self) -> bool:
        P.write_packet(self.sock, 0, b"\x0e")
        _seq, payload = P.read_packet(self.sock)
        return bool(payload) and payload[0] == 0x00

    # -- binary protocol (prepared statements) -------------------------

    def prepare(self, sql: str) -> Tuple[int, int]:
        """COM_STMT_PREPARE; returns (stmt_id, n_params)."""
        P.write_packet(self.sock, 0, b"\x16" + sql.encode("utf-8"))
        _seq, payload = P.read_packet(self.sock)
        if payload and payload[0] == 0xFF:
            raise self._err(payload)
        stmt_id = struct.unpack_from("<I", payload, 1)[0]
        num_cols = struct.unpack_from("<H", payload, 5)[0]
        n_params = struct.unpack_from("<H", payload, 7)[0]
        for _ in range(n_params + (1 if n_params else 0)):
            P.read_packet(self.sock)  # param defs + EOF
        for _ in range(num_cols + (1 if num_cols else 0)):
            P.read_packet(self.sock)  # column defs + EOF
        return stmt_id, n_params

    def execute_prepared(self, stmt_id: int, params: Tuple = ()) -> Tuple[List[str], List[tuple]]:
        body = struct.pack("<I", stmt_id) + b"\x00" + struct.pack("<I", 1)
        n = len(params)
        if n:
            bitmap = bytearray((n + 7) // 8)
            types = b""
            values = b""
            for i, v in enumerate(params):
                if v is None:
                    bitmap[i // 8] |= 1 << (i % 8)
                    types += bytes([0x06, 0])
                elif isinstance(v, bool):
                    types += bytes([0x01, 0])
                    values += struct.pack("<b", 1 if v else 0)
                elif isinstance(v, int):
                    types += bytes([0x08, 0])
                    values += struct.pack("<q", v)
                elif isinstance(v, float):
                    types += bytes([0x05, 0])
                    values += struct.pack("<d", v)
                else:
                    types += bytes([0xFD, 0])
                    values += P.lenc_str(str(v).encode("utf-8"))
            body += bytes(bitmap) + b"\x01" + types + values
        P.write_packet(self.sock, 0, b"\x17" + body)
        return self._read_binary_resultset()

    def close_prepared(self, stmt_id: int) -> None:
        P.write_packet(self.sock, 0, b"\x19" + struct.pack("<I", stmt_id))

    def _read_binary_resultset(self) -> Tuple[List[str], List[tuple]]:
        _seq, payload = P.read_packet(self.sock)
        if not payload:
            raise ConnectionError("empty response")
        if payload[0] == 0xFF:
            raise self._err(payload)
        if payload[0] == 0x00:
            return [], []
        ncols, _ = P.read_lenc_int(payload, 0)
        names, types = [], []
        for _ in range(ncols):
            _seq, col = P.read_packet(self.sock)
            name, mtype = self._column_name_type(col)
            names.append(name)
            types.append(mtype)
        P.read_packet(self.sock)  # EOF
        rows = []
        while True:
            _seq, pkt = P.read_packet(self.sock)
            if pkt and pkt[0] == 0xFE and len(pkt) < 9:
                break
            if pkt and pkt[0] == 0xFF:
                raise self._err(pkt)
            rows.append(self._parse_binary_row(pkt, types))
        return names, rows

    @staticmethod
    def _column_name_type(payload: bytes) -> Tuple[str, int]:
        pos = 0
        parts = []
        for _ in range(6):  # catalog, schema, table, org_table, name, org_name
            n, pos = P.read_lenc_int(payload, pos)
            parts.append(payload[pos:pos + n])
            pos += n
        pos += 1 + 2 + 4  # 0x0C marker, charset, length
        return parts[4].decode(), payload[pos]

    @staticmethod
    def _parse_binary_row(payload: bytes, types: List[int]) -> tuple:
        n = len(types)
        pos = 1
        nb = (n + 7 + 2) // 8
        bitmap = payload[pos:pos + nb]
        pos += nb
        vals = []
        for i, t in enumerate(types):
            bit = i + 2
            if bitmap[bit // 8] & (1 << (bit % 8)):
                vals.append(None)
                continue
            if t == 0x08:  # LONGLONG
                vals.append(struct.unpack_from("<q", payload, pos)[0])
                pos += 8
            elif t == 0x01:  # TINY
                vals.append(struct.unpack_from("<b", payload, pos)[0])
                pos += 1
            elif t == 0x05:  # DOUBLE
                vals.append(struct.unpack_from("<d", payload, pos)[0])
                pos += 8
            else:  # lenc string (decimal/varchar/date-as-string)
                ln, pos = P.read_lenc_int(payload, pos)
                vals.append(payload[pos:pos + ln].decode("utf-8"))
                pos += ln
        return tuple(vals)

    def close(self) -> None:
        try:
            P.write_packet(self.sock, 0, b"\x01")
        except OSError:
            pass
        self.sock.close()

    # ------------------------------------------------------------------

    @staticmethod
    def _err(payload: bytes) -> ServerError:
        code = struct.unpack_from("<H", payload, 1)[0]
        msg = payload[3:].decode("utf-8", "replace")
        if msg.startswith("#"):
            msg = msg[6:]
        return ServerError(code, msg)

    @staticmethod
    def _column_name(payload: bytes) -> str:
        pos = 0
        out = []
        for _ in range(5):  # catalog, schema, table, org_table, name
            n, pos = P.read_lenc_int(payload, pos)
            out.append(payload[pos:pos + n])
            pos += n
        return out[4].decode()

    @staticmethod
    def _parse_row(payload: bytes, ncols: int) -> tuple:
        pos = 0
        vals = []
        for _ in range(ncols):
            if payload[pos] == 0xFB:
                vals.append(None)
                pos += 1
            else:
                n, pos = P.read_lenc_int(payload, pos)
                vals.append(payload[pos:pos + n].decode("utf-8"))
                pos += n
        return tuple(vals)


def _main():  # pragma: no cover - interactive CLI
    import sys

    host, port = "127.0.0.1", 4000
    if len(sys.argv) > 1:
        host, _, p = sys.argv[1].partition(":")
        port = int(p or 4000)
    c = Client(host, port)
    print(f"connected to {host}:{port}; enter SQL, empty line to quit")
    while True:
        try:
            sql = input("sql> ").strip()
        except EOFError:
            break
        if not sql:
            break
        try:
            names, rows = c.query(sql)
        except ServerError as e:
            print("ERROR:", e)
            continue
        if names:
            print("\t".join(names))
            for r in rows:
                print("\t".join("NULL" if v is None else str(v) for v in r))
        else:
            print("OK")
    c.close()


if __name__ == "__main__":
    _main()
