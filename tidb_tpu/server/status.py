"""HTTP status port (ref: the tidb-server status port: /metrics for
Prometheus, /status for liveness/version, plus schema introspection).

Endpoints:
    /metrics     - Prometheus text exposition of tidb_tpu_* collectors;
                   ?scope=cluster scrapes every live Cluster's workers
                   over DCN and renders per-worker `worker` labels plus
                   the merged `worker="fleet"` view (unreachable
                   workers become error samples, never a failed scrape)
    /status      - JSON: version, connections, schema version, uptime
    /schema      - JSON: databases -> tables -> row counts
    /statements  - JSON: top-N statement digests by cumulative latency
                   (?top=N, default 50) from the statements-summary store
    /plan_cache  - JSON: plan-cache hit/miss/bypass/evict/invalidate
                   totals plus per-entry digests (?top=N, default 50)
    /cluster     - JSON: per-worker DCN health machine (up/suspect/down,
                   reconnect counts, backoff windows) for every live
                   Cluster in this process
    /scheduler   - JSON: serving-tier stats for every live statement
                   scheduler (queue depth, inflight batches, admission
                   counters, per-digest coalesce counts)
    /trace       - JSON: summaries of the kept (tail-sampled) traces
                   (?top=N, default 50); /trace?id=<trace_id> returns
                   one trace's full cross-process span tree
    /plan_feedback - JSON: the plan-feedback store (?top=N digests,
                   default 50): per-(digest, plan) est-vs-actual
                   operator cardinalities, warm latencies, eager-agg
                   exploration state, tile-overflow telemetry
    /slo         - JSON: the per-digest latency SLO store (?top=N,
                   default 50): sliding-window p50/p95/p99, breach
                   counts, and burn ratios against tidb_tpu_slo_target_ms
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

__all__ = ["StatusServer"]


class StatusServer:
    def __init__(self, catalog, host: str = "127.0.0.1", port: int = 10080,
                 version: str = ""):
        self.catalog = catalog
        self.version = version
        self.started = time.time()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                try:
                    if self.path == "/metrics" or \
                            self.path.startswith("/metrics?"):
                        from urllib.parse import parse_qs, urlparse

                        q = parse_qs(urlparse(self.path).query)
                        if q.get("scope", [""])[0] == "cluster":
                            from tidb_tpu.parallel.dcn import \
                                fleet_metrics_entries
                            from tidb_tpu.utils.metrics import \
                                render_cluster

                            body = render_cluster(
                                fleet_metrics_entries()).encode()
                        else:
                            from tidb_tpu.utils.metrics import \
                                render_prometheus

                            body = render_prometheus().encode()
                        ctype = "text/plain; version=0.0.4"
                    elif self.path == "/status":
                        from tidb_tpu.utils.metrics import CONN_GAUGE

                        body = json.dumps({
                            "version": outer.version,
                            "status": "ok",
                            "connections": CONN_GAUGE.value(),
                            "schema_version": outer.catalog.schema_version,
                            "uptime_s": round(time.time() - outer.started, 1),
                        }).encode()
                        ctype = "application/json"
                    elif self.path == "/statements" or \
                            self.path.startswith("/statements?"):
                        from urllib.parse import parse_qs, urlparse

                        q = parse_qs(urlparse(self.path).query)
                        try:
                            top = int(q.get("top", ["50"])[0])
                        except ValueError:
                            top = 50
                        body = json.dumps({
                            "statements":
                                outer.catalog.stmt_summary.top(top),
                            "evicted": outer.catalog.stmt_summary.evicted,
                        }).encode()
                        ctype = "application/json"
                    elif self.path == "/plan_cache" or \
                            self.path.startswith("/plan_cache?"):
                        from urllib.parse import parse_qs, urlparse

                        q = parse_qs(urlparse(self.path).query)
                        try:
                            top = int(q.get("top", ["50"])[0])
                        except ValueError:
                            top = 50
                        body = json.dumps(
                            outer.catalog.plan_cache.stats_dict(top)).encode()
                        ctype = "application/json"
                    elif self.path == "/trace" or \
                            self.path.startswith("/trace?"):
                        from urllib.parse import parse_qs, urlparse

                        from tidb_tpu.utils import tracing

                        q = parse_qs(urlparse(self.path).query)
                        tid = q.get("id", [None])[0]
                        if tid is not None:
                            t = tracing.STORE.get(tid)
                            if t is None:
                                self.send_error(404, "no such trace")
                                return
                            body = json.dumps(t.to_dict()).encode()
                        else:
                            try:
                                top = int(q.get("top", ["50"])[0])
                            except ValueError:
                                top = 50
                            body = json.dumps({
                                "traces": tracing.STORE.list(top),
                                "capacity": tracing.STORE.capacity,
                            }).encode()
                        ctype = "application/json"
                    elif self.path == "/plan_feedback" or \
                            self.path.startswith("/plan_feedback?"):
                        from urllib.parse import parse_qs, urlparse

                        from tidb_tpu.planner.feedback import STORE

                        q = parse_qs(urlparse(self.path).query)
                        try:
                            top = int(q.get("top", ["50"])[0])
                        except ValueError:
                            top = 50
                        body = json.dumps(STORE.stats_dict(top)).encode()
                        ctype = "application/json"
                    elif self.path == "/slo" or \
                            self.path.startswith("/slo?"):
                        from urllib.parse import parse_qs, urlparse

                        from tidb_tpu.serving.slo import STORE as slo_store

                        q = parse_qs(urlparse(self.path).query)
                        try:
                            top = int(q.get("top", ["50"])[0])
                        except ValueError:
                            top = 50
                        body = json.dumps(
                            slo_store.stats_dict(top)).encode()
                        ctype = "application/json"
                    elif self.path == "/cluster":
                        from tidb_tpu.parallel.dcn import clusters_alive

                        body = json.dumps({
                            "clusters": [c.health_snapshot()
                                         for c in clusters_alive()],
                        }).encode()
                        ctype = "application/json"
                    elif self.path == "/scheduler":
                        from tidb_tpu.serving import schedulers_alive

                        body = json.dumps({
                            "schedulers": [s.stats_dict()
                                           for s in schedulers_alive()],
                        }).encode()
                        ctype = "application/json"
                    elif self.path == "/schema":
                        # snapshot under the catalog lock: concurrent DDL
                        # mutates these dicts
                        with outer.catalog.lock:
                            snap = {
                                dbn: {tn: t.live_rows
                                      for tn, t in db.tables.items()}
                                for dbn, db in outer.catalog.databases.items()
                            }
                        body = json.dumps(snap).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except BrokenPipeError:
                    pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
