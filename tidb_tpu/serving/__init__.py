"""Serving tier (ISSUE 7): the layer between the MySQL-protocol server
and Session that turns a thread-per-connection SQL node into an
admission-controlled, throughput-oriented statement scheduler.

Two pieces:

``scheduler.py``  — a bounded worker pool with admission control (queue
    depth cap, queue-claim timeout, per-session and server-wide memory
    quotas wired into utils/memory.py's tracker tree) and typed
    rejection errors instead of unbounded thread spawn.

``batcher.py``    — cross-session micro-batching: concurrent statements
    that would hit the plan cache under the SAME key (digest +
    param-type fingerprint + planner sysvars — PR 2's key) on a
    batchable plan coalesce during a short gather window into ONE
    gathered device dispatch, with results de-multiplexed per session
    and every per-statement semantic (warnings, @@last_plan_from_cache,
    stmt-summary, deadlines/KILL) preserved exactly. Anything unsafe
    falls back to singleton execution — a correctness gate, not
    best-effort.
"""

from tidb_tpu.serving.scheduler import (
    StatementScheduler,
    schedulers_alive,
)

__all__ = ["StatementScheduler", "schedulers_alive"]
