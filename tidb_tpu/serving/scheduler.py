"""Admission-controlled statement scheduler (ISSUE 7).

Replaces the wire server's unbounded thread-per-connection execution
with a bounded worker pool: connection threads do protocol I/O only and
``submit_*`` their statements; ``tidb_tpu_scheduler_workers`` workers
execute them (still serialized on the catalog statement lock where the
storage layer demands it). Admission control rejects — with typed,
retry-safe errors — instead of queueing unboundedly:

  * queue depth       — ``tidb_tpu_sched_max_queue`` statements waiting
  * claim timeout     — ``tidb_tpu_sched_queue_timeout_ms`` unclaimed
  * memory            — a server-wide MemTracker root
    (``tidb_tpu_sched_mem_quota``) with per-session child trackers
    (``tidb_tpu_mem_quota_session``); every statement's query tracker
    chains into them (Session._exec_ctx), so quotas see live
    consumption, and admission refuses new work while the server sits
    over budget.

Batchable prepared statements detour through the Batcher (one gathered
dispatch per group); everything else runs singleton on a worker. The
scheduler drains deterministically on shutdown: queued statements
finish (or are rejected, drain=False), workers join, later submissions
get the typed draining rejection.
"""

from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Optional

from tidb_tpu.errors import (
    AdmissionRejectedError,
    SchedulerQueueTimeoutError,
    SLOShedError,
)
from tidb_tpu.serving.batcher import Batcher, BatchGroup
from tidb_tpu.session.sysvars import SysVarStore
from tidb_tpu.utils.memory import MemTracker

__all__ = ["StatementScheduler", "schedulers_alive"]

_SCHEDULERS = weakref.WeakSet()


def schedulers_alive():
    """Live schedulers in this process (the /scheduler endpoint and
    information_schema.scheduler_stats enumerate them)."""
    return list(_SCHEDULERS)


_QUEUED, _RUNNING, _DONE, _EVICTED = range(4)


class _Task:
    """One queued singleton statement."""

    __slots__ = ("session", "fn", "state", "t0", "done", "result", "exc")

    def __init__(self, session, fn):
        self.session = session
        self.fn = fn
        self.state = _QUEUED
        self.t0 = time.perf_counter()
        self.done = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None


class StatementScheduler:
    def __init__(self, catalog, workers: Optional[int] = None):
        self.catalog = catalog
        # GLOBAL-scope knobs read through the catalog's global overlay,
        # exactly like a session would resolve them
        self.sysvars = SysVarStore(catalog.global_vars)
        # server-wide memory root; budget refreshed per admission from
        # tidb_tpu_sched_mem_quota (0 = unlimited)
        self.server_tracker = MemTracker("server", budget=None)
        self.batcher = Batcher(self)
        # cv over a sanitizer-tracked lock (ISSUE 12): worker-thread
        # acquisition orders join the runtime witness graph
        from tidb_tpu.analysis import sanitizer as _san

        self._cv = threading.Condition(
            _san.tracked_lock("StatementScheduler._cv", threading.RLock))
        self._work = collections.deque()  # _Task | BatchGroup
        self._queued = 0                  # admitted, not yet claimed
        self._inflight_batches = 0
        self._draining = False
        self._stop = False
        self.admitted = 0
        self.rejected = 0
        self.timed_out = 0
        n = workers if workers is not None else int(
            self.sysvars.get("tidb_tpu_scheduler_workers"))
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"sched-worker-{i}")
            for i in range(max(1, int(n)))
        ]
        for t in self._workers:
            t.start()
        _SCHEDULERS.add(self)

    # -- session wiring --------------------------------------------------

    def attach_session(self, sess) -> MemTracker:
        """Give `sess` a session-level tracker chained under the server
        root; every statement's query tracker then parents here
        (Session._exec_ctx), so per-session and server-wide quotas see
        live consumption."""
        tr = MemTracker(f"session-{getattr(sess, 'conn_id', 0)}",
                        budget=None, parent=self.server_tracker)
        sess._mem_parent = tr
        return tr

    def _session_tracker(self, sess) -> MemTracker:
        tr = getattr(sess, "_mem_parent", None)
        if tr is None:
            tr = self.attach_session(sess)
        q = int(sess.sysvars.get("tidb_tpu_mem_quota_session"))
        tr.budget = q or None  # re-read per statement: SET takes effect
        return tr

    # -- admission -------------------------------------------------------

    def _shed_digest(self, sess, sql=None, stmt_id=None) -> str:
        """Statement digest for the SLO shed consumer, or "" when
        tidb_tpu_sched_slo_shed is off — the default path computes
        NOTHING and admission decisions stay byte-identical."""
        if not bool(self.sysvars.get("tidb_tpu_sched_slo_shed")):
            return ""
        try:
            if sql is not None:
                from tidb_tpu.bindinfo import normalize_sql, sql_digest

                return sql_digest(normalize_sql(sql))
            ent = sess._prepared.get(stmt_id)
            return ent[4] if ent is not None else ""
        except Exception:  # noqa: BLE001 — a digest failure must never
            return ""      # lose a statement; it just skips the shed

    def _admit(self, shed_digest: str = "") -> None:
        from tidb_tpu.utils import metrics as M

        quota = int(self.sysvars.get("tidb_tpu_sched_mem_quota"))
        self.server_tracker.budget = quota or None
        maxq = int(self.sysvars.get("tidb_tpu_sched_max_queue"))
        # SLO shed (ISSUE 16), deliberately minimal: only when the flag
        # gave us a digest AND the queue is pressured (>= 3/4 full — a
        # racy read by design; pressure is a heuristic, not an
        # invariant) does the burn ranking get consulted. Checked
        # before _cv: the SLO store lock is a leaf and must not nest
        # under the scheduler's.
        if shed_digest and self._queued * 4 >= maxq * 3:
            from tidb_tpu.serving.slo import STORE as _slo

            if _slo.should_shed(shed_digest):
                with self._cv:
                    self.rejected += 1
                M.SCHED_ADMISSION_TOTAL.inc(outcome="rejected")
                M.SLO_SHED_TOTAL.inc()
                raise SLOShedError(
                    "server is busy: shed by SLO burn ranking "
                    f"(digest {shed_digest[:16]} over budget under "
                    "queue pressure; tidb_tpu_sched_slo_shed=1)")
        with self._cv:
            if self._draining:
                why = "statement scheduler is draining (server shutdown)"
            elif self._queued >= maxq:
                why = (f"scheduler queue is full "
                       f"({self._queued} >= tidb_tpu_sched_max_queue={maxq})")
            elif quota and self.server_tracker.consumed >= quota:
                why = (f"server memory quota exhausted "
                       f"({self.server_tracker.consumed} >= "
                       f"tidb_tpu_sched_mem_quota={quota})")
            else:
                self._queued += 1
                self.admitted += 1
                M.SCHED_QUEUE_DEPTH.set(self._queued)
                M.SCHED_ADMISSION_TOTAL.inc(outcome="admitted")
                return
            self.rejected += 1
        M.SCHED_ADMISSION_TOTAL.inc(outcome="rejected")
        raise AdmissionRejectedError(f"server is busy: {why}")

    def _unqueue(self, n: int = 1) -> None:
        from tidb_tpu.utils import metrics as M

        with self._cv:
            self._queued = max(0, self._queued - n)
            M.SCHED_QUEUE_DEPTH.set(self._queued)

    # -- submission ------------------------------------------------------

    def submit_query(self, sess, sql: str):
        """Text-protocol statement: admission + singleton execution on
        a worker (the catalog statement lock is taken by the worker,
        exactly as the thread-per-connection server did). Autocommit
        point writes may instead join a group-commit window (ISSUE 17)
        and ride one merged engine pass."""
        self._admit(self._shed_digest(sess, sql=sql))
        self._session_tracker(sess)
        met = int(sess.sysvars.get("max_execution_time"))
        deadline = (time.monotonic() + met / 1e3) if met > 0 else None
        try:
            member = self.batcher.try_join_dml(sess, sql, deadline)
        except Exception:  # noqa: BLE001 — the probe must never lose a
            member = None  # statement; singleton fallback handles it
        if member is not None:
            return self._await_member(member)
        task = _Task(sess, lambda: sess.execute(sql))
        self._enqueue_task(task)
        return self._await_task(task)

    def submit_prepared(self, sess, stmt_id: int, params: list):
        """Binary-protocol execution: coalescible statements join a
        batch group; everything else runs singleton."""
        self._admit(self._shed_digest(sess, stmt_id=stmt_id))
        self._session_tracker(sess)
        met = int(sess.sysvars.get("max_execution_time"))
        deadline = (time.monotonic() + met / 1e3) if met > 0 else None
        try:
            member = self.batcher.try_join(sess, stmt_id, list(params),
                                           deadline)
        except Exception:  # noqa: BLE001 — the probe must never lose a
            member = None  # statement; singleton fallback handles it
        if member is not None:
            return self._await_member(member)
        task = _Task(sess, lambda: sess.execute_prepared(stmt_id,
                                                         list(params)))
        self._enqueue_task(task)
        return self._await_task(task)

    # -- waiting ---------------------------------------------------------

    def _timeout_s(self) -> float:
        return int(self.sysvars.get("tidb_tpu_sched_queue_timeout_ms")) / 1e3

    def _note_timeout(self):
        from tidb_tpu.utils import metrics as M

        with self._cv:
            self.timed_out += 1
        M.SCHED_ADMISSION_TOTAL.inc(outcome="timed_out")
        raise SchedulerQueueTimeoutError(
            "statement evicted from the scheduler queue after "
            f"{int(self.sysvars.get('tidb_tpu_sched_queue_timeout_ms'))}ms "
            "unclaimed (it never started executing; safe to retry)")

    def _await_task(self, task: _Task):
        if not task.done.wait(self._timeout_s()):
            with self._cv:
                unclaimed = task.state == _QUEUED
                if unclaimed:
                    task.state = _EVICTED
            if unclaimed:
                self._unqueue()
                self._note_timeout()
            task.done.wait()  # claimed: execution owns it, however long
        if task.exc is not None:
            raise task.exc
        return task.result

    def _await_member(self, member):
        if not member.done.wait(self._timeout_s()):
            if self.batcher.try_evict(member):
                self._unqueue()
                self._note_timeout()
            member.done.wait()  # sealed: execution owns it
        if member.exc is not None:
            raise member.exc
        return member.result

    # -- queue / workers -------------------------------------------------

    def _enqueue_task(self, task: _Task) -> None:
        with self._cv:
            self._work.append(task)
            self._cv.notify()

    def enqueue_group(self, group: BatchGroup) -> None:
        with self._cv:
            self._work.append(group)
            self._cv.notify()

    def on_group_sealed(self, group: BatchGroup, n_members: int) -> None:
        """Batcher callback at seal: the members leave the admission
        queue together (evicted ones already left one by one)."""
        if n_members:
            self._unqueue(n_members)

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                while not self._work and not self._stop:
                    self._cv.wait(0.5)
                if not self._work:
                    return  # stopping and drained
                item = self._work.popleft()
            try:
                if isinstance(item, BatchGroup):
                    with self._cv:
                        self._inflight_batches += 1
                    try:
                        self.batcher.run_group(item)
                    finally:
                        with self._cv:
                            self._inflight_batches -= 1
                else:
                    self._run_single(item)
            except Exception:  # noqa: BLE001 — a worker must survive
                # anything one statement does; per-item errors are
                # already relayed through task/member results, so
                # whatever reaches here is bookkeeping-only
                pass

    def _run_single(self, task: _Task) -> None:
        with self._cv:
            if task.state != _QUEUED:
                return  # evicted by a queue timeout
            task.state = _RUNNING
        self._unqueue()
        task.session._sched_queue_s = time.perf_counter() - task.t0
        try:
            # the storage layer is single-writer: statements across
            # sessions serialize on the catalog statement lock, exactly
            # as the thread-per-connection server did
            with self.catalog.lock:
                task.result = task.fn()
        except BaseException as e:  # noqa: BLE001 — relayed verbatim to
            task.exc = e            # the submitting connection thread
        finally:
            task.session._sched_queue_s = 0.0
            task.state = _DONE
            task.done.set()

    # -- lifecycle / stats -----------------------------------------------

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Deterministic drain: stop admitting, let queued work finish
        (drain=True) or reject it typed (drain=False), join workers."""
        rejected = []
        with self._cv:
            self._draining = True
            self._stop = True
            if not drain:
                while self._work:
                    rejected.append(self._work.popleft())
            self._cv.notify_all()
        for item in rejected:
            exc = AdmissionRejectedError(
                "server is busy: statement scheduler shut down before "
                "this statement was claimed")
            if isinstance(item, BatchGroup):
                members = self.batcher.seal_for_shutdown(item)
                self.on_group_sealed(item, len(members))
                for m in members:
                    m.finish(exc=exc)
            else:
                self._unqueue()
                item.exc = exc
                item.state = _DONE
                item.done.set()
        for t in self._workers:
            t.join(timeout)

    def stats_dict(self) -> dict:
        with self._cv:
            d = {
                "workers": len(self._workers),
                "queue_depth": self._queued,
                "inflight_batches": self._inflight_batches,
                "admitted": self.admitted,
                "rejected": self.rejected,
                "timed_out": self.timed_out,
                "draining": self._draining,
                "mem_consumed": int(self.server_tracker.consumed),
                "mem_budget": int(self.server_tracker.budget or 0),
            }
        d.update(self.batcher.snapshot())
        return d
