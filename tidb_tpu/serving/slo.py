"""Per-digest latency SLOs (ISSUE 16): a capacity-bounded sliding
window of recent statement latencies per digest, with percentiles and
an SLO burn ratio against ``tidb_tpu_slo_target_ms``.

ROADMAP item 5 wants admission and micro-batch sizing driven by
"observed per-digest latency/drift instead of static busy-classes";
this store is that observation. Every statement end (success AND
error — what the user waited is what the SLO measures) folds its wall
time into the digest's window; reads expose p50/p95/p99, the breach
count, and the burn ratio:

    burn = (fraction of window observations over target) / (1 - 0.99)

i.e. how many times faster than its error budget the digest is
consuming the 99% objective. burn <= 1.0 is within budget; a digest
steadily at burn 3.0 exhausts a month's budget in ten days.

Surfaces: ``information_schema.digest_latency``, the ``/slo`` status
endpoint, and the ``tidb_tpu_digest_p99_seconds`` gauge (label sets
follow the LRU — an evicted digest's series is removed, not frozen).

One deliberately-minimal consumer exists behind
``tidb_tpu_sched_slo_shed`` (default OFF): under admission queue
pressure the scheduler sheds statements whose digest is burning its
budget fastest (``should_shed``), with a typed 9008 rejection. Plans
and results are NEVER affected — the consumer only picks which
statements wait when the server is saturated anyway.

Concurrency: the store lock is a LEAF like ``planner/feedback.py``'s —
fold/read only under it; the DIGEST_P99 gauge update and eviction
cleanup (which take the metric's own lock) happen after it is
released. The lock-discipline and blocking-under-lock passes check
this module.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from typing import List, Optional

__all__ = ["DigestLatencyStore", "STORE", "DEFAULT_CAPACITY",
           "DEFAULT_TARGET_MS", "WINDOW", "OBJECTIVE"]

DEFAULT_CAPACITY = 512

# default latency objective per statement execution; overridden by the
# tidb_tpu_slo_target_ms sysvar at observe time
DEFAULT_TARGET_MS = 300.0

# sliding window of recent latencies per digest: enough for a stable
# p99 without unbounded growth on hot statements (stmtsummary's ring
# rule, sized up for the tail percentile)
WINDOW = 256

# the objective fraction: 99% of a digest's executions under target.
# Its complement (0.01) is the error budget the burn ratio divides by.
OBJECTIVE = 0.99


def _pct(xs: List[float], q: float) -> float:
    """Percentile of a non-empty sorted list (stmtsummary's estimator)."""
    return xs[min(len(xs) - 1, int(q * (len(xs) - 1) + 0.5))]


class _Entry:
    __slots__ = ("digest", "digest_text", "lat", "execs", "breaches",
                 "burn", "p99_s", "target_ms", "last_seen")

    def __init__(self, digest: str, digest_text: str):
        self.digest = digest
        self.digest_text = digest_text
        self.lat: deque = deque(maxlen=WINDOW)  # seconds
        self.execs = 0
        self.breaches = 0       # lifetime, vs target at observe time
        self.burn = 0.0         # cached at observe (should_shed is hot)
        self.p99_s = 0.0
        self.target_ms = DEFAULT_TARGET_MS  # target in force last observe
        self.last_seen = time.time()


class DigestLatencyStore:
    """Process-global, capacity-bounded (LRU on digest) latency-SLO
    store. The lock is a LEAF: fold/read only — metric updates happen
    outside it."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        from tidb_tpu.analysis import sanitizer as _san

        # tracked like PlanFeedbackStore.lock: a future consumer that
        # nests this under another registered lock shows up as a cycle
        # finding, not a hang
        self.lock = _san.tracked_lock("DigestLatencyStore.lock")
        self.capacity = capacity
        self._by_digest: "OrderedDict[str, _Entry]" = OrderedDict()
        self.evicted = 0

    # -- recording ----------------------------------------------------------

    def observe(self, digest: str, digest_text: str, latency_s: float,
                target_ms: float = DEFAULT_TARGET_MS,
                capacity: Optional[int] = None) -> None:
        """Fold one execution's wall time into the digest's window and
        refresh its cached burn/p99. Gauge updates and eviction cleanup
        run after the store lock is released (leaf-lock rule)."""
        if not digest:
            return
        target_s = max(float(target_ms), 0.0) / 1e3
        evicted_digests: List[str] = []
        with self.lock:
            if capacity is not None:
                self.capacity = max(1, int(capacity))
            e = self._by_digest.get(digest)
            if e is None:
                # bound retained text like the statements summary does
                e = _Entry(digest, digest_text[:2048])
                self._by_digest[digest] = e
            self._by_digest.move_to_end(digest)
            e.execs += 1
            e.target_ms = float(target_ms)
            e.lat.append(float(latency_s))
            if target_s and latency_s > target_s:
                e.breaches += 1
            xs = sorted(e.lat)
            e.p99_s = _pct(xs, 0.99)
            over = sum(1 for v in e.lat if target_s and v > target_s)
            e.burn = (over / len(e.lat)) / (1.0 - OBJECTIVE)
            e.last_seen = time.time()
            p99 = e.p99_s
            while len(self._by_digest) > self.capacity:
                old, _ = self._by_digest.popitem(last=False)
                evicted_digests.append(old)
                self.evicted += 1
        from tidb_tpu.utils.metrics import DIGEST_P99

        DIGEST_P99.set(round(p99, 6), digest=digest)
        for old in evicted_digests:
            DIGEST_P99.remove(digest=old)

    # -- the shed consumer --------------------------------------------------

    def should_shed(self, digest: str) -> bool:
        """True when this digest is burning its budget fastest: over
        budget (burn > 1.0) AND within 10% of the worst burner tracked
        — under saturation the scheduler sheds the statements already
        blowing their SLO, preserving budget for the ones still inside
        it. Cheap by design (cached burns, one O(capacity) scan): this
        runs on the admission path, though only when
        tidb_tpu_sched_slo_shed is on AND the queue is pressured."""
        if not digest:
            return False
        with self.lock:
            e = self._by_digest.get(digest)
            if e is None or e.burn <= 1.0:
                return False
            worst = max(x.burn for x in self._by_digest.values())
            return e.burn >= 0.9 * worst

    # -- read side ----------------------------------------------------------

    def burn(self, digest: str) -> float:
        with self.lock:
            e = self._by_digest.get(digest)
            return e.burn if e is not None else 0.0

    def rows(self) -> List[tuple]:
        """information_schema.digest_latency rows (latencies in ms;
        target_ms = the sysvar value in force at the digest's last
        observation), worst burn first."""
        with self.lock:
            entries = list(self._by_digest.values())
            out = []
            for e in entries:
                xs = sorted(e.lat)
                out.append((
                    e.digest, e.digest_text, len(e.lat), e.execs,
                    round(_pct(xs, 0.50) * 1e3, 3) if xs else 0.0,
                    round(_pct(xs, 0.95) * 1e3, 3) if xs else 0.0,
                    round(_pct(xs, 0.99) * 1e3, 3) if xs else 0.0,
                    round(e.target_ms, 3), e.breaches,
                    round(e.burn, 4),
                    time.strftime("%Y-%m-%d %H:%M:%S",
                                  time.localtime(e.last_seen)),
                ))
        out.sort(key=lambda r: r[9], reverse=True)
        return out

    def stats_dict(self, top: int = 50) -> dict:
        """/slo endpoint payload."""
        cols = ("digest", "digest_text", "window_n", "execs", "p50_ms",
                "p95_ms", "p99_ms", "target_ms", "breaches",
                "burn_ratio", "last_seen")
        with self.lock:
            capacity, evicted = self.capacity, self.evicted
        return {
            "digests": [dict(zip(cols, r))
                        for r in self.rows()[:max(0, top)]],
            "capacity": capacity,
            "evicted": evicted,
            "objective": OBJECTIVE,
        }

    def __len__(self) -> int:
        with self.lock:
            return len(self._by_digest)

    def clear(self) -> None:
        with self.lock:
            digests = list(self._by_digest)
            self._by_digest.clear()
            self.evicted = 0
        from tidb_tpu.utils.metrics import DIGEST_P99

        for d in digests:
            DIGEST_P99.remove(digest=d)


STORE = DigestLatencyStore()
