"""Cross-session micro-batching (the tentpole of ISSUE 7).

The OLTP hot case PR 2 built — plan-cache-hit statements differing only
in bound parameters — is exactly the shape inference servers coalesce:
many same-shaped requests, one batched device entry. Here, concurrent
prepared point-selects whose plan-cache keys match (same digest +
param-type fingerprint + planner sysvars) gather for a short window
(``tidb_tpu_batch_window_us``) and execute as ONE pass:

  1. per member: the O(log n) unique-index probe resolves its key to
     visible row ids (the members' params, stacked along the batch axis,
     drive N probes against one shared index cache);
  2. one gather over the UNION of every member's rows builds one chunk;
  3. the (parameter-free, shared) projection pipeline runs ONCE;
  4. one host materialization, then a positional split hands each
     member exactly the rows its singleton execution would have built.

Per-statement semantics stay exact because each member still passes
through ``Session._execute_timed`` — with the executor swapped for a
runner returning its pre-demuxed slice — so warnings reset, deadlines,
KILL, tracing (``sched.batch[n=N]`` spans), the statements summary and
the slow log all behave as if the statement ran alone. A member killed
or expired while gathering leaves the batch with its typed error; the
batch itself is never aborted. Any failure of the shared pass falls
back to full singleton execution for every member — the correctness
gate the ISSUE demands, not best-effort.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

__all__ = ["Batcher", "BatchGroup", "Member"]


class _DmlFallback(Exception):
    """Raised inside a group-commit pass to abort the (not yet
    committed) group transaction and send every member to singleton
    execution — the same correctness gate the read batcher's
    shared-pass fallback provides."""


class Member:
    """One admitted, coalescible statement waiting for its result."""

    __slots__ = ("session", "stmt_id", "params", "info", "t0", "deadline",
                 "group", "done", "result", "exc", "timed_out", "drop",
                 "sql")

    def __init__(self, session, stmt_id: int, params: list, info,
                 deadline: Optional[float], sql: Optional[str] = None):
        self.session = session
        self.stmt_id = stmt_id
        self.params = params
        self.info = info                  # StmtInfo / DML spec from the probe
        self.sql = sql                    # text-protocol member (DML window)
        self.t0 = time.perf_counter()     # for the sched.queue span
        self.deadline = deadline          # monotonic; None = unbounded
        self.group: Optional["BatchGroup"] = None
        self.done = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None
        self.timed_out = False
        # typed error captured at finalize time for a member killed or
        # deadline-expired during the gather (raised by its runner so
        # the statement still flows through _execute_timed's error path)
        self.drop: Optional[BaseException] = None

    def finish(self, result=None, exc: Optional[BaseException] = None):
        self.result = result
        self.exc = exc
        self.done.set()


class BatchGroup:
    """Members sharing one plan-cache key, gathering toward one
    dispatch. ``cv`` guards the fill signal and wakes the gathering
    worker early when the group fills; the gather wait holds NO other
    lock (the lock-discipline pass enforces this for serving/)."""

    def __init__(self, key, entry, window_s: float, max_size: int):
        self.key = key
        self.entry = entry
        self.window_s = window_s
        self.max_size = max_size
        self.created = time.monotonic()
        from tidb_tpu.analysis import sanitizer as _san

        self.cv = threading.Condition(
            _san.tracked_lock("BatchGroup.cv", threading.RLock))
        self.members: List[Member] = []
        self.sealed = False
        # group-commit DML window (ISSUE 17): the opening member's spec
        # (shape fields — kind/table/SET columns — are digest-identical
        # across members); None = a read batch
        self.dml = None


class Batcher:
    def __init__(self, scheduler):
        from tidb_tpu.analysis import sanitizer as _san

        self.scheduler = scheduler
        self._lock = _san.tracked_lock("Batcher._lock")
        self._open: Dict[object, BatchGroup] = {}
        self._seq = itertools.count(1)
        # per-digest coalesce counts for information_schema.scheduler_stats
        self._coalesced_by_digest: Dict[str, int] = {}
        self.batches = 0            # groups executed (any size)
        self.coalesced_stmts = 0    # members of n>=2 groups
        # internal session owning group-commit DML transactions (lazy:
        # read-only deployments never create it)
        self._writer = None

    # -- submit side ----------------------------------------------------

    def try_join(self, session, stmt_id: int, params: list,
                 deadline: Optional[float]) -> Optional[Member]:
        """Coalesce this prepared execution into an open group (or open
        a group and enqueue its gather task). None = not coalescible;
        the caller runs the singleton path."""
        sched = self.scheduler
        window_us = int(sched.sysvars.get("tidb_tpu_batch_window_us"))
        if window_us <= 0:
            return None
        probe = session.batch_probe(stmt_id, params)
        if probe is None:
            return None
        key, entry, info = probe
        member = Member(session, stmt_id, params, info, deadline)
        return self._join(key, member, window_us, entry=entry)

    def try_join_dml(self, session, sql: str,
                     deadline: Optional[float]) -> Optional[Member]:
        """Coalesce an autocommit text-protocol point write into an
        open group-commit window (ISSUE 17). Same gather/seal machinery
        as reads — the keys carry a "dml" marker so a write window can
        never mix with a read batch. None = not coalescible."""
        sched = self.scheduler
        window_us = int(sched.sysvars.get("tidb_tpu_batch_window_us"))
        if window_us <= 0:
            return None
        probe = session.dml_batch_probe(sql)
        if probe is None:
            return None
        key, spec = probe
        member = Member(session, -1, [], spec, deadline, sql=sql)
        return self._join(key, member, window_us, dml=spec)

    def _join(self, key, member: Member, window_us: int, entry=None,
              dml=None) -> Member:
        """Append `member` to the open group for `key`, or open a fresh
        group and enqueue its gather task."""
        sched = self.scheduler
        max_size = int(sched.sysvars.get("tidb_tpu_max_batch_size"))
        with self._lock:
            g = self._open.get(key)
            if g is not None and not g.sealed and len(g.members) < max_size:
                g.members.append(member)
                member.group = g
                full = len(g.members) >= max_size
                enqueue = False
            else:
                g = BatchGroup(key, entry, window_us / 1e6, max_size)
                g.dml = dml
                g.members.append(member)
                member.group = g
                self._open[key] = g
                enqueue = True
                full = max_size <= 1
        if enqueue:
            sched.enqueue_group(g)
        if full:
            with g.cv:
                g.cv.notify_all()
        return member

    def try_evict(self, member: Member) -> bool:
        """Queue-timeout eviction: remove `member` from a still-open
        group. False once the group sealed — execution owns it now and
        the caller must keep waiting for the result."""
        with self._lock:
            g = member.group
            if g is None or g.sealed:
                return False
            try:
                g.members.remove(member)
            except ValueError:
                return False
            member.timed_out = True
            return True

    def seal_for_shutdown(self, group: BatchGroup) -> List[Member]:
        """Scheduler shutdown(drain=False): seal `group` without
        executing it and hand back its members for typed rejection.
        Same seal sequence as run_group so `_open` never retains a
        sealed group."""
        with self._lock:
            group.sealed = True
            if self._open.get(group.key) is group:
                del self._open[group.key]
            return list(group.members)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "open_groups": len(self._open),
                "batches": self.batches,
                "coalesced_stmts": self.coalesced_stmts,
                "coalesce_by_digest": dict(self._coalesced_by_digest),
            }

    # -- worker side ----------------------------------------------------

    def run_group(self, group: BatchGroup) -> None:
        """Gather (lock-free wait), seal, execute, demux."""
        deadline = group.created + group.window_s
        # adaptive seal: submitters arrive as a wave (each blocked
        # client re-submits right after its previous result); once no
        # member has joined for a fraction of the window, the wave has
        # landed and waiting out the rest is pure latency
        idle_gap = max(group.window_s / 4.0, 100e-6)
        with group.cv:
            while len(group.members) < group.max_size:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                n0 = len(group.members)
                group.cv.wait(min(rem, idle_gap))
                if group.members and len(group.members) == n0:
                    break  # no growth for idle_gap
        with self._lock:
            group.sealed = True
            if self._open.get(group.key) is group:
                del self._open[group.key]
            members = list(group.members)
        self.scheduler.on_group_sealed(group, len(members))
        if not members:
            return  # every member timed out of the queue while gathering
        from tidb_tpu.utils import metrics as M

        n = len(members)
        if group.dml is not None:
            M.DML_BATCH_SIZE.observe(n)
        else:
            M.BATCH_SIZE.observe(n)
        with self._lock:
            self.batches += 1
            if n >= 2:
                self.coalesced_stmts += n
                d = self._coalesced_by_digest
                d[group.key[0]] = d.get(group.key[0], 0) + n
                if len(d) > 256:
                    d.pop(next(iter(d)))
        if n >= 2:
            M.BATCH_COALESCE_TOTAL.inc(n)
        if group.dml is not None:
            self._execute_dml(group, members)
        else:
            self._execute(group, members)

    # -- the one gathered dispatch --------------------------------------

    def _execute(self, group: BatchGroup, members: List[Member]) -> None:
        """One device pass for every member, then per-member
        finalization through Session._execute_timed. The whole batch
        shares one catalog-lock acquisition (all members read the same
        committed snapshot — commits serialize on that lock), one plan
        instantiation shape and one executor pipeline."""
        catalog = self.scheduler.catalog
        batch_id = next(self._seq)
        with catalog.lock:
            try:
                shared = self._shared_pass(group, members)
            except Exception:  # noqa: BLE001 — ANY shared-pass failure
                # falls back to full-fidelity singleton execution: the
                # batch is an optimization, never a correctness risk
                shared = None
            n = len(members)
            for i, m in enumerate(members):
                runner = (None if shared is None
                          else self._member_runner(shared, i, n, batch_id, m))
                self._finalize(m, runner)

    def _shared_pass(self, group: BatchGroup, members: List[Member]):
        """The stacked-params pass. Returns a dict consumed by
        _member_runner, or None when the cached entry no longer
        validates (DDL/ANALYZE raced the gather window) — the members
        then re-plan individually through the normal path."""
        import numpy as np

        from tidb_tpu.chunk.chunk import Chunk
        from tidb_tpu.chunk.column import Column
        from tidb_tpu.executor.builder import peel_stages
        from tidb_tpu.executor.scan import make_pipeline_fn
        from tidb_tpu.planner import plancache as _pc
        from tidb_tpu.planner.physical import PProjection
        from tidb_tpu.utils.device import host_eager

        catalog = self.scheduler.catalog
        cache = getattr(catalog, "plan_cache", None)
        if cache is None:
            return None
        # re-validate under the catalog lock: stale pinned tables must
        # never serve the batch (schema_version / stats identity checks
        # run inside lookup, exactly as a singleton probe would)
        entry = cache.lookup(group.key, catalog.schema_version)
        if entry is not group.entry or entry is None or entry.patches is None:
            return None
        if catalog.has_stale_txns():
            catalog.resolve_locks()  # reader-side resolve, like _execute_timed
        leader = _pc.instantiate(entry, members[0].info.params)

        def point_node(plan):
            node = plan
            while isinstance(node, PProjection):
                node = node.children[0]
            return node

        pg0 = point_node(leader)
        table, index_name = pg0.table, pg0.index_name
        row_sets = []
        for m in members:
            pg = pg0 if m is members[0] else point_node(
                _pc.instantiate(entry, m.info.params))
            row_sets.append(np.asarray(
                table.index_lookup(index_name, pg.key_values),
                dtype=np.int64))
        counts = [len(r) for r in row_sets]
        offsets = [0]
        for c in counts:
            offsets.append(offsets[-1] + c)
        total = offsets[-1]
        all_ids = (np.concatenate(row_sets) if total
                   else np.zeros(0, dtype=np.int64))
        cap = 8
        while cap < total:
            cap *= 2
        cols = {}
        row_bytes = 0
        for c in pg0.schema:  # storage columns of the point access
            if c.name == "__rowid__":
                d = all_ids
                v = np.ones(total, dtype=np.bool_)
            else:
                d = table.data[c.name][all_ids]
                v = table.valid[c.name][all_ids]
            row_bytes += int(getattr(d, "itemsize", 8)) + 1
            cols[c.uid] = Column.from_numpy(d, c.type_, valid=v, capacity=cap)
        sel = np.zeros(cap, dtype=np.bool_)
        sel[:total] = True
        chunk = Chunk(cols, sel)
        # batchable_plan guarantees the peeled stages are projections
        # only (parameter-free, 1:1 on rows), so ONE eager pipeline run
        # serves every member and the positional split below is exact
        stages, _base = peel_stages(leader)
        with host_eager():
            if stages:
                chunk = make_pipeline_fn(stages)(chunk)
        n_vis = leader.n_visible if isinstance(leader, PProjection) else None
        schema = leader.schema
        visible = schema if n_vis is None else schema[:n_vis]
        dicts = {c.uid: c.dict_ for c in visible if c.dict_ is not None}
        rows_all = chunk.to_pylist(dicts=dicts,
                                   names=[c.uid for c in visible])
        return {
            "entry": entry,
            "phys": leader,
            "rows": rows_all,
            "offsets": offsets,
            "row_bytes": row_bytes,
            "names": [c.name for c in visible],
            "types": [c.type_.kind for c in visible],
            "sql_types": [c.type_ for c in visible],
            "collations": [getattr(c.dict_, "collation", None)
                           for c in visible],
        }

    def _member_runner(self, shared: dict, i: int, n: int, batch_id: int,
                       member: Member):
        """The injected _stmt_runner for member `i`: raises the typed
        drop error for a killed/expired member, else books the cache
        hit + memory charge + sched.batch span and returns the member's
        pre-demuxed ResultSet."""
        entry = shared["entry"]
        lo, hi = shared["offsets"][i], shared["offsets"][i + 1]
        rows = shared["rows"][lo:hi]
        est = int(shared["row_bytes"]) * (hi - lo)
        sess = member.session

        def run(_stmt):
            if member.drop is not None:
                raise member.drop
            from tidb_tpu.executor.base import ResultSet
            from tidb_tpu.utils import tracing

            with tracing.span(f"sched.batch[n={n}]"):
                tracing.annotate(f"batch:{batch_id} member:{i} "
                                 f"rows:{len(rows)}")
                ctx = sess._exec_ctx(plan=shared["phys"])
                if est:
                    # per-member accounting: propagates into the
                    # session/server trackers; a quota breach cancels
                    # THIS member only (typed OOM), never the batch.
                    # lifecycle: the statement tracker owns the charge —
                    # Session._execute_timed detach()es it (residuals
                    # included) at this member's statement end
                    ctx.mem_tracker.consume(est)
                cache = sess.catalog.plan_cache
                cache.note_hit(entry)
                sess.sysvars.set("last_plan_from_cache", True, "session")
                sess._plan_from_cache_stmt = True
                if not entry.plan_digest:
                    import hashlib as _hl

                    from tidb_tpu.planner.physical import explain_text

                    entry.plan_digest = _hl.sha256(
                        explain_text(entry.phys).encode()).hexdigest()[:32]
                sess._last_plan_digest = entry.plan_digest
                return ResultSet(names=shared["names"], rows=rows,
                                 types=shared["types"],
                                 sql_types=shared["sql_types"],
                                 collations=shared["collations"])

        return run

    def _finalize(self, member: Member, runner) -> None:
        """Run one member through Session._execute_timed on this worker
        thread (the member's connection thread is parked on its done
        event). runner=None re-executes the statement singleton-style —
        the shared-pass fallback."""
        import time as _time

        from tidb_tpu.errors import QueryKilledError, QueryTimeoutError

        sess = member.session
        # kill/deadline observed during the gather: the member leaves
        # the batch with its typed error. Captured HERE because
        # _execute_timed consumes the one-shot kill flag at entry.
        if sess._kill_query:
            member.drop = QueryKilledError(
                "Query execution was interrupted (KILL)")
        elif member.deadline is not None and \
                _time.monotonic() > member.deadline:
            member.drop = QueryTimeoutError(
                "Query execution was interrupted, maximum statement "
                "execution time exceeded")
        if member.drop is not None and runner is None:
            def runner(_stmt):  # noqa: F811 — fallback member, same drop
                raise member.drop
        sess._stmt_runner = runner
        sess._sched_queue_s = _time.perf_counter() - member.t0
        try:
            if member.sql is not None:
                res = sess.execute(member.sql)
            else:
                res = sess.execute_prepared(member.stmt_id, member.params)
        except BaseException as e:  # noqa: BLE001 — relayed verbatim to
            member.finish(exc=e)    # the submitting connection thread
        else:
            member.finish(result=res)
        finally:
            sess._stmt_runner = None
            sess._sched_queue_s = 0.0

    # -- group-commit DML (ISSUE 17) ------------------------------------

    def _dml_writer(self):
        """The internal session owning group-commit transactions. Not a
        client connection: removed from the process list so KILL can
        never target the shared writer."""
        if self._writer is None:
            from tidb_tpu.session.session import Session

            w = Session(self.scheduler.catalog)
            w.catalog.processes.pop(w.conn_id, None)
            self._writer = w
        return self._writer

    def _execute_dml(self, group: BatchGroup, members: List[Member]) -> None:
        """One engine pass for every live member's point write — one
        merged insert/update/delete inside ONE writer transaction —
        then per-member finalization through Session._execute_timed.
        Any failure of the merged pass rolls the group transaction back
        (``_run_dml`` aborts implicit txns on any exception) and every
        member re-executes singleton-style with its exact typed error."""
        catalog = self.scheduler.catalog
        batch_id = next(self._seq)
        with catalog.lock:
            try:
                included = self._dml_pass(group, members)
            except Exception:  # noqa: BLE001 — ANY group-commit failure
                # (conflict shapes, schema race, engine error) aborted
                # the group txn; singleton re-execution is exact
                included = None
            n = len(members)
            for i, m in enumerate(members):
                runner = (self._dml_runner(i, n, batch_id, m)
                          if included is not None and included[i] else None)
                self._finalize(m, runner)

    def _dml_pass(self, group: BatchGroup,
                  members: List[Member]) -> List[bool]:
        """The merged write. Runs under catalog.lock in the writer
        session's own (implicit, autocommit) transaction: one index
        probe stack, one delta append / MVCC marker write, one commit.
        Returns the per-member inclusion mask — members killed or
        expired before the pass are excluded and get their typed error
        from _finalize without having written anything."""
        import time as _time

        import numpy as np

        catalog = self.scheduler.catalog
        # drop snapshot at T1: the kill flag is only consumed at
        # statement entry and deadlines are monotone, so _finalize's
        # re-check re-derives the same typed error for excluded members
        included = []
        now = _time.monotonic()
        for m in members:
            sess = m.session
            dead = m.deadline is not None and now > m.deadline
            included.append(not (sess._kill_query or sess._killed or dead))
        live = [m for m, ok in zip(members, included) if ok]
        if not live:
            return included
        if catalog.schema_version != group.key[4]:
            raise _DmlFallback("schema changed during gather")
        spec0 = group.dml
        table = catalog.table(spec0["db"], spec0["table"])
        kind = spec0["kind"]
        writer = self._dml_writer()

        if kind == "insert":
            rows = []
            for m in live:
                rows.extend(m.info["rows"])

            def do(txn):
                table.insert_rows(rows, columns=spec0["columns"],
                                  begin_ts=txn.marker,
                                  log=txn.log_for(table))
        else:
            def probe(txn):
                sets_ids = []
                for m in live:
                    ids = np.asarray(table.index_lookup(
                        m.info["index"], m.info["key"],
                        read_ts=txn.read_ts, marker=txn.marker),
                        dtype=np.int64)
                    sets_ids.append(ids)
                return sets_ids

            if kind == "update":
                def do(txn):
                    sets_ids = probe(txn)
                    all_ids = (np.concatenate(sets_ids) if sets_ids
                               else np.zeros(0, dtype=np.int64))
                    if len(all_ids) == 0:
                        return
                    if len(np.unique(all_ids)) != len(all_ids):
                        # two members hit the same row: serial order
                        # matters (k+2 vs k+1) — group cannot be exact
                        raise _DmlFallback("duplicate target rows")
                    updates = {name: [] for name, _, _ in live[0].info["sets"]}
                    for m, ids in zip(live, sets_ids):
                        k = len(ids)
                        for name, mode, val in m.info["sets"]:
                            if mode == "const":
                                updates[name].extend([val] * k)
                            else:  # delta: col ± literal on OLD values
                                src, op, delta = val
                                d = table.data[src][ids].tolist()
                                v = table.valid[src][ids].tolist()
                                for dv, ok in zip(d, v):
                                    if not ok:
                                        updates[name].append(None)
                                    elif op == "+":
                                        updates[name].append(dv + delta)
                                    else:
                                        updates[name].append(dv - delta)
                    table.update_rows(all_ids.tolist(), updates,
                                      begin_ts=txn.marker,
                                      end_ts=txn.marker, marker=txn.marker,
                                      log=txn.log_for(table),
                                      log_for=txn.log_for)
            else:  # delete — dup ids dedup to ONE marker, serial-exact
                def do(txn):
                    sets_ids = probe(txn)
                    all_ids = (np.concatenate(sets_ids) if sets_ids
                               else np.zeros(0, dtype=np.int64))
                    if len(all_ids) == 0:
                        return
                    all_ids = np.unique(all_ids)
                    table.delete_rows(all_ids.tolist(), end_ts=txn.marker,
                                      marker=txn.marker,
                                      log=txn.log_for(table),
                                      log_for=txn.log_for)

        writer._run_dml(do)
        return included

    def _dml_runner(self, i: int, n: int, batch_id: int, member: Member):
        """The injected _stmt_runner for an applied group-commit member:
        its write already committed in the merged pass, so the runner
        only books the batch span (DML returns no rows in this engine)."""

        def run(_stmt):
            if member.drop is not None:
                raise member.drop
            from tidb_tpu.utils import tracing

            with tracing.span(f"sched.batch[n={n}]"):
                tracing.annotate(f"batch:{batch_id} member:{i} dml:applied")
                return None

        return run
