"""Table/column statistics feeding the cost model.

Ref counterpart: statistics/ (histograms, NDV, auto-analyze feeding
planner/core's cost-based search). Here ANALYZE TABLE collects, per
column: NDV, null count, min/max, and an equi-depth histogram over the
live rows; the planner consumes them for scan selectivity and join
cardinality (planner/physical.py, planner/rules.py join reordering).

Stats are version-stamped: a table mutation bumps table.version, and
estimates silently degrade to the no-stats heuristics until the next
ANALYZE — the same freshness model as the reference's stale-stats
behavior, without its feedback loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from tidb_tpu.types import TypeKind

__all__ = ["ColumnStats", "TableStats", "analyze_table", "table_stats",
           "scan_selectivity", "column_ndv", "HIST_BUCKETS"]

HIST_BUCKETS = 64


@dataclass
class ColumnStats:
    ndv: int
    null_count: int
    min: Optional[float] = None
    max: Optional[float] = None
    # equi-depth histogram: `bounds` are the sorted values at the bucket
    # quantiles (len <= HIST_BUCKETS+1); each bucket holds ~equal rows
    bounds: Optional[np.ndarray] = None


@dataclass
class TableStats:
    n_rows: int
    version: int
    cols: Dict[str, ColumnStats] = field(default_factory=dict)


def analyze_table(table) -> TableStats:
    """Collect stats over the live rows of a host table."""
    n = table.n
    live = np.asarray(table.live_mask(0, n)) if n else np.zeros(0, dtype=bool)
    n_live = int(live.sum())
    stats = TableStats(n_rows=n_live, version=table.version)
    for c in table.schema.columns:
        data, valid = table.column_slice(c.name, 0, n)
        data, valid = np.asarray(data)[live], np.asarray(valid)[live]
        vals = data[valid]
        null_count = n_live - len(vals)
        if len(vals) == 0:
            stats.cols[c.name] = ColumnStats(ndv=0, null_count=null_count)
            continue
        sv = np.sort(vals.astype(np.float64, copy=False))
        ndv = int(1 + np.count_nonzero(np.diff(sv)))
        idx = np.linspace(0, len(sv) - 1, min(HIST_BUCKETS + 1, len(sv))).astype(np.int64)
        stats.cols[c.name] = ColumnStats(
            ndv=ndv, null_count=null_count,
            min=float(sv[0]), max=float(sv[-1]),
            bounds=sv[idx],
        )
    table.stats = stats
    return stats


def table_stats(table) -> Optional[TableStats]:
    """Current stats if fresh (collected at this table version)."""
    s = getattr(table, "stats", None)
    if s is not None and s.version == table.version:
        return s
    return None


# ---------------------------------------------------------------------------
# estimation
# ---------------------------------------------------------------------------


def column_ndv(table, col_name: str) -> Optional[float]:
    s = table_stats(table)
    if s is None or col_name not in s.cols:
        return None
    return max(float(s.cols[col_name].ndv), 1.0)


def _range_fraction(cs: ColumnStats, lo: float, hi: float) -> float:
    """Fraction of non-null rows with lo <= value <= hi (equi-depth
    interpolation)."""
    b = cs.bounds
    if b is None or len(b) < 2 or cs.min is None:
        return 0.33
    if hi < cs.min or lo > cs.max:
        return 0.0
    # position of a value in row-fraction space: bucket index + linear
    # interpolation inside the bucket
    nb = len(b) - 1

    def frac(x: float, side: str) -> float:
        i = int(np.searchsorted(b, x, side="left" if side == "lo" else "right"))
        if i <= 0:
            return 0.0
        if i > nb:
            return 1.0
        lo_b, hi_b = b[i - 1], b[min(i, nb)]
        inner = 0.0 if hi_b <= lo_b else (x - lo_b) / (hi_b - lo_b)
        return ((i - 1) + min(max(inner, 0.0), 1.0)) / nb

    f = frac(hi, "hi") - frac(lo, "lo")
    return min(max(f, 0.0), 1.0)


def _conjuncts(cond):
    from tidb_tpu.expression.expr import Call

    if isinstance(cond, Call) and cond.op == "and":
        for a in cond.args:
            yield from _conjuncts(a)
    else:
        yield cond


_CMP = {"eq", "ne", "lt", "le", "gt", "ge"}


def _pred_selectivity(stats: TableStats, pred, uid_to_col: Dict[str, str]) -> float:
    from tidb_tpu.expression.expr import Call, ColumnRef, InList, Literal

    if isinstance(pred, Call) and pred.op in _CMP and len(pred.args) == 2:
        a, b = pred.args
        if isinstance(b, ColumnRef) and isinstance(a, Literal):
            a, b = b, a
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}
            op = flip.get(pred.op, pred.op)
        else:
            op = pred.op
        if isinstance(a, ColumnRef) and isinstance(b, Literal) and b.value is not None:
            col = uid_to_col.get(a.name)
            cs = stats.cols.get(col) if col else None
            if cs is None:
                return {"eq": 0.1, "ne": 0.9}.get(op, 0.33)
            nn = max(stats.n_rows - cs.null_count, 1)
            v = float(b.value)
            if op == "eq":
                return min(1.0 / max(cs.ndv, 1), 1.0) * (nn / max(stats.n_rows, 1))
            if op == "ne":
                return (1.0 - 1.0 / max(cs.ndv, 1)) * (nn / max(stats.n_rows, 1))
            if op in ("lt", "le"):
                f = _range_fraction(cs, -np.inf, v)
            else:
                f = _range_fraction(cs, v, np.inf)
            return f * (nn / max(stats.n_rows, 1))
    if isinstance(pred, InList) and isinstance(pred.arg, ColumnRef):
        col = uid_to_col.get(pred.arg.name)
        cs = stats.cols.get(col) if col else None
        if cs is not None:
            f = min(len(pred.values) / max(cs.ndv, 1), 1.0)
            return 1.0 - f if pred.negated else f
    if isinstance(pred, Call) and pred.op == "or":
        s = 0.0
        for a in pred.args:
            s = s + _pred_selectivity(stats, a, uid_to_col) * (1 - s)
        return min(s, 1.0)
    if isinstance(pred, Call) and pred.op == "is_null":
        arg = pred.args[0]
        if isinstance(arg, ColumnRef):
            col = uid_to_col.get(arg.name)
            cs = stats.cols.get(col) if col else None
            if cs is not None:
                return cs.null_count / max(stats.n_rows, 1)
    return 0.33


def scan_selectivity(table, cond, uid_to_col: Dict[str, str]) -> float:
    """Estimated fraction of rows passing `cond` (compiled IR over scan
    uids); falls back to fixed heuristics without fresh stats."""
    stats = table_stats(table)
    if stats is None or stats.n_rows == 0:
        n = sum(1 for _ in _conjuncts(cond))
        return 0.25 ** min(n, 2)
    sel = 1.0
    for pred in _conjuncts(cond):
        sel *= _pred_selectivity(stats, pred, uid_to_col)
    return min(max(sel, 1.0 / max(stats.n_rows, 1)), 1.0)
