"""Table/column statistics feeding the cost model.

Ref counterpart: statistics/ (histograms, CMSketch+TopN, NDV,
auto-analyze feeding planner/core's cost-based search). Here ANALYZE
TABLE collects, per column: NDV, null count, min/max, an equi-depth
histogram, and a most-common-values (MCV/TopN) list over the live rows;
the planner consumes them for scan selectivity and join cardinality
(planner/physical.py, planner/rules.py join reordering). The MCV list
is the skew signal the reference keeps in its TopN sketch: equi-join
selectivity matches heavy hitters across both sides instead of assuming
uniform key frequency (`eq_join_selectivity`).

Stats are version-stamped: a table mutation bumps table.version and
histogram/MCV estimates degrade to heuristics until the next ANALYZE —
the reference's stale-stats freshness model. NDV degrades more
gracefully: a per-column KMV sketch (`NDVSketch`, the analogue of the
reference's sketch-based NDV maintenance between analyzes) is seeded at
ANALYZE and updated on every insert, so join-key distinct counts track
DML churn without a full re-collection.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from tidb_tpu.types import TypeKind

__all__ = ["ColumnStats", "TableStats", "analyze_table", "table_stats",
           "zone_map_stats", "scan_selectivity", "column_ndv",
           "eq_join_selectivity", "NDVSketch", "HIST_BUCKETS", "MCV_SIZE"]

HIST_BUCKETS = 64
MCV_SIZE = 16


@dataclass
class ColumnStats:
    ndv: int
    null_count: int
    min: Optional[float] = None
    max: Optional[float] = None
    # equi-depth histogram: `bounds` are the sorted values at the bucket
    # quantiles (len <= HIST_BUCKETS+1); each bucket holds ~equal rows
    bounds: Optional[np.ndarray] = None
    # most-common values: up to MCV_SIZE (value, count) pairs with
    # count >= 2, by descending count. Values are in comparable logical
    # form across tables: floats for numerics, python strings for
    # dict-encoded columns (codes are table-local and can't be matched
    # across tables).
    mcv: Optional[Dict[object, int]] = None


@dataclass
class TableStats:
    n_rows: int
    version: int
    cols: Dict[str, ColumnStats] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# NDV sketch (stats maintenance between analyzes)
# ---------------------------------------------------------------------------


from tidb_tpu.utils.hashutil import splitmix64 as _splitmix64


def _hash_reprs(arr: np.ndarray) -> np.ndarray:
    """Hash device-representation values (ints/floats) to uint64."""
    a = np.asarray(arr)
    if a.dtype.kind == "f":
        u = a.astype(np.float64).view(np.uint64)
    elif a.dtype.kind == "b":
        u = a.astype(np.uint64)
    else:
        u = a.astype(np.int64).view(np.uint64)
    return _splitmix64(u)


def _hash_strings(vals) -> np.ndarray:
    """Hash python strings to uint64 (CPython string hash is 64-bit and
    stable within a process — sketches are in-memory state, never
    persisted)."""
    return _splitmix64(np.array([hash(v) for v in vals],
                                dtype=np.int64).view(np.uint64))


class NDVSketch:
    """K-minimum-values distinct-count sketch.

    Keeps the K smallest distinct 64-bit hashes seen; NDV is estimated
    as (K-1) / kth_min_normalized. Inserts only — deletes are ignored,
    so between analyzes the estimate is an (approximate) upper bound on
    live NDV, which is the safe direction for join estimates. Ref
    counterpart: the sketch-based NDV the reference maintains between
    full analyzes (statistics/ CMSketch family)."""

    __slots__ = ("mins",)
    K = 256

    def __init__(self, mins: Optional[np.ndarray] = None):
        self.mins = (np.empty(0, dtype=np.uint64)
                     if mins is None else mins.astype(np.uint64))

    def update(self, hashes: np.ndarray) -> None:
        if len(hashes) == 0:
            return
        h = hashes.astype(np.uint64)
        if len(self.mins) >= self.K:
            # saturated: only hashes below the current kth-min can enter;
            # pre-filter before the O(B log B) merge (expected survivors
            # ~ K*B/2^64, i.e. none)
            h = h[h < self.mins[-1]]
            if len(h) == 0:
                return
        merged = np.union1d(self.mins, h)
        self.mins = merged[: self.K]

    def estimate(self) -> float:
        k = len(self.mins)
        if k < self.K:
            return float(k)
        return (self.K - 1) * (2.0 ** 64) / float(max(self.mins[-1], 1))


def hash_column_values(vals: np.ndarray, dic) -> np.ndarray:
    """Hash a column's device-representation values for the NDV sketch.
    Dict-encoded columns hash the decoded strings — codes shift when the
    sorted dictionary grows, so they are not stable identities over
    time. The ONE definition shared by ANALYZE seeding and the insert
    hook (desynchronized hashing would corrupt estimates)."""
    if dic is not None:
        codes = np.unique(np.asarray(vals).astype(np.int64))
        return _hash_strings([dic.values[int(c)] for c in codes])
    return _hash_reprs(vals)


def _seed_sketch(table, col_name: str, vals: np.ndarray) -> None:
    """Seed the per-column NDV sketch from ANALYZE's value pass."""
    sk = NDVSketch()
    if len(vals):
        sk.update(hash_column_values(vals, table.dicts.get(col_name)))
    table.ndv_sketch[col_name] = sk


def analyze_table(table) -> TableStats:
    """Collect stats over the live rows of a host table.

    Also invalidates the plan-feedback store (ISSUE 15): recorded
    est-vs-actual truth was measured against the OLD statistics and the
    plans they produced — ANALYZE (manual or auto) resets the baseline,
    mirroring the plan cache's stats-identity revalidation. One choke
    point here covers both the ANALYZE statement and auto-analyze."""
    from tidb_tpu.planner import feedback as _feedback

    _feedback.STORE.on_schema_change()
    n = table.n
    live = np.asarray(table.live_mask(0, n)) if n else np.zeros(0, dtype=bool)
    n_live = int(live.sum())
    stats = TableStats(n_rows=n_live, version=table.version)
    if not hasattr(table, "ndv_sketch"):
        table.ndv_sketch = {}
    for c in table.schema.columns:
        data, valid = table.column_slice(c.name, 0, n)
        data, valid = np.asarray(data)[live], np.asarray(valid)[live]
        vals = data[valid]
        null_count = n_live - len(vals)
        _seed_sketch(table, c.name, vals)
        if len(vals) == 0:
            stats.cols[c.name] = ColumnStats(ndv=0, null_count=null_count)
            continue
        sv = np.sort(vals.astype(np.float64, copy=False))
        boundaries = np.flatnonzero(np.diff(sv))  # last index of each run
        starts = np.concatenate(([0], boundaries + 1))
        counts = np.diff(np.concatenate((starts, [len(sv)])))
        ndv = len(starts)
        idx = np.linspace(0, len(sv) - 1, min(HIST_BUCKETS + 1, len(sv))).astype(np.int64)
        # MCV/TopN: heaviest values with count >= 2, decoded to a
        # cross-table-comparable form
        mcv = None
        heavy = np.flatnonzero(counts >= 2)
        if len(heavy):
            top = heavy[np.argsort(counts[heavy])[::-1][:MCV_SIZE]]
            dic = table.dicts.get(c.name)
            mcv = {}
            for i in top:
                v = sv[starts[i]]
                key = dic.values[int(v)] if dic is not None else float(v)
                mcv[key] = int(counts[i])
        stats.cols[c.name] = ColumnStats(
            ndv=ndv, null_count=null_count,
            min=float(sv[0]), max=float(sv[-1]),
            bounds=sv[idx], mcv=mcv,
        )
    table.stats = stats
    return stats


def table_stats(table) -> Optional[TableStats]:
    """Current stats if fresh (collected at this table version)."""
    s = getattr(table, "stats", None)
    if s is not None and s.version == table.version:
        return s
    return None


def zone_map_stats(table) -> Optional[TableStats]:
    """Fallback stats derived from the columnar segment store's zone
    maps (ISSUE 8): per-column min/max as a two-point histogram,
    null counts, and a summed per-segment NDV upper bound. Only
    consulted when no fresh ANALYZE stats exist, and never stored on
    `table.stats` (the plan cache keys entry freshness on that object's
    identity). Reads an EXISTING store only — estimation must not
    trigger a segment build."""
    store = getattr(table, "_segment_store", None)
    if store is None:
        return None
    try:
        return store.stats_view()
    except Exception:  # noqa: BLE001 — estimation must never fail a plan
        return None


# ---------------------------------------------------------------------------
# estimation
# ---------------------------------------------------------------------------


def column_ndv(table, col_name: str) -> Optional[float]:
    """Distinct-count estimate for a column. Fresh stats give the exact
    ANALYZE-time NDV; between analyzes the insert-maintained KMV sketch
    keeps tracking churn (a table that doubled its key domain since
    ANALYZE is estimated near its new NDV, not its stale one)."""
    s = table_stats(table)
    if s is not None and col_name in s.cols:
        # fresh stats imply the sketch hasn't moved since ANALYZE (any
        # insert bumps table.version first): the exact count wins
        return max(float(s.cols[col_name].ndv), 1.0)
    sk = getattr(table, "ndv_sketch", {}).get(col_name)
    if sk is not None:
        return max(sk.estimate(), 1.0)
    # never analyzed: the segment store's zone maps still carry a
    # per-segment exact NDV whose sum upper-bounds the table's
    zs = zone_map_stats(table)
    if zs is not None and col_name in zs.cols and zs.cols[col_name].ndv:
        return max(float(zs.cols[col_name].ndv), 1.0)
    return None


def eq_join_selectivity(sl: TableStats, cl: ColumnStats,
                        sr: TableStats, cr: ColumnStats) -> float:
    """P(random left row key == random right row key) for an equi-join,
    MCV-aware (ref: the TopN-matched join estimation in the reference's
    planner; same shape as PostgreSQL's eqjoinsel). Heavy hitters are
    matched value-by-value across both MCV lists; the residual mass is
    assumed uniform over the residual distinct values. NULLs never
    match. Captures skew the 1/max(ndv) uniformity rule misses: two
    columns 90%-concentrated on one shared value join at sel ~0.81, not
    1/ndv."""
    n_l, n_r = max(sl.n_rows, 1), max(sr.n_rows, 1)
    nn_l = 1.0 - cl.null_count / n_l
    nn_r = 1.0 - cr.null_count / n_r
    pl = {v: c / n_l for v, c in (cl.mcv or {}).items()}
    pr = {v: c / n_r for v, c in (cr.mcv or {}).items()}
    dl = max(cl.ndv - len(pl), 1)
    dr = max(cr.ndv - len(pr), 1)
    rl = max(nn_l - sum(pl.values()), 0.0)  # residual (non-MCV) mass
    rr = max(nn_r - sum(pr.values()), 0.0)
    sel = 0.0
    for v, p in pl.items():
        if v in pr:
            sel += p * pr[v]          # heavy hitter on both sides
        else:
            sel += p * rr / dr        # matches one residual right value
    for v, p in pr.items():
        if v not in pl:
            sel += p * rl / dl
    sel += rl * rr / max(dl, dr)      # residual-residual, uniform
    return min(max(sel, 0.0), 1.0)


def _range_fraction(cs: ColumnStats, lo: float, hi: float) -> float:
    """Fraction of non-null rows with lo <= value <= hi (equi-depth
    interpolation)."""
    b = cs.bounds
    if b is None or len(b) < 2 or cs.min is None:
        return 0.33
    if hi < cs.min or lo > cs.max:
        return 0.0
    # position of a value in row-fraction space: bucket index + linear
    # interpolation inside the bucket
    nb = len(b) - 1

    def frac(x: float, side: str) -> float:
        i = int(np.searchsorted(b, x, side="left" if side == "lo" else "right"))
        if i <= 0:
            return 0.0
        if i > nb:
            return 1.0
        lo_b, hi_b = b[i - 1], b[min(i, nb)]
        inner = 0.0 if hi_b <= lo_b else (x - lo_b) / (hi_b - lo_b)
        return ((i - 1) + min(max(inner, 0.0), 1.0)) / nb

    f = frac(hi, "hi") - frac(lo, "lo")
    return min(max(f, 0.0), 1.0)


def _conjuncts(cond):
    from tidb_tpu.expression.expr import Call

    if isinstance(cond, Call) and cond.op == "and":
        for a in cond.args:
            yield from _conjuncts(a)
    else:
        yield cond


_CMP = {"eq", "ne", "lt", "le", "gt", "ge"}


def _pred_selectivity(stats: TableStats, pred, uid_to_col: Dict[str, str]) -> float:
    from tidb_tpu.expression.expr import Call, ColumnRef, InList, Literal

    if isinstance(pred, Call) and pred.op in _CMP and len(pred.args) == 2:
        a, b = pred.args
        if isinstance(b, ColumnRef) and isinstance(a, Literal):
            a, b = b, a
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}
            op = flip.get(pred.op, pred.op)
        else:
            op = pred.op
        if isinstance(a, ColumnRef) and isinstance(b, Literal) and b.value is not None:
            col = uid_to_col.get(a.name)
            cs = stats.cols.get(col) if col else None
            if cs is None:
                return {"eq": 0.1, "ne": 0.9}.get(op, 0.33)
            nn = max(stats.n_rows - cs.null_count, 1)
            v = float(b.value)
            if op == "eq":
                return min(1.0 / max(cs.ndv, 1), 1.0) * (nn / max(stats.n_rows, 1))
            if op == "ne":
                return (1.0 - 1.0 / max(cs.ndv, 1)) * (nn / max(stats.n_rows, 1))
            if op in ("lt", "le"):
                f = _range_fraction(cs, -np.inf, v)
            else:
                f = _range_fraction(cs, v, np.inf)
            return f * (nn / max(stats.n_rows, 1))
    if isinstance(pred, InList) and isinstance(pred.arg, ColumnRef):
        col = uid_to_col.get(pred.arg.name)
        cs = stats.cols.get(col) if col else None
        if cs is not None:
            f = min(len(pred.values) / max(cs.ndv, 1), 1.0)
            return 1.0 - f if pred.negated else f
    if isinstance(pred, Call) and pred.op == "or":
        s = 0.0
        for a in pred.args:
            s = s + _pred_selectivity(stats, a, uid_to_col) * (1 - s)
        return min(s, 1.0)
    if isinstance(pred, Call) and pred.op == "is_null":
        arg = pred.args[0]
        if isinstance(arg, ColumnRef):
            col = uid_to_col.get(arg.name)
            cs = stats.cols.get(col) if col else None
            if cs is not None:
                return cs.null_count / max(stats.n_rows, 1)
    return 0.33


def scan_selectivity(table, cond, uid_to_col: Dict[str, str]) -> float:
    """Estimated fraction of rows passing `cond` (compiled IR over scan
    uids); falls back to fixed heuristics without fresh stats."""
    stats = table_stats(table)
    if stats is None or stats.n_rows == 0:
        # between analyzes the segment store's zone maps still give
        # per-column min/max + null counts — range predicates estimate
        # against real bounds instead of the 0.25-per-conjunct guess
        stats = zone_map_stats(table)
    if stats is None or stats.n_rows == 0:
        n = sum(1 for _ in _conjuncts(cond))
        return 0.25 ** min(n, 2)
    sel = 1.0
    for pred in _conjuncts(cond):
        sel *= _pred_selectivity(stats, pred, uid_to_col)
    return min(max(sel, 1.0 / max(stats.n_rows, 1)), 1.0)
