"""Fused, shape-stable device top-k kernels (ISSUE 18).

``executor/sort.py`` materializes EVERY child row to host runs (one
``device_get`` per chunk) before a single ``np.lexsort`` picks the
``LIMIT k`` survivors — for an ORDER BY+LIMIT root over a fact table
that is a full-table host round trip to keep ~10 rows. This module is
the device side of ``FusedScanTopNExec``: a bounded top-k state of
capacity C (``shape_bucket(offset + count)``) rides ACROSS staged scan
chunks exactly like the fused aggregate state, merged per chunk by one
``jax.lax.sort`` over the concatenated [C + N] key operands, and the
host fetches the C winners exactly once at finalize.

The sort semantics replicate ``executor/sort.py::_sort_order`` EXACTLY
(MySQL NULL ordering — NULLs first ASC / last DESC — via a null-rank
operand that dominates the value within each key, DESC by negation,
bools widened to int64, floats compared as float64) plus a trailing
global drain-position operand, so ties resolve in drain order just like
``np.lexsort``'s stability and fused == classic row-for-row.

Like ``join_kernels``, everything query-specific arrives as arguments;
the per-key DESC flags and value dtypes are static trace parameters.
The helpers here are traced INSIDE the fused scan→topk program minted
through ``cached_jit`` (the ``probe_ranges_any`` pattern), so they
carry no ``_note_trace`` of their own; the standalone ``merge_topk``
entry point exists for kernel-level tests and non-fused callers.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.utils import dispatch

__all__ = ["rank_operands", "topk_init", "topk_merge", "merge_topk",
           "key_spec"]


def key_spec(type_) -> bool:
    """Static per-key value-dtype flag: True when the key compares as
    float64 (else int64). Derived from the column/expression SQLType so
    the state arrays minted by ``topk_init`` and the merged operands
    produced by ``rank_operands`` can never disagree on dtype."""
    return bool(np.issubdtype(type_.np_dtype, np.floating))


def rank_operands(data, valid, desc: bool):
    """One sort key -> its (null-rank, value) operand pair, mirroring
    ``_sort_order`` exactly: ASC ranks NULLs (0) before values (1),
    DESC ranks NULLs (1) after values (0) and negates the value; NULL
    slots carry 0 so the rank operand alone decides them."""
    d = data
    if d.dtype == jnp.bool_:
        d = d.astype(jnp.int64)
    if jnp.issubdtype(d.dtype, jnp.floating):
        d = d.astype(jnp.float64)
    else:
        d = d.astype(jnp.int64)
    if desc:
        d = -d
        nullrank = (~valid).astype(jnp.int32)
    else:
        nullrank = valid.astype(jnp.int32)
    d = jnp.where(valid, d, jnp.zeros_like(d))
    return nullrank, d


def topk_init(cap: int, key_floats: Sequence[bool],
              payload_dtypes: Sequence[np.dtype]):
    """The empty device top-k state: every slot dead (dead=1 sorts
    after any live row), zeroed key operands and payload, and the
    global drain-position counter at 0. Layout:

        (dead [C] i32,
         ((nullrank [C] i32, value [C] i64|f64), ...) per sort key,
         pos [C] i64, next_pos scalar i64,
         ((data [C], valid [C] bool), ...) per output column)
    """
    dead = jnp.ones(cap, dtype=jnp.int32)
    ranks = tuple(
        (jnp.zeros(cap, dtype=jnp.int32),
         jnp.zeros(cap, dtype=jnp.float64 if f else jnp.int64))
        for f in key_floats)
    pos = jnp.zeros(cap, dtype=jnp.int64)
    next_pos = jnp.zeros((), dtype=jnp.int64)
    payload = tuple(
        (jnp.zeros(cap, dtype=dt), jnp.zeros(cap, dtype=jnp.bool_))
        for dt in payload_dtypes)
    return (dead, ranks, pos, next_pos, payload)


_SAMPLE = 8192  # strided-sample size for the threshold estimate
_CAND = 8192    # fixed candidate buffer the fast selection sorts


def _kth_smallest(masked, k):
    """Exact k-th smallest (1-based, k pre-clamped to [1, n]) of a
    sentinel-masked value array. Large arrays avoid the full
    single-array sort in the common case: a strided sample estimates a
    conservative threshold, the rows at-or-under it compact into a
    fixed ``_CAND`` buffer whose sort yields the exact k-th value, and
    a ``lax.cond`` falls back to the full sort whenever the estimate
    kept too few (< k) or too many (> buffer) rows — heavy duplicate
    classes land there. Exact either way; only the cost differs."""
    n = masked.shape[0]
    if jnp.issubdtype(masked.dtype, jnp.floating):
        fill = jnp.asarray(jnp.inf, masked.dtype)
    else:
        fill = jnp.asarray(jnp.iinfo(masked.dtype).max, masked.dtype)
    if n <= 4 * _CAND:
        return jax.lax.sort(masked)[jnp.clip(k - 1, 0, n - 1)]
    stride = max(1, n // _SAMPLE)
    sample = jax.lax.sort(masked[::stride])
    n_s = sample.shape[0]
    # 4x-oversampled rank + slack: expected survivors ~4k + 16·(n/n_s),
    # comfortably >= k and << _CAND for value-rich keys
    ks = jnp.clip((k * n_s) // n * 4 + 16, 0, n_s - 1)
    t_est = sample[ks]
    cand = masked <= t_est
    count = jnp.sum(cand.astype(jnp.int64))

    def fast(operands):
        # compact survivors by gather (searchsorted over the running
        # count), not scatter -- XLA CPU scatter is a serial loop over
        # all n updates and would cost more than the sort it replaces
        vals, kk = operands
        ccum = jnp.cumsum(cand.astype(jnp.int32))
        pos = jnp.searchsorted(
            ccum, jnp.arange(1, _CAND + 1, dtype=jnp.int32), side="left")
        buf = jnp.where(jnp.arange(_CAND) < count,
                        vals[jnp.clip(pos, 0, n - 1)], fill)
        return jax.lax.sort(buf)[jnp.clip(kk - 1, 0, _CAND - 1)]

    def slow(operands):
        vals, kk = operands
        return jax.lax.sort(vals)[jnp.clip(kk - 1, 0, n - 1)]

    ok = (count >= jnp.maximum(k, 1)) & (count <= _CAND)
    return jax.lax.cond(ok, fast, slow, (masked, k))


def _cut_single_key(nullrank, value, sel, cap: int, desc: bool):
    """Exact top-``cap`` candidate cut of one chunk for a SINGLE sort
    key, using only a single-array ``lax.sort`` plus prefix sums. XLA's
    variadic comparator sort (the general merge below) runs ~7x slower
    than its vectorized single-array sort on CPU, so cutting the chunk
    to ``cap`` candidates first and merging 2·cap rows is the
    difference between the fused path winning and losing against the
    classic host ``np.lexsort``.

    Exactness: the key's null-rank classes select in rank order
    (ASC: NULLs then values; DESC: values then NULLs — the
    ``rank_operands`` convention). The all-NULL class ties completely,
    so its winners are the first ``k`` in drain (array) order — one
    cumsum. The value class takes every row strictly better than the
    k-th best value (one single-array sort over the class, non-class
    rows masked to the dtype maximum) plus boundary ties in drain
    order — a second cumsum. A real value colliding with the mask
    sentinel merely joins the boundary class, where the explicit class
    mask keeps the selection exact. Ties therefore resolve identically
    to the full merge's drain-position operand.

    NaN (float keys only) is its own third class: ``< thresh`` and
    ``== thresh`` are both false for NaN, so leaving NaN rows in the
    value class would silently DROP them (and poison the threshold
    sort). Both orderings the engine must match — host ``np.lexsort``
    and the XLA total-order merge sort — place NaN after every real
    value in either direction (DESC negates, and NumPy/XLA rank any
    NaN as maximal), i.e. ASC: NULLs, values, NaN; DESC: values, NaN,
    NULLs. NaNs tie completely, so like the NULL class their winners
    are the first ``k`` in drain order.

    Returns ``(idx [cap] i32, live [cap] bool)`` — source-row gathers
    for the candidate buffer (winner order is irrelevant: the variadic
    merge re-sorts)."""
    n = sel.shape[0]
    null_nr = jnp.int32(1 if desc else 0)
    is_null = (nullrank == null_nr) & sel
    is_val = sel & ~is_null
    floating = jnp.issubdtype(value.dtype, jnp.floating)
    if floating:
        is_nan = is_val & jnp.isnan(value)
        is_val = is_val & ~is_nan
        n_nan = jnp.sum(is_nan.astype(jnp.int64))
    else:  # trace-time skip: int keys have no NaN class
        is_nan = None
        n_nan = jnp.int64(0)
    n_null = jnp.sum(is_null.astype(jnp.int64))
    n_val = jnp.sum(is_val.astype(jnp.int64))
    c = jnp.int64(cap)
    if desc:  # values, NaN, NULLs
        k_val = jnp.minimum(c, n_val)
        k_nan = jnp.minimum(c - k_val, n_nan)
        k_null = jnp.minimum(c - k_val - k_nan, n_null)
    else:  # NULLs, values, NaN
        k_null = jnp.minimum(c, n_null)
        k_val = jnp.minimum(c - k_null, n_val)
        k_nan = jnp.minimum(c - k_null - k_val, n_nan)
    ncum = jnp.cumsum(is_null.astype(jnp.int64))
    win_null = is_null & (ncum <= k_null)
    if floating:
        sentinel = jnp.asarray(jnp.inf, value.dtype)
    else:
        sentinel = jnp.asarray(jnp.iinfo(value.dtype).max, value.dtype)
    masked = jnp.where(is_val, value, sentinel)
    thresh = _kth_smallest(masked, jnp.maximum(k_val, 1))
    strict = is_val & (masked < thresh)
    boundary = is_val & (masked == thresh)
    bcum = jnp.cumsum(boundary.astype(jnp.int64))
    n_strict = jnp.sum(strict.astype(jnp.int64))
    win_val = (strict | (boundary & (bcum <= k_val - n_strict))) \
        & (k_val > 0)
    win = win_null | win_val
    if is_nan is not None:
        nancum = jnp.cumsum(is_nan.astype(jnp.int64))
        win = win | (is_nan & (nancum <= k_nan))
    # compact the <= cap winners by gather, not scatter: the j-th winner
    # sits at the first index whose running win-count reaches j+1, and
    # cap binary searches beat an n-update serial XLA CPU scatter
    wcum = jnp.cumsum(win.astype(jnp.int32))
    idx = jnp.searchsorted(
        wcum, jnp.arange(1, cap + 1, dtype=jnp.int32), side="left")
    live = jnp.arange(cap, dtype=jnp.int32) < wcum[n - 1]
    return jnp.clip(idx, 0, n - 1).astype(jnp.int32), live


def topk_merge(state, key_pairs: Tuple, payload_cols: Tuple, sel,
               descs: Tuple = None):
    """One chunk folded into the state — traced inside the fused
    scan→topk program. Concatenates the state's C entries with the
    chunk's N rows per operand, sorts ONCE over (dead, per-key
    null-rank/value pairs, drain position, source index) and keeps the
    first C of every operand; the trailing index operand routes the
    two-source payload gather (slot < C = carried state row, else chunk
    row). Filtered-out chunk rows (sel False) enter dead and can never
    displace a live entry.

    With ``descs`` given and a SINGLE sort key, the chunk is first cut
    to C exact candidates by ``_cut_single_key`` (cheap single-array
    sort) so the variadic merge sorts 2·C rows instead of C + N —
    without the cut the comparator sort over the whole chunk costs
    MORE than the classic host path it replaces. Multi-key chunks keep
    the full merge (a key-boundary tie class is unbounded, so no fixed
    candidate buffer can cut them exactly)."""
    dead, ranks, pos, next_pos, payload = state
    C = dead.shape[0]
    N = sel.shape[0]
    cpos = next_pos + jnp.arange(N, dtype=jnp.int64)
    new_next = next_pos + N
    if descs is not None and len(key_pairs) == 1 and N > C:
        (cnr, cv), = key_pairs
        idx, live = _cut_single_key(cnr, cv, sel, C, bool(descs[0]))
        key_pairs = ((jnp.take(cnr, idx, mode="clip"),
                      jnp.take(cv, idx, mode="clip")),)
        payload_cols = tuple(
            (jnp.take(d, idx, mode="clip"),
             jnp.take(v, idx, mode="clip"))
            for d, v in payload_cols)
        cpos = jnp.take(cpos, idx, mode="clip")
        sel = live
        N = C
    cdead = (~sel).astype(jnp.int32)
    ops = [jnp.concatenate([dead, cdead])]
    for (snr, sv), (cnr, cv) in zip(ranks, key_pairs):
        ops.append(jnp.concatenate([snr, cnr]))
        ops.append(jnp.concatenate([sv, cv]))
    ops.append(jnp.concatenate([pos, cpos]))
    src = jnp.arange(C + N, dtype=jnp.int64)
    sorted_ops = jax.lax.sort(tuple(ops) + (src,), num_keys=len(ops))
    top = tuple(o[:C] for o in sorted_ops)
    perm = top[-1]
    from_state = perm < C
    si = jnp.clip(perm, 0, C - 1)
    ci = jnp.clip(perm - C, 0, max(N - 1, 0))
    new_ranks = tuple((top[1 + 2 * i], top[2 + 2 * i])
                      for i in range(len(ranks)))
    new_payload = tuple(
        (jnp.where(from_state, jnp.take(sd, si, mode="clip"),
                   jnp.take(cd, ci, mode="clip")),
         jnp.where(from_state, jnp.take(sv, si, mode="clip"),
                   jnp.take(cv, ci, mode="clip")))
        for (sd, sv), (cd, cv) in zip(payload, payload_cols))
    return (top[0], new_ranks, top[-2], new_next, new_payload)


@functools.partial(jax.jit, donate_argnums=0, static_argnums=4)
def _merge_topk(state, key_pairs, payload_cols, sel, descs):
    from tidb_tpu.ops.join_kernels import _note_trace

    _note_trace("topk_merge")
    return topk_merge(state, key_pairs, payload_cols, sel, descs)


def merge_topk(state, key_pairs, payload_cols, sel, descs=None):
    """Standalone jitted merge (kernel tests / non-fused callers): the
    fused pipeline instead traces ``topk_merge`` inside its own
    ``cached_jit`` program, which counts its dispatches there."""
    dispatch.record(site="jit:topk.merge")
    return _merge_topk(state, tuple(key_pairs), tuple(payload_cols), sel,
                       None if descs is None else tuple(descs))
