"""Segment aggregation as tiled one-hot MXU matmuls (Pallas).

XLA lowers `acc.at[seg].add(vals)` to a serialized scatter on TPU; the
MXU-native formulation is a one-hot matmul per tile:

    onehot[t, g] = (seg[t] == g)          # [TILE, G] built from iota
    partial[g]   = vals[1, TILE] @ onehot # one MXU pass
    out[g]      += partial                # accumulated across the grid

Exactness: f32 matmul accumulation is integer-exact below 2^24, so
  * segment_count is EXACT for any chunk up to 2^24 rows (per-tile
    partial <= TILE, total <= R) — counts dispatch to Pallas on TPU;
  * segment_sum_f32 matches XLA f32 summation to reordering — used for
    FLOAT aggregates where SQL float semantics already permit it;
  * int64/decimal sums stay on the XLA path (exactness first).

Group count G is padded to the 128-lane boundary; segment ids >= G are
the caller's NULL/overflow slots and pad lanes simply accumulate zeros
that are sliced off.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["segment_count", "segment_sum_f32", "segment_sum_i64",
           "pallas_enabled", "set_pallas_enabled", "xla_segment_sum",
           "force_platform"]

_TILE = 1024
_MAX_PALLAS_G = 8192  # above this the [TILE, G] one-hot exceeds VMEM budget

_enabled: bool | None = None  # None = auto (TPU backend only)


def set_pallas_enabled(v: bool | None) -> None:
    global _enabled
    _enabled = v


_forced_platform: str | None = None


@contextlib.contextmanager
def force_platform(p: str):
    """Pin the Pallas target platform for the duration of a call. Mesh
    fragments are traced while the executor glue has jax.default_device
    pinned to host CPU (utils/device.py host_eager), yet they execute on
    the mesh's devices — the fragment runner wraps each dispatch in
    force_platform(mesh_platform) so kernels pick the right mode."""
    global _forced_platform
    prev, _forced_platform = _forced_platform, p
    try:
        yield
    finally:
        _forced_platform = prev


def _target_platform() -> str:
    """Platform the *current* computation lands on: an explicit
    force_platform() wins (mesh fragments), then the pinned default
    device (host-eager glue), then the default backend. The backend name
    alone is wrong in both pinned cases."""
    if _forced_platform is not None:
        return _forced_platform
    d = jax.config.jax_default_device
    if d is not None:
        return d.platform
    try:
        return jax.default_backend()
    except RuntimeError:  # pragma: no cover
        return "cpu"


def pallas_enabled() -> bool:
    if _enabled is not None:
        return _enabled
    return _target_platform() == "tpu"


def xla_segment_sum(vals: jax.Array, seg: jax.Array, G: int) -> jax.Array:
    """Reference path: XLA scatter-add. A single-segment (global) sum is
    a masked reduction instead — every row collides on one slot and
    XLA:CPU serializes colliding scatter updates (~35 ms per 2^17-row
    chunk, measured driving count/sum over a join output). The mask
    keeps the scatter contract: seg ids >= G (callers' NULL/overflow
    drop slots) still contribute nothing."""
    if G == 1:
        return jnp.sum(jnp.where(seg == 0, vals, 0))[None]
    return jnp.zeros(G, dtype=vals.dtype).at[seg].add(vals)


_SUB = 8  # sublanes per tile row; tile is [_SUB, 128] = _TILE elements
_LANES = 128


def _pad_tile(x: jax.Array, fill) -> jax.Array:
    """[R] -> [n_tiles, 8, 128] (Mosaic's (8, 128) f32 tiling)."""
    R = x.shape[0]
    Rp = ((R + _TILE - 1) // _TILE) * _TILE
    if Rp != R:
        x = jnp.concatenate([x, jnp.full(Rp - R, fill, dtype=x.dtype)])
    return x.reshape(Rp // _TILE, _SUB, _LANES)


@functools.partial(jax.jit, static_argnames=("G", "Gp"))
def _pallas_segsum_f32(vals: jax.Array, seg: jax.Array, G: int, Gp: int) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from jax._src.config import enable_x64

    vals2 = _pad_tile(vals.astype(jnp.float32), 0.0)
    seg2 = _pad_tile(seg.astype(jnp.int32), Gp)  # pad rows land off-range
    n_tiles = vals2.shape[0]

    def kernel(vals_ref, seg_ref, out_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        s = seg_ref[0]  # [8, 128] int32
        v = vals_ref[0]  # [8, 128] f32
        # one-hot over a new trailing group axis, contracted on the VPU;
        # [8, 128, Gp] stays well inside VMEM for the segment-agg G range
        gid = jax.lax.broadcasted_iota(jnp.int32, (_SUB, _LANES, Gp), 2)
        onehot = (s[:, :, None] == gid).astype(jnp.float32)
        part = jnp.sum(v[:, :, None] * onehot, axis=(0, 1))  # [Gp]
        out_ref[:] = out_ref[:] + part[None, :]

    # trace the kernel with x64 OFF: the engine enables x64 globally
    # (decimals are scaled int64), but Mosaic can't legalize the i64
    # constants that leak into index maps / grid bookkeeping
    with enable_x64(False):
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1, Gp), jnp.float32),
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((1, _SUB, _LANES), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, _SUB, _LANES), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, Gp), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM),
            # off-TPU (tests force-enable) the interpreter runs the same
            # kernel logic, so CPU CI covers the Pallas path too
            interpret=_target_platform() != "tpu",
        )(vals2, seg2)
    return out[0, :G]


def _gp(G: int) -> int:
    return max(((G + 127) // 128) * 128, 128)


def segment_sum_f32(vals: jax.Array, seg: jax.Array, G: int) -> jax.Array:
    """Float32 segment sum; Pallas on TPU, XLA elsewhere."""
    if not pallas_enabled() or G > _MAX_PALLAS_G:
        return xla_segment_sum(vals.astype(jnp.float32), seg, G)
    return _pallas_segsum_f32(vals, seg, G, _gp(G))


_N_LIMBS = 8
_LIMB_BITS = 8


@functools.partial(jax.jit, static_argnames=("G", "Gp"))
def _pallas_segsum_i64(vals: jax.Array, seg: jax.Array, G: int, Gp: int) -> jax.Array:
    """EXACT int64 (decimal) segment sum on the Pallas path.

    The value splits into 8 unsigned byte limbs OUTSIDE the kernel (the
    kernel traces with x64 off — Mosaic cannot legalize i64); each limb
    accumulates in int32 on the VPU against the shared one-hot, and the
    limb sums recombine in uint64 with natural wraparound — exact for
    any int64 inputs because two's-complement addition is mod 2^64.
    Exactness bound: per-limb sums must fit int32, i.e. 255 * R < 2^31
    (R < 2^23 rows), enforced by the dispatcher."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from jax._src.config import enable_x64

    u = jax.lax.bitcast_convert_type(vals.astype(jnp.int64), jnp.uint64)
    limbs = [
        ((u >> jnp.uint64(_LIMB_BITS * j)) & jnp.uint64(0xFF)).astype(jnp.int32)
        for j in range(_N_LIMBS)
    ]
    limbs2 = jnp.stack([_pad_tile(l, 0) for l in limbs], axis=1)
    # [n_tiles, 8 limbs, 8 sub, 128 lanes]
    seg2 = _pad_tile(seg.astype(jnp.int32), Gp)
    n_tiles = seg2.shape[0]

    def kernel(limbs_ref, seg_ref, out_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        s = seg_ref[0]  # [8, 128] int32
        gid = jax.lax.broadcasted_iota(jnp.int32, (_SUB, _LANES, Gp), 2)
        onehot = (s[:, :, None] == gid).astype(jnp.int32)
        for j in range(_N_LIMBS):
            v = limbs_ref[0, j]  # [8, 128] int32
            part = jnp.sum(v[:, :, None] * onehot, axis=(0, 1))  # [Gp]
            out_ref[j, :] = out_ref[j, :] + part

    with enable_x64(False):
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((_N_LIMBS, Gp), jnp.int32),
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((1, _N_LIMBS, _SUB, _LANES),
                             lambda i: (i, 0, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, _SUB, _LANES), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((_N_LIMBS, Gp), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM),
            interpret=_target_platform() != "tpu",
        )(limbs2, seg2)
    # recombine: limb sums (int32, exact) widen to uint64, shift, add —
    # wraparound is exactly int64 addition's
    acc = jnp.zeros(Gp, dtype=jnp.uint64)
    for j in range(_N_LIMBS):
        acc = acc + (out[j].astype(jnp.uint64) << jnp.uint64(_LIMB_BITS * j))
    return jax.lax.bitcast_convert_type(acc, jnp.int64)[:G]


def segment_sum_i64(vals: jax.Array, seg: jax.Array, G: int) -> jax.Array:
    """Exact int64/decimal segment sum; Pallas limb kernel on TPU, XLA
    scatter elsewhere. Covers Q1's decimal sum_qty/sum_base_price/
    sum_disc_price/sum_charge accumulators."""
    if (not pallas_enabled() or G > _MAX_PALLAS_G
            or vals.shape[0] >= (1 << 23)):  # 255 * R < 2^31 limb bound
        return xla_segment_sum(vals.astype(jnp.int64), seg, G)
    return _pallas_segsum_i64(vals, seg, G, _gp(G))


def segment_count(mask: jax.Array, seg: jax.Array, G: int) -> jax.Array:
    """Count mask-true rows per segment, EXACT (counts < 2^24), int64.

    The hottest accumulator shape in segment aggregation: occ + one cnt
    per aggregate function all reduce a boolean through this. 10-13x
    faster than the XLA int64 scatter on TPU v5e (ops/SEGSUM_BENCH.json)."""
    if (not pallas_enabled() or G > _MAX_PALLAS_G
            or mask.shape[0] >= (1 << 24)):  # f32 exactness bound
        return xla_segment_sum(mask.astype(jnp.int64), seg, G)
    f = _pallas_segsum_f32(mask.astype(jnp.float32), seg, G, _gp(G))
    return f.astype(jnp.int64)
