"""Device kernels (Pallas) for the hot relational primitives.

The compute path is jax/XLA; this package holds the hand-written TPU
kernels for the few primitives XLA lowers poorly — today the segment
aggregation scatter-add (ref: SURVEY.md §7.4's "Pallas hash-table /
segment kernel as the optimized path"). Every kernel has an XLA
reference implementation; `pallas_enabled()` gates dispatch (TPU
backend only, overridable for benchmarks), and ops/SEGSUM_BENCH.json
records the microbenchmark that justifies the default.
"""

from tidb_tpu.ops.segment_sum import (
    force_platform,
    pallas_enabled,
    segment_count,
    segment_sum_f32,
    segment_sum_i64,
    set_pallas_enabled,
)

__all__ = ["segment_count", "segment_sum_f32", "segment_sum_i64",
           "pallas_enabled",
           "set_pallas_enabled", "force_platform"]
