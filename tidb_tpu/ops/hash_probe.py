"""Open-addressing hash probe for the device joins (Pallas).

Both join tiers sort their build side and, pre-ISSUE 10, probed with
two `jnp.searchsorted` calls — O(log Rb) dependent gather rounds per
probe element, hostile to TPU (each round is an HBM gather the next
round depends on). The reference's hash join probes an O(1)-expected
hash table instead (ref: executor/'s HashJoinExec build+probe workers;
SURVEY.md:294-296 names this kernel as the planned fast path). This
module supplies that table, consumed two ways: the fragment join
(parallel/fragment.py) builds + probes it inside one shard_map program
via `probe_for_join`, and the main single-chip join (ISSUE 10) builds
it ONCE per join build (ops/join_kernels.build_hash_table) and probes
it per chunk with the table arrays as kernel args. Strategy selection:
`tidb_tpu_join_probe_mode` (off/auto/xla/pallas) through
`resolve_mode` — auto picks the table exactly when the computation
targets TPU.

  * BUILD (XLA, inside the same jit): runs of equal values in the sorted
    hash array become (lo, hi) ranges; each run's FIRST row inserts
    (hash, lo, hi) into an open-addressing table of power-of-two
    capacity ~2x the run count via bounded scatter rounds (linear
    probing; round r claims slot (home + r) & mask with scatter-min
    arbitration). `placed` tracks success — if any run needs more than
    MAX_PROBES displacements the whole probe falls back to searchsorted
    THROUGH lax.cond, so results never depend on table luck.
  * PROBE (Pallas): the table lives in VMEM (the kernel targets
    dimension-sized build sides; capacity is capped so three i32 tables
    fit comfortably), each probe element scans its MAX_PROBES window
    with vectorized selects — no data-dependent loop, no divergence.

Correctness envelope: every inserted run sits within MAX_PROBES slots
of its home (else the searchsorted branch runs), so a probe that scans
the full window and finds no match has PROVEN absence. Duplicate probe
hashes, absent keys, and invalid rows all resolve exactly like
searchsorted — pinned by tests against it (tests/test_ops_probe.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tidb_tpu.ops.segment_sum import pallas_enabled

__all__ = ["probe_ranges", "xla_probe_ranges", "probe_for_join",
           "set_mode", "resolve_mode", "table_capacity", "MAX_CAPACITY"]

import os

# "off": always searchsorted; "auto" (default): hash table when the
# computation targets TPU (trace-time force_platform aware, like
# segment_sum); "xla": hash table everywhere (window-scan probe);
# "pallas": hash table with the Pallas VMEM kernel.
#
# Auto keeps CPU on searchsorted because it measures faster there
# (bench.py bench_probe — 32 fixed window rounds vs ~2*log2(Rb)
# cache-friendly binary rounds) while TPU gets the VMEM-resident table
# instead of O(log Rb) dependent HBM gather rounds per element.
# Sessions thread tidb_tpu_join_probe_mode PER STATEMENT through
# ExecContext/fragment args (ISSUE 12 — the old per-statement set_mode
# write raced concurrent sessions); this global is only the default
# for offline tools and bare fragments, seeded by the env var.
_mode = os.environ.get("TIDB_HASH_PROBE", "auto")


def set_mode(m: str) -> None:
    """Seed the PROCESS-WIDE default probe mode. Offline tools and bare
    fragments only: engine statements thread the session's resolved
    mode per-statement (ExecContext.join_probe_mode -> fragment args,
    ISSUE 12), so concurrent sessions never race this global. The
    sanitizer's shared-mutable-global witness flags any write that
    lands while a statement is in flight."""
    global _mode
    from tidb_tpu.analysis import sanitizer as _san

    if _san.enabled():
        _san.note_global_write("ops.hash_probe._mode", m)
    _mode = m


def resolve_mode(mode: str = None) -> str:
    """Concrete probe strategy — 'sorted' | 'xla' | 'pallas' — for the
    platform the CURRENT computation targets (trace-time, so mesh
    fragments under force_platform resolve against the mesh's devices).
    `mode` defaults to the module global the session sysvar wires."""
    m = _mode if mode is None else mode
    if m == "off":
        return "sorted"
    if m == "auto":
        return "xla" if pallas_enabled() else "sorted"
    return m


def probe_for_join(sorted_hashes: jax.Array, probes: jax.Array,
                   mode: str = None):
    """The fragment join's probe entry point: (lo, hi) ranges over the
    sorted build hashes via the configured strategy. ``mode`` is the
    per-statement value threaded from ExecContext through the fragment
    builder (ISSUE 12 — the trace-time global read raced concurrent
    sessions); None falls back to the process default for offline
    tools and bare fragments."""
    m = _mode if mode is None else mode
    if m == "off" or (m == "auto" and not pallas_enabled()):
        lo, hi = xla_probe_ranges(sorted_hashes, probes)
        return lo.astype(jnp.int64), hi.astype(jnp.int64)
    return probe_ranges(sorted_hashes, probes,
                        use_pallas=(m == "pallas"))

MAX_PROBES = 32
# three int32 tables of this capacity ~= 6 MiB of VMEM: dimension-sized
# build sides (the star-join case) qualify; big fact-fact joins keep the
# searchsorted path
MAX_CAPACITY = 1 << 19

_EMPTY = jnp.int32(0x7FFFFFFF)


def _mix32(h: jax.Array, salt: int = 0) -> jax.Array:
    """int64 hash -> well-spread int32 (splitmix tail); `salt` derives
    the independent fingerprint stream."""
    h = h.astype(jnp.uint64) ^ jnp.uint64(salt)
    h = (h ^ (h >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    h = (h ^ (h >> 27)) * jnp.uint64(0x94D049BB133111EB)
    out = (h ^ (h >> 31)).astype(jnp.uint32).astype(jnp.int32)
    # the table's EMPTY sentinel must be unreachable as a fingerprint:
    # a run stored as 0x7FFFFFFF would look like a free slot (silent
    # match loss); remap it consistently on build AND probe sides
    return jnp.where(out == _EMPTY, jnp.int32(0), out)


_FP_SALT = 0x9E3779B97F4A7C15


def xla_probe_ranges(sorted_hashes: jax.Array, probes: jax.Array):
    """Reference path: (lo, hi) = searchsorted left/right."""
    lo = jnp.searchsorted(sorted_hashes, probes, side="left")
    hi = jnp.searchsorted(sorted_hashes, probes, side="right")
    return lo, hi


def _next_pow2(n: int) -> int:
    c = 1
    while c < n:
        c *= 2
    return c


def table_capacity(n_build: int):
    """Open-addressing table capacity for an `n_build`-row build side,
    or None when the table is ineligible (load factor would exceed 1/2
    within the VMEM cap, or the build is empty). One definition shared
    by probe_ranges (fragment tier, in-jit) and the main join's
    build-time table construction (ops/join_kernels.build_hash_table)."""
    if n_build == 0:
        return None
    cap = min(_next_pow2(max(2 * n_build, 16)), MAX_CAPACITY)
    if cap < 2 * n_build:
        return None
    return cap


def _build_table(sh: jax.Array, cap: int):
    """(keys32[cap], lo32[cap], hi32[cap], all_placed) from the sorted
    hash array. keys32 stores the mixed 32-bit fingerprint of the run's
    hash; collisions between DIFFERENT 64-bit hashes on both slot AND
    fingerprint are resolved by verifying via the (lo) range's actual
    hash at probe time."""
    Rb = sh.shape[0]
    idx = jnp.arange(Rb, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones(1, dtype=jnp.bool_), sh[1:] != sh[:-1]])
    # hi of the run starting at i = index of the NEXT start (suffix min)
    start_pos = jnp.where(is_start, idx, Rb)
    next_start = jnp.flip(jax.lax.cummin(jnp.flip(
        jnp.concatenate([start_pos[1:], jnp.array([Rb], jnp.int32)]))))
    mask = cap - 1
    home = _mix32(sh) & mask
    fp = _mix32(sh, salt=_FP_SALT)

    # one PARKING slot at index cap: losers scatter there, never into a
    # live slot (a parked .set at a shared fixed index could clobber a
    # genuine win landing on that same slot in the same scatter)
    keys = jnp.full(cap + 1, _EMPTY, dtype=jnp.int32)
    los = jnp.zeros(cap + 1, dtype=jnp.int32)
    his = jnp.zeros(cap + 1, dtype=jnp.int32)
    placed = ~is_start  # non-starts have nothing to insert

    def round_(r, state):
        keys, los, his, placed = state
        pos = (home + r) & mask
        want = ~placed
        # scatter-min arbitration: the lowest claiming row wins the slot
        claim = jnp.full(cap + 1, jnp.iinfo(jnp.int32).max, dtype=jnp.int32)
        claim = claim.at[jnp.where(want, pos, cap)].min(
            jnp.where(want, idx, jnp.iinfo(jnp.int32).max))
        free = keys[pos] == _EMPTY
        won = want & free & (claim[pos] == idx)
        park = jnp.where(won, pos, cap)
        keys = keys.at[park].set(jnp.where(won, fp, _EMPTY))
        los = los.at[park].set(jnp.where(won, idx, 0))
        his = his.at[park].set(jnp.where(won, next_start, 0))
        return keys, los, his, placed | won

    keys, los, his, placed = jax.lax.fori_loop(
        0, MAX_PROBES, round_, (keys, los, his, placed))
    return keys[:cap], los[:cap], his[:cap], placed.all()


def _probe_xla(keys, los, his, sh, probes, cap):
    """Window-scan probe expressed in plain XLA (the same arithmetic the
    Pallas kernel runs; also the interpret-mode/CPU executable path)."""
    mask = cap - 1
    home = _mix32(probes) & mask
    fp = _mix32(probes, salt=_FP_SALT)
    lo = jnp.zeros(probes.shape[0], dtype=jnp.int32)
    hi = jnp.zeros(probes.shape[0], dtype=jnp.int32)
    found = jnp.zeros(probes.shape[0], dtype=jnp.bool_)

    def round_(r, state):
        lo, hi, found = state
        pos = (home + r) & mask
        k = keys[pos]
        cand_lo = los[pos]
        # fingerprint match is only a CANDIDATE: verify via the run's
        # actual 64-bit hash (two different hashes can share slot + fp)
        hit = (~found) & (k == fp) & (sh[jnp.clip(cand_lo, 0, sh.shape[0] - 1)]
                                      == probes)
        lo = jnp.where(hit, cand_lo, lo)
        hi = jnp.where(hit, his[pos], hi)
        found = found | hit
        return lo, hi, found

    lo, hi, found = jax.lax.fori_loop(
        0, MAX_PROBES, round_, (lo, hi, found))
    # miss => empty range (searchsorted yields lo == hi there; the join
    # only consumes hi - lo and lo + k under cnt, so any equal pair works)
    lo = jnp.where(found, lo, 0)
    hi = jnp.where(found, hi, 0)
    return lo.astype(jnp.int64), hi.astype(jnp.int64)


def _probe_pallas(keys, los, his, sh, probes, cap):
    """VMEM-resident table scan: one grid step per probe tile, the three
    [cap] tables mapped whole into VMEM, MAX_PROBES vectorized rounds."""
    from jax.experimental import pallas as pl

    T = 2048
    Rp = probes.shape[0]
    n_tiles = (Rp + T - 1) // T
    pad = n_tiles * T - Rp
    probes_p = jnp.concatenate(
        [probes, jnp.full(pad, -1, dtype=probes.dtype)]) if pad else probes
    mask = cap - 1
    home = (_mix32(probes_p) & mask).astype(jnp.int32)
    fp = _mix32(probes_p, salt=_FP_SALT)
    # probe-side hash identity check runs on the table's lo -> sh lookup;
    # precompute sh as int32 pair to keep the kernel i32-only
    sh_hi = (sh >> 32).astype(jnp.int32)
    sh_lo = sh.astype(jnp.int32)
    pr_hi = (probes_p >> 32).astype(jnp.int32)
    pr_lo = probes_p.astype(jnp.int32)

    def kernel(home_ref, fp_ref, prhi_ref, prlo_ref, keys_ref, los_ref,
               his_ref, shhi_ref, shlo_ref, lo_ref, hi_ref):
        h = home_ref[...]
        f = fp_ref[...]
        phi = prhi_ref[...]
        plo = prlo_ref[...]
        lo = jnp.zeros_like(h)
        hi = jnp.zeros_like(h)
        found = jnp.zeros(h.shape, dtype=jnp.bool_)
        Rb = shhi_ref.shape[0]
        for r in range(MAX_PROBES):
            pos = (h + r) & mask
            k = keys_ref[pos]
            cand = los_ref[pos]
            ci = jnp.clip(cand, 0, Rb - 1)
            hit = ((~found) & (k == f)
                   & (shhi_ref[ci] == phi) & (shlo_ref[ci] == plo))
            lo = jnp.where(hit, cand, lo)
            hi = jnp.where(hit, his_ref[pos], hi)
            found = found | hit
        lo_ref[...] = lo
        hi_ref[...] = hi

    grid = (n_tiles,)
    tile = pl.BlockSpec((T,), lambda i: (i,))
    whole_cap = pl.BlockSpec((cap,), lambda i: (0,))
    whole_rb = pl.BlockSpec((sh.shape[0],), lambda i: (0,))
    lo32, hi32 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[tile, tile, tile, tile, whole_cap, whole_cap, whole_cap,
                  whole_rb, whole_rb],
        out_specs=[tile, tile],
        out_shape=[jax.ShapeDtypeStruct((n_tiles * T,), jnp.int32)] * 2,
        interpret=not pallas_enabled(),
    )(home, fp, pr_hi, pr_lo, keys, los, his, sh_hi, sh_lo)
    return lo32[:Rp].astype(jnp.int64), hi32[:Rp].astype(jnp.int64)


@functools.partial(jax.jit, static_argnames=("use_pallas",))
def probe_ranges(sorted_hashes: jax.Array, probes: jax.Array,
                 use_pallas: bool = False):
    """(lo, hi) per probe element over the sorted build hashes —
    numerically identical to searchsorted left/right wherever the join
    consumes them (hi - lo counts and lo + k positions). Falls back to
    searchsorted inside the SAME jit when the table build overflows its
    displacement bound, so callers never see a behavioral difference."""
    from tidb_tpu.ops.join_kernels import _note_trace

    _note_trace("hash_probe")  # trace-time only: joins the retrace guard
    cap = table_capacity(sorted_hashes.shape[0])
    if cap is None:
        # load factor would exceed 1/2 (or VMEM): stay on searchsorted
        return xla_probe_ranges(sorted_hashes, probes)
    keys, los, his, ok = _build_table(sorted_hashes, cap)

    def fast(_):
        if use_pallas:
            return _probe_pallas(keys, los, his, sorted_hashes, probes, cap)
        return _probe_xla(keys, los, his, sorted_hashes, probes, cap)

    def slow(_):
        lo, hi = xla_probe_ranges(sorted_hashes, probes)
        return lo.astype(jnp.int64), hi.astype(jnp.int64)

    return jax.lax.cond(ok, fast, slow, None)
