"""Fused, shape-stable kernels for the partitioned device hash join.

The pre-PR join paid two structural costs (BENCH_tpu.json:
join_build_probe_gbps = 0.009 while scans sustained ~10M rows/s):

  * the build side round-tripped through a host ``np.argsort`` (and
    fancy-indexed the sorted keys twice, once per tier);
  * every probe chunk ran through ``counted_jit`` closures minted per
    executor instance, so a repeated join re-traced and re-compiled its
    probe + expand programs on EVERY execution (~hundreds of ms of XLA
    work per query on the CPU backend, seconds on a tunneled TPU).

This module is the fix: the join's device programs live HERE, at module
level, and take everything query-specific — key arrays, pack ranges,
payload columns — as *arguments*, never as closure state. jax.jit then
keys executables purely on (shapes, dtypes, static flags):

  * build sides are padded to power-of-two buckets (``shape_bucket``),
    so two queries whose build sides land in the same bucket share one
    compiled program, and a steady-state repeated join re-traces nothing;
  * the probe is ONE fused kernel — key pack → range lookup → per-row
    match count → prefix sum — and expansion is one fused kernel
    emitting ``[T, C]`` fixed-capacity output tiles (the same layout
    ``parallel/partition.py`` streams), T output tiles per dispatch
    instead of one dispatch per output window. The range lookup
    (``probe_ranges_any``) is strategy-parameterized (ISSUE 10):
    dense packed domains take the O(1) direct-address index, the
    TPU-shaped path probes the prebuilt open-addressing table
    (``build_hash_table`` / ops/hash_probe, MAX_PROBES vectorized
    window rounds instead of O(log B) dependent gathers), and
    searchsorted remains the CPU default and in-jit fallback;
  * the build sort runs on device: NULL/dead keys are sent to
    ``INT64_MAX`` and sorted to the tail with a stable secondary flag,
    so ``n_build`` (a traced scalar) bounds every probe range exactly
    and the padding can never produce a phantom match — even for a
    legitimate INT64_MAX key, whose valid run sits before the sentinels.

Every kernel body calls ``_note_trace`` as its first statement: the
Python body only runs while jax traces, so ``JOIN_COMPILE_TOTAL`` counts
real XLA (re)compilations, not dispatches. The retrace-guard test and
EXPLAIN ANALYZE's per-operator ``recompiles:`` field both read it.

``parallel/fragment.py``'s all_to_all repartition join reuses the same
primitives (``sort_build_hashes``, ``probe_hash_ranges``,
``tile_positions``) inside its shard_map trace, so local and distributed
joins share one definition of the sort/probe/expand arithmetic.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.utils import dispatch
from tidb_tpu.utils.hashutil import SM_ADD, SM_MUL1, SM_MUL2

__all__ = [
    "shape_bucket", "as_int64_key", "hash_combine_device", "pack_keys",
    "build_sort", "build_hash_table", "no_table", "probe_count",
    "probe_ranges_any", "expand_tiles",
    "sort_build_hashes", "probe_hash_ranges", "tile_positions",
]

I64_MAX = np.iinfo(np.int64).max


def shape_bucket(n: int, floor: int = 64) -> int:
    """Next power of two >= max(n, floor): the padding target that makes
    jit signatures stable across nearby build/probe sizes."""
    b = max(int(floor), 1)
    while b < n:
        b *= 2
    return b


def _note_trace(kernel: str) -> None:
    """Trace-time side effect: the enclosing jitted body executes only
    while XLA traces it, so this counts compilations (cache misses),
    never steady-state dispatches."""
    from tidb_tpu.utils.metrics import JOIN_COMPILE_TOTAL

    JOIN_COMPILE_TOTAL.inc(kernel=kernel)
    dispatch.record_compile(kernel)


# -- key packing (device) ---------------------------------------------------

def as_int64_key(d: jax.Array, mode: str) -> jax.Array:
    """Comparable int64 key; floats by bit pattern ('bits' mode)."""
    if mode == "bits":
        return jax.lax.bitcast_convert_type(d.astype(jnp.float64), jnp.int64)
    return d.astype(jnp.int64)


def hash_combine_device(keys_i64) -> jax.Array:
    """uint64 mixing hash of composite int64 keys (splitmix64 finalizer,
    identical to the host combiner in executor/join.py)."""
    h = jnp.zeros_like(keys_i64[0], dtype=jnp.uint64)
    for k in keys_i64:
        z = jax.lax.bitcast_convert_type(k, jnp.uint64) + jnp.uint64(SM_ADD)
        z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(SM_MUL1)
        z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(SM_MUL2)
        z = z ^ (z >> jnp.uint64(31))
        h = h * jnp.uint64(SM_ADD) ^ z
    return jax.lax.bitcast_convert_type(h, jnp.int64)


def _pack_device(key_datas, key_valids, los, strides, rngs, sel,
                 modes: Tuple[str, ...], hash_mode: bool):
    """(packed int64, key-valid, in-range) from per-key arrays + traced
    pack ranges. Mirrors the host packer exactly: range packing with an
    out-of-range mask (a definite non-match, NOT a NULL — anti joins keep
    the row), or the mixing hash when ranges overflowed int64."""
    ones = jnp.ones_like(sel)
    if not key_datas:  # keyless (cross) join: constant key matches all
        return jnp.zeros(sel.shape[0], dtype=jnp.int64), ones, ones
    if hash_mode:
        keys = [as_int64_key(d, m) for d, m in zip(key_datas, modes)]
        valid = key_valids[0]
        for v in key_valids[1:]:
            valid = valid & v
        # every hash is "in range"; exact per-key verification removes
        # false candidates after expansion
        return hash_combine_device(keys), valid, ones
    if len(key_datas) == 1:
        return as_int64_key(key_datas[0], modes[0]), key_valids[0], ones
    packed = jnp.zeros(sel.shape[0], dtype=jnp.int64)
    valid = ones
    in_range = ones
    for i, (d, v) in enumerate(zip(key_datas, key_valids)):
        d = as_int64_key(d, modes[i])
        lo, stride, rng = los[i], strides[i], rngs[i]
        valid = valid & v
        in_range = in_range & (d >= lo) & (d < lo + rng)
        packed = packed + jnp.clip(d - lo, 0, jnp.maximum(rng - 1, 0)) * stride
    return packed, valid, in_range


# the fused scan→probe program (executor/pipeline.py) traces the SAME
# packing step as the standalone probe kernel, so the two cannot drift
# on multi-key range packing or the out-of-range mask
pack_keys = _pack_device


# -- build: pack + sort + payload gather, all on device ---------------------

@functools.partial(jax.jit, static_argnames=("modes", "hash_mode"))
def _build_sort(key_datas, key_valids, ok, payload_datas, payload_valids,
                los, strides, rngs, modes, hash_mode):
    _note_trace("build_sort")
    B = ok.shape[0]
    packed, kvalid, in_range = _pack_device(
        key_datas, key_valids, los, strides, rngs, ok, modes, hash_mode)
    live = ok & kvalid & in_range
    # dead keys -> INT64_MAX; a stable secondary flag sorts them AFTER
    # any legitimate INT64_MAX keys, so [0, n_build) is exactly the live
    # sorted prefix and searchsorted ranges clamp against it losslessly
    skey = jnp.where(live, packed, I64_MAX)
    flag = (~live).astype(jnp.int32)
    sorted_keys, _, order = jax.lax.sort(
        (skey, flag, jnp.arange(B, dtype=jnp.int64)), num_keys=2)
    n_build = jnp.sum(live.astype(jnp.int64))
    out_d = tuple(jnp.take(d, order, mode="clip") for d in payload_datas)
    out_v = tuple(jnp.take(v, order, mode="clip") for v in payload_valids)
    # raw key values build-sorted — only hash-mode exact verification
    # reads them; hash_mode is static, so non-hash builds pay nothing
    out_k = (tuple(jnp.take(as_int64_key(d, m), order, mode="clip")
                   for d, m in zip(key_datas, modes))
             if hash_mode else ())
    return sorted_keys, n_build, out_d, out_v, out_k


def build_sort(key_datas, key_valids, ok, payload_datas, payload_valids,
               los, strides, rngs, modes, hash_mode):
    """Device-resident build: returns (sorted_keys [B], n_build scalar,
    sorted payload datas/valids, sorted raw key values). Inputs must be
    padded to a ``shape_bucket`` capacity with ok=False padding."""
    dispatch.record(site="jit:join.build")
    return _build_sort(key_datas, key_valids, ok, payload_datas,
                       payload_valids, los, strides, rngs,
                       modes=tuple(modes), hash_mode=bool(hash_mode))


# -- direct-address (radix-histogram) index over the packed-key domain ------

@functools.partial(jax.jit, static_argnames=("rng_bucket",))
def _build_direct_index(sorted_keys, n_build, lo, rng_bucket):
    _note_trace("direct_index")
    B = sorted_keys.shape[0]
    live = jnp.arange(B, dtype=jnp.int64) < n_build
    idx = jnp.clip(sorted_keys - lo, 0, rng_bucket - 1)
    # radix histogram by scatter-add: counts[k] = live keys equal to lo+k
    # (every live key is in [lo, lo+rng) by construction of the index)
    counts = jnp.zeros(rng_bucket + 1, dtype=jnp.int64).at[
        jnp.where(live, idx, rng_bucket)].add(1, mode="drop")
    # firsts[i] = first sorted position with key >= lo + i; probes then
    # resolve in O(1) gathers instead of O(log B) dependent rounds
    return jnp.concatenate([jnp.zeros(1, dtype=jnp.int64),
                            jnp.cumsum(counts[:rng_bucket])])


def build_direct_index(sorted_keys, n_build, lo, rng_bucket: int):
    """[rng_bucket + 1] run-start positions over the dense packed-key
    domain [lo, lo + rng_bucket): the partition-then-probe structure.
    Built once per join build; XLA:CPU measures the O(1) gather probe
    ~30x faster than its searchsorted lowering (and on TPU it replaces
    log(B) dependent gather rounds with two vector gathers)."""
    dispatch.record(site="jit:join.build")
    return _build_direct_index(sorted_keys, n_build,
                               jnp.asarray(lo, dtype=jnp.int64),
                               rng_bucket=int(rng_bucket))


# -- open-addressing hash table over the sorted build keys ------------------

@functools.partial(jax.jit, static_argnames=("cap",))
def _build_hash_table(sorted_keys, cap):
    _note_trace("hash_table")
    from tidb_tpu.ops.hash_probe import _build_table

    return _build_table(sorted_keys, cap)


def build_hash_table(sorted_keys):
    """(keys32, lo32, hi32, all_placed) open-addressing table over the
    sorted build keys — built ONCE per join build (like the
    direct-address index) and passed to every probe_count as args, so
    the per-chunk probe is MAX_PROBES vectorized window rounds instead
    of O(log B) dependent searchsorted gathers (ISSUE 10: the TPU-shaped
    main-join probe). Returns None when the build side exceeds the VMEM
    capacity envelope — the caller stays on searchsorted. The sentinel
    tail (NULL/dead keys at INT64_MAX) forms ordinary runs whose ranges
    the probe's n_build clamp truncates exactly like searchsorted's."""
    from tidb_tpu.ops.hash_probe import table_capacity

    cap = table_capacity(sorted_keys.shape[0])
    if cap is None:
        return None
    dispatch.record(site="jit:join.build")
    return _build_hash_table(sorted_keys, cap=cap)


_NO_TABLE = None


def no_table():
    """Placeholder table args for the searchsorted path: tiny constant-
    shape arrays the kernel's static 'sorted' branch never reads (XLA
    dead-code-eliminates them), keeping one probe_count signature.
    Memoized — probe_count runs once per probe chunk, and minting four
    device constants per chunk would tax the very hot path this module
    exists to thin (first call is lazy so no arrays materialize at
    import, before backend selection)."""
    global _NO_TABLE
    if _NO_TABLE is None:
        _NO_TABLE = (jnp.full(2, 0x7FFFFFFF, dtype=jnp.int32),
                     jnp.zeros(2, dtype=jnp.int32),
                     jnp.zeros(2, dtype=jnp.int32), jnp.asarray(False))
    return _NO_TABLE


def probe_ranges_any(sorted_keys, n_build, packed, firsts, lo_packed,
                     rng_packed, tkeys, tlos, this, tok,
                     direct: bool, probe: str):
    """(start, end, in_range) match ranges per packed probe key — THE
    range-lookup step, traced inside both the standalone probe kernel
    and the fused scan→probe program so the two cannot drift. Strategy
    is static: 'direct' wins when the dense-domain index exists (two
    O(1) gathers beat any hash walk), else the open-addressing table
    ('xla' window scan / 'pallas' VMEM kernel) with the in-jit lax.cond
    searchsorted fallback when the build overflowed its displacement
    bound, else plain searchsorted. Ranges clamp to n_build so the
    NULL/dead/padding sentinel tail can never produce a match."""
    from tidb_tpu.ops import hash_probe as hp

    ones = jnp.ones(packed.shape[0], dtype=jnp.bool_)
    if direct:
        # dense domain: two gathers into the radix histogram's prefix sums
        idx = packed - lo_packed
        in_range = (idx >= 0) & (idx < rng_packed)
        idxc = jnp.clip(idx, 0, firsts.shape[0] - 2)
        return jnp.take(firsts, idxc), jnp.take(firsts, idxc + 1), in_range
    if probe != "sorted":
        def fast(_):
            fn = hp._probe_pallas if probe == "pallas" else hp._probe_xla
            return fn(tkeys, tlos, this, sorted_keys, packed,
                      tkeys.shape[0])

        def slow(_):
            lo = jnp.searchsorted(sorted_keys, packed, side="left")
            hi = jnp.searchsorted(sorted_keys, packed, side="right")
            return lo.astype(jnp.int64), hi.astype(jnp.int64)

        start, end = jax.lax.cond(tok, fast, slow, None)
    else:
        start = jnp.searchsorted(sorted_keys, packed, side="left")
        end = jnp.searchsorted(sorted_keys, packed, side="right")
    # the region past n_build holds NULL/dead/padding sentinels: clamp
    # so a probe of INT64_MAX counts only the genuine run
    return (jnp.minimum(start, n_build), jnp.minimum(end, n_build), ones)


# -- probe: pack + range lookup + count + prefix sum, one kernel ------------

@functools.partial(jax.jit, static_argnames=("modes", "hash_mode",
                                             "left_pad", "direct", "probe"))
def _probe_count(sorted_keys, n_build, key_datas, key_valids, sel,
                 los, strides, rngs, firsts, lo_packed, rng_packed,
                 tkeys, tlos, this, tok,
                 modes, hash_mode, left_pad, direct, probe):
    _note_trace("probe")
    packed, kvalid, in_range = _pack_device(
        key_datas, key_valids, los, strides, rngs, sel, modes, hash_mode)
    ok = kvalid & sel
    start, end, range_ok = probe_ranges_any(
        sorted_keys, n_build, packed, firsts, lo_packed, rng_packed,
        tkeys, tlos, this, tok, direct, probe)
    in_range = in_range & range_ok
    count = jnp.where(ok & in_range, end - start, 0)
    matched = count > 0
    real_count = count
    if left_pad:
        # unfiltered LEFT JOIN: every live probe row emits >= 1 slot; the
        # slot beyond real_count carries NULL build payload
        count = jnp.where(sel, jnp.maximum(count, 1), 0)
    cum = jnp.cumsum(count)
    return start, count, real_count, cum, cum[-1], ok, matched


def probe_count(sorted_keys, n_build, key_datas, key_valids, sel,
                los, strides, rngs, firsts, lo_packed, rng_packed,
                modes, hash_mode, left_pad, direct,
                table=None, probe="sorted"):
    """Fused probe over one chunk: (start, count, real_count, cum, total,
    ok, matched). ``total`` is the only value a caller syncs to the
    host (to size the expansion). ``table`` is the prebuilt
    open-addressing table (build_hash_table) consulted when ``probe``
    is 'xla'/'pallas'; 'sorted' takes placeholder args and the
    searchsorted branch."""
    from tidb_tpu.utils.metrics import JOIN_PROBE_MODE_TOTAL

    probe = "sorted" if table is None else str(probe)
    JOIN_PROBE_MODE_TOTAL.inc(mode="direct" if direct else probe)
    tkeys, tlos, this, tok = table if table is not None else no_table()
    dispatch.record(site="jit:join.probe")
    return _probe_count(sorted_keys, n_build, key_datas, key_valids, sel,
                        los, strides, rngs, firsts,
                        jnp.asarray(lo_packed, dtype=jnp.int64),
                        jnp.asarray(rng_packed, dtype=jnp.int64),
                        tkeys, tlos, this, tok,
                        modes=tuple(modes), hash_mode=bool(hash_mode),
                        left_pad=bool(left_pad), direct=bool(direct),
                        probe=probe)


# -- shared expand-position arithmetic --------------------------------------

def tile_positions(start, count, cum, w0, n_slots: int,
                   n_probe_cap: int, n_build_cap: int):
    """Map output slots [w0, w0 + n_slots) to (valid_out, probe_row,
    build_pos, k).

    The single source of truth for windowed join expansion — traced both
    inside ``expand_tiles`` (local executor) and inside the fragment
    tier's shard_map program, so the two tiers cannot drift.

    probe_row is recovered with a scatter + prefix sum over the window
    (probe_row(j) = #{r : cum[r] <= w0 + j} = a scalar searchsorted at
    the window base plus the running count of row boundaries inside the
    window) instead of an elementwise searchsorted — O(R + n_slots)
    vector work where XLA:CPU's searchsorted lowering paid ~20 ms per
    2^17-slot window."""
    w0 = jnp.asarray(w0, dtype=jnp.int64)
    total = cum[-1]
    j = w0 + jnp.arange(n_slots, dtype=jnp.int64)
    valid_out = j < total
    row0 = jnp.searchsorted(cum, w0, side="right")  # scalar: window base
    bound = cum - w0  # row r's matches end at window-relative slot bound[r]
    in_win = (bound >= 1) & (bound <= n_slots - 1)
    marks = jnp.zeros(n_slots + 1, dtype=jnp.int64).at[
        jnp.where(in_win, bound, n_slots)].add(1, mode="drop")
    probe_row = jnp.clip(row0 + jnp.cumsum(marks[:n_slots]),
                         0, n_probe_cap - 1)
    k = j - (cum[probe_row] - count[probe_row])
    build_pos = jnp.clip(start[probe_row] + k, 0, max(n_build_cap - 1, 0))
    return valid_out, probe_row, build_pos, k


# -- expand: gather probe + build payload into [T, C] tiles -----------------

@functools.partial(jax.jit, static_argnames=(
    "n_tiles", "tile_cap", "build_cap", "left",
    "with_probe_row", "with_build_pos"))
def _expand_tiles(start, count, real_count, cum, w0,
                  probe_datas, probe_valids, build_datas, build_valids,
                  n_tiles, tile_cap, build_cap, left,
                  with_probe_row, with_build_pos):
    _note_trace("expand")
    R = start.shape[0]
    # build_cap is explicit, NOT inferred from the payload: semi/anti
    # joins carry no payload columns but still need exact __build_pos__
    # for hash-mode key verification
    B = build_cap
    valid_out, probe_row, build_pos, k = tile_positions(
        start, count, cum, w0, n_tiles * tile_cap, R, B)
    real = k < real_count[probe_row]

    def shape(a):
        return a.reshape(n_tiles, tile_cap)

    out_p = tuple((shape(jnp.take(d, probe_row, mode="clip")),
                   shape(jnp.take(v, probe_row, mode="clip") & valid_out))
                  for d, v in zip(probe_datas, probe_valids))
    out_b = []
    for d, v in zip(build_datas, build_valids):
        bv = jnp.take(v, build_pos, mode="clip") & valid_out
        if left:
            # the left-join pad slot (k beyond the real match count)
            # carries NULL build payload
            bv = bv & real
        out_b.append((shape(jnp.take(d, build_pos, mode="clip")), shape(bv)))
    prow = shape(probe_row) if with_probe_row else None
    bpos = shape(build_pos) if with_build_pos else None
    return out_p, tuple(out_b), shape(valid_out), prow, bpos


def expand_tiles(start, count, real_count, cum, w0,
                 probe_datas, probe_valids, build_datas, build_valids,
                 n_tiles, tile_cap, build_cap, left=False,
                 with_probe_row=False, with_build_pos=False):
    """One fused dispatch emitting ``n_tiles`` output tiles of capacity
    ``tile_cap`` ([T, C] arrays — the partition.py streaming layout)
    starting at flat output offset ``w0``."""
    dispatch.record(site="jit:join.expand")
    return _expand_tiles(
        start, count, real_count, cum, jnp.asarray(w0, dtype=jnp.int64),
        tuple(probe_datas), tuple(probe_valids),
        tuple(build_datas), tuple(build_valids),
        n_tiles=int(n_tiles), tile_cap=int(tile_cap),
        build_cap=int(build_cap), left=bool(left),
        with_probe_row=bool(with_probe_row),
        with_build_pos=bool(with_build_pos))


# -- fragment-tier primitives (traced inside shard_map) ---------------------

def sort_build_hashes(b_hash, b_live):
    """Sorted-run build for the repartitioned fragment join: (sorted
    hashes, cvi, order) where dead rows sort after live rows of the same
    hash and ``cvi[i]`` counts live rows in the sorted prefix [0, i) —
    so (cvi[hi] - cvi[lo]) is an exact live-match count per range."""
    Rb = b_hash.shape[0]
    inval = (~b_live).astype(jnp.int32)
    sh, sinv, order = jax.lax.sort(
        (b_hash, inval, jnp.arange(Rb)), num_keys=2)
    cvi = jnp.concatenate([
        jnp.zeros(1, dtype=jnp.int64),
        jnp.cumsum((sinv == 0).astype(jnp.int64)),
    ])
    return sh, cvi, order


def probe_hash_ranges(sh, cvi, p_hash, p_ok, mode=None):
    """(lo, cnt) per probe row over a sorted build-hash array, through
    the configured probe strategy (ops/hash_probe: open-addressing table
    on TPU, searchsorted elsewhere — identical range semantics).
    ``mode`` threads the per-statement tidb_tpu_join_probe_mode from
    the fragment args (ISSUE 12); None = process default."""
    from tidb_tpu.ops.hash_probe import probe_for_join

    lo, hi = probe_for_join(sh, p_hash, mode=mode)
    cnt = jnp.where(p_ok, cvi[hi] - cvi[lo], 0)
    return lo, cnt
