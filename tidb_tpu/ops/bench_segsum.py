#!/usr/bin/env python
"""Microbenchmark: Pallas one-hot segment kernels vs XLA scatter-add.

Run on the target accelerator; writes ops/SEGSUM_BENCH.json next to
this file. The dispatch defaults in segment_sum.py are justified by
this artifact (re-run on new hardware/jax versions).

    python -m tidb_tpu.ops.bench_segsum
"""

# lint: module-disable=jit-hygiene -- offline microbench: per-config
# fresh jits ARE the experiment (cold compile + steady state timed)
# lint: module-disable=host-sync -- correctness cross-checks fetch
# every result on purpose; nothing here runs under a query

import json
import os
import time

import numpy as np


def main():
    import tidb_tpu  # noqa: F401 (x64 config)
    import jax
    import jax.numpy as jnp

    from tidb_tpu.ops.segment_sum import (
        segment_count,
        segment_sum_f32,
        segment_sum_i64,
        xla_segment_sum,
    )

    rng = np.random.default_rng(0)
    R = 1 << 20
    vals = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    # i64 config: scaled-decimal magnitudes (Q1 extendedprice ~ 1e7 at
    # scale 2); exactness matters, not just speed
    ivals = jnp.asarray(rng.integers(-(10 ** 7), 10 ** 7, R, dtype=np.int64))
    mask = jnp.asarray(rng.random(R) < 0.7)

    def bench(fn, *args, reps=20):
        jax.block_until_ready(fn(*args))
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps

    results = {
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "rows": R,
        "configs": [],
    }
    for g in (16, 256, 2048):
        seg = jnp.asarray(rng.integers(0, g, R).astype(np.int32))
        want = np.zeros(g, np.float64)
        np.add.at(want, np.asarray(seg), np.asarray(vals, np.float64))
        got = np.asarray(segment_sum_f32(vals, seg, g))
        err = float(np.abs(got - want).max() / max(np.abs(want).max(), 1.0))
        wc = np.zeros(g, np.int64)
        np.add.at(wc, np.asarray(seg)[np.asarray(mask)], 1)
        exact = bool((np.asarray(segment_count(mask, seg, g)) == wc).all())
        wi = np.zeros(g, np.int64)
        np.add.at(wi, np.asarray(seg), np.asarray(ivals))
        i64_exact = bool((np.asarray(segment_sum_i64(ivals, seg, g)) == wi).all())
        t_ps = bench(lambda v, s, g=g: segment_sum_f32(v, s, g), vals, seg)
        t_xs = bench(jax.jit(lambda v, s, g=g: xla_segment_sum(v, s, g)), vals, seg)
        t_pc = bench(lambda m, s, g=g: segment_count(m, s, g), mask, seg)
        t_xc = bench(jax.jit(
            lambda m, s, g=g: xla_segment_sum(m.astype(jnp.int64), s, g)), mask, seg)
        t_pi = bench(lambda v, s, g=g: segment_sum_i64(v, s, g), ivals, seg)
        t_xi = bench(jax.jit(
            lambda v, s, g=g: xla_segment_sum(v, s, g)), ivals, seg)
        results["configs"].append({
            "G": g, "sum_rel_err": err, "count_exact": exact,
            "i64_exact": i64_exact,
            "sum_pallas_ms": round(t_ps * 1e3, 3),
            "sum_xla_ms": round(t_xs * 1e3, 3),
            "sum_speedup": round(t_xs / t_ps, 2),
            "count_pallas_ms": round(t_pc * 1e3, 3),
            "count_xla_i64_ms": round(t_xc * 1e3, 3),
            "count_speedup": round(t_xc / t_pc, 2),
            "i64_pallas_ms": round(t_pi * 1e3, 3),
            "i64_xla_ms": round(t_xi * 1e3, 3),
            "i64_speedup": round(t_xi / t_pi, 2),
        })
        print(results["configs"][-1])

    path = os.path.join(os.path.dirname(__file__), "SEGSUM_BENCH.json")
    with open(path, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
