"""Microbenchmark: hash-table probe vs searchsorted (the fragment
join's probe step). Run on whatever backend is live:

    python -m tidb_tpu.ops.bench_probe

Writes ops/PROBE_BENCH.json: per-size best-of-5 timings for the two
strategies (plus the Pallas kernel on TPU), the same (lo, hi) contract
the join consumes."""

# lint: module-disable=jit-hygiene -- offline microbench: per-config
# fresh jits ARE the experiment (cold compile + steady state timed)
# lint: module-disable=host-sync -- result fetch is the measurement
# boundary, not a hot path; nothing here runs under a query

import json
import os
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import tidb_tpu  # noqa: F401 (x64)
    from tidb_tpu.ops import hash_probe as hp
    from tidb_tpu.ops.segment_sum import pallas_enabled

    plat = jax.devices()[0].platform
    out = {"platform": plat, "max_probes": hp.MAX_PROBES, "sizes": []}
    rng = np.random.default_rng(7)
    for nb, npr in [(1 << 12, 1 << 20), (1 << 16, 1 << 20), (1 << 18, 1 << 21)]:
        build = np.sort(rng.integers(0, 1 << 40, nb))
        probes = rng.integers(0, 1 << 41, npr)
        sh = jnp.asarray(build)
        pr = jnp.asarray(probes)
        row = {"build": nb, "probes": npr}

        def timed(fn):
            r = fn()
            jax.block_until_ready(r)
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                jax.block_until_ready(fn())
                best = min(best, time.perf_counter() - t0)
            return best, r

        ss = jax.jit(lambda a, b: hp.xla_probe_ranges(a, b))
        t_ss, r_ss = timed(lambda: ss(sh, pr))
        row["searchsorted_s"] = round(t_ss, 5)
        t_tab, r_tab = timed(lambda: hp.probe_ranges(sh, pr, use_pallas=False))
        row["table_xla_s"] = round(t_tab, 5)
        c_ok = bool((np.asarray(r_ss[1]) - np.asarray(r_ss[0])
                     == np.asarray(r_tab[1]) - np.asarray(r_tab[0])).all())
        row["counts_match"] = c_ok
        if pallas_enabled():
            t_pl, r_pl = timed(
                lambda: hp.probe_ranges(sh, pr, use_pallas=True))
            row["table_pallas_s"] = round(t_pl, 5)
            row["pallas_counts_match"] = bool(
                (np.asarray(r_pl[1]) - np.asarray(r_pl[0])
                 == np.asarray(r_ss[1]) - np.asarray(r_ss[0])).all())
        row["speedup_vs_searchsorted"] = round(
            t_ss / min(t_tab, row.get("table_pallas_s", t_tab)), 2)
        out["sizes"].append(row)
        print(row, flush=True)
    path = os.path.join(os.path.dirname(__file__), "PROBE_BENCH.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
