"""Fused segment decode + scan pipeline (device side of the columnar
segment store).

One module-level program per (pipeline stages, column layout) pair:
the encoded columns cross the host→device boundary in their narrow
storage dtypes (int8/int16/int32 frame-of-reference payloads, raw
floats/bools), and the decode — ``ref + stored`` widened to the
column's device repr — happens INSIDE the jitted program, fused with
the scan's pushed filter and projections. Device bytes moved shrink
with the encoding; XLA dead-code-eliminates the decode of columns the
pipeline projects away.

Frame-of-reference refs arrive as ARGS (per-segment values must not
bake into the trace — jit keys on the dict structure and dtypes only),
so a repeated scan re-traces nothing across segments of the same
layout. Callers go through ``cached_jit`` keyed on (stages, layout).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column

__all__ = ["make_segment_scan_fn", "segment_scan_key"]


def segment_scan_key(stages, col_types) -> str:
    """Cache key covering everything the closure bakes in: the compiled
    pipeline IR and the (uid -> SQLType) output layout."""
    return repr(stages) + "|" + repr(
        [(uid, t.kind.value, t.precision, t.scale, t.members)
         for uid, t in col_types])


def make_segment_scan_fn(stages, col_types: List[Tuple[str, object]]
                         ) -> Callable:
    """Build the Chunk-producing program for one scan layout.

    `col_types`: (uid, SQLType) pairs of the staged storage columns.
    The returned function takes (data, valid, refs, sel) dicts/arrays —
    refs holds the FoR base per encoded uid (absent for raw columns) —
    and returns the post-pipeline Chunk.
    """
    from tidb_tpu.executor.scan import make_pipeline_fn

    pipeline = make_pipeline_fn(stages) if stages else None
    types = list(col_types)

    def run(data: Dict, valid: Dict, refs: Dict, sel) -> Chunk:
        cols = {}
        for uid, t in types:
            d = data[uid]
            dt = t.np_dtype
            r = refs.get(uid)
            if r is not None:
                d = d.astype(dt) + r.astype(dt)  # fused FoR decode
            elif d.dtype != dt:
                d = d.astype(dt)
            cols[uid] = Column(d, valid[uid], t)
        ch = Chunk(cols, sel)
        return pipeline(ch) if pipeline is not None else ch

    return run
