"""Fused segment decode + scan pipeline (device side of the columnar
segment store).

One module-level program per (pipeline stages, column layout) pair:
the encoded columns cross the host→device boundary in their narrow
storage dtypes (int8/int16/int32 frame-of-reference payloads, raw
floats/bools), and the decode — ``ref + stored`` widened to the
column's device repr — happens INSIDE the jitted program, fused with
the scan's pushed filter and projections. Device bytes moved shrink
with the encoding; XLA dead-code-eliminates the decode of columns the
pipeline projects away.

Frame-of-reference refs arrive as ARGS (per-segment values must not
bake into the trace — jit keys on the dict structure and dtypes only),
so a repeated scan re-traces nothing across segments of the same
layout. Callers go through ``cached_jit`` keyed on (stages, layout).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column

__all__ = ["make_segment_scan_fn", "segment_scan_key", "decode_for"]


def decode_for(d, ref, np_dtype):
    """THE in-program FoR decode: widen the narrow stored payload to the
    column's device repr and add the base (``ref`` None = raw staging —
    dtype-align only). One definition shared by every staging consumer
    (fused scan batches here, `distsql._shard_chunk`, the fragment scan
    producer) so the single-chip and distributed tiers can never decode
    differently."""
    import jax.numpy as jnp

    if ref is not None:
        return d.astype(np_dtype) + jnp.asarray(ref).astype(np_dtype)
    if d.dtype != np_dtype:
        return d.astype(np_dtype)
    return d


def segment_scan_key(stages, col_types, seg_stride: Optional[int] = None
                     ) -> str:
    """Cache key covering everything the closure bakes in: the compiled
    pipeline IR, the (uid -> SQLType) output layout, and the packed
    segment stride (a static shape divisor when present)."""
    return repr(stages) + "|" + repr(
        [(uid, t.kind.value, t.precision, t.scale, t.members)
         for uid, t in col_types]) + f"|stride={seg_stride}"


def make_segment_scan_fn(stages, col_types: List[Tuple[str, object]],
                         seg_stride: Optional[int] = None) -> Callable:
    """Build the Chunk-producing program for one scan layout.

    `col_types`: (uid, SQLType) pairs of the staged storage columns.
    The returned function takes (data, valid, refs, sel) dicts/arrays —
    refs holds the FoR base per encoded uid (absent for raw columns) —
    and returns the post-pipeline Chunk.

    With `seg_stride`, the staged buffer packs SEVERAL segments at a
    fixed stride (the fused pipeline's multi-segment batches, ISSUE 9):
    a ref may then be a [k]-shaped per-segment base vector, and row i
    decodes against ref[i // seg_stride] — the segment id is derived on
    device from an iota, so the narrow payload is still all that moves
    across the host→device boundary.
    """
    from tidb_tpu.executor.scan import make_pipeline_fn

    pipeline = make_pipeline_fn(stages) if stages else None
    types = list(col_types)

    def run(data: Dict, valid: Dict, refs: Dict, sel) -> Chunk:
        import jax.numpy as jnp

        cols = {}
        for uid, t in types:
            d = data[uid]
            r = refs.get(uid)
            if r is not None and seg_stride is not None \
                    and getattr(r, "ndim", 0) >= 1:
                # packed batch: per-segment FoR bases, gathered by the
                # device-computed segment id
                r = r[jnp.arange(d.shape[0]) // seg_stride]
            cols[uid] = Column(decode_for(d, r, t.np_dtype), valid[uid], t)
        ch = Chunk(cols, sel)
        return pipeline(ch) if pipeline is not None else ch

    return run
