"""jit-hygiene pass: device programs must be module-level and
argument-driven.

The PR 3 bug class: a ``jax.jit`` / ``counted_jit`` / ``shard_map``
wrapped program minted inside a function gets a fresh Python identity
per call, so jax's trace cache can never hit — every execution
re-traces — and any value it closes over is frozen at trace time, so a
cache hit (via an outer memo) can silently read a STALE closure.  Both
failure modes disappear when the program lives at module level and
every query-specific value arrives as an argument.

Rule: any wrapper application at function scope is a violation; the
message names the outer variables the wrapped function captures (the
retrace/staleness surface).  The sanctioned escape for legitimately
dynamic programs is ``utils.jitcache.cached_jit`` / a signature-keyed
cache, with a line suppression explaining the key discipline.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tidb_tpu.analysis.core import Pass, Project, SourceFile, Violation

__all__ = ["JitHygienePass"]

# modules whose exported callables are jit-family wrappers
_WRAPPER_IMPORTS = {
    ("jax", "jit"), ("jax", "shard_map"),
    ("jax.experimental.shard_map", "shard_map"),
    ("tidb_tpu.utils.dispatch", "counted_jit"),
    ("tidb_tpu.parallel.mesh", "shard_map_compat"),
}


def _bound_names(fn: ast.AST) -> Set[str]:
    """Names bound inside a function scope (params + any assignment
    target + comprehension/for/with/except targets + local defs),
    NOT descending into nested function scopes (their bindings are
    their own)."""
    out: Set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = fn.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            out.add(arg.arg)

    def walk(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(node.name)
            return  # its body is a new scope
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.ClassDef):
            out.add(node.name)
            return
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            out.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                out.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, ast.ExceptHandler) and node.name:
            out.add(node.name)
        for child in ast.iter_child_nodes(node):
            walk(child)

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        walk(stmt)
    return out


def _loaded_names(fn: ast.AST) -> Set[str]:
    """Names read inside a function INCLUDING nested scopes (a nested
    lambda reading an outer name still captures it)."""
    out: Set[str] = set()
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                out.add(node.id)
    return out


class JitHygienePass(Pass):
    id = "jit-hygiene"
    doc = ("jit/counted_jit/shard_map wraps must be module-level; "
           "query-specific values arrive as arguments, never closures")

    def run(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for sf in project.files():
            out.extend(self._check_module(sf))
        # one violation per wrap site even when wrappers nest
        # (jax.jit(shard_map_compat(...)) is one device program)
        seen = set()
        uniq = []
        for v in out:
            key = (v.path, v.line)
            if key not in seen:
                seen.add(key)
                uniq.append(v)
        return uniq

    # ------------------------------------------------------------------

    def _check_module(self, sf: SourceFile) -> List[Violation]:
        wrappers = self._wrapper_names(sf.tree)
        out: List[Violation] = []

        def visit(node: ast.AST, fn_stack: List[ast.AST]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_wrapper(dec, wrappers) and fn_stack:
                        out.append(self._violation(
                            sf, dec, node, fn_stack,
                            f"`{node.name}` is jit-wrapped at function "
                            f"scope (decorator)"))
                fn_stack = fn_stack + [node]
            elif isinstance(node, ast.Lambda):
                fn_stack = fn_stack + [node]
            elif isinstance(node, ast.Call) and self._is_wrapper(
                    node.func, wrappers):
                if fn_stack:
                    target = self._wrapped_target(node, fn_stack[-1])
                    out.append(self._violation(
                        sf, node, target, fn_stack,
                        "device program wrapped at function scope"))
            for child in ast.iter_child_nodes(node):
                visit(child, fn_stack)

        visit(sf.tree, [])
        return out

    def _violation(self, sf: SourceFile, site: ast.AST,
                   target: Optional[ast.AST], fn_stack: List[ast.AST],
                   what: str) -> Violation:
        captured: List[str] = []
        if target is not None:
            enclosing_bound: Set[str] = set()
            for fn in fn_stack:
                enclosing_bound |= _bound_names(fn)
            free = _loaded_names(target) - _bound_names(target)
            captured = sorted(free & enclosing_bound)
        msg = (f"{what}: fresh jit identity per call (retrace) and any "
               "captured value goes stale on cache hits")
        if captured:
            msg += f"; closes over {', '.join(captured)}"
        msg += (". Hoist to module level with the dynamic values as "
                "arguments, or route through a signature-keyed cache "
                "(utils.jitcache.cached_jit) and suppress with the key "
                "discipline as the reason.")
        return Violation(self.id, sf.rel, site.lineno, msg)

    @staticmethod
    def _wrapped_target(call: ast.Call,
                        scope: ast.AST) -> Optional[ast.AST]:
        """The function object being wrapped: a lambda argument, or the
        local def a Name argument points at."""
        if not call.args:
            return None
        arg = call.args[0]
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            body = scope.body if isinstance(scope.body, list) else []
            for stmt in body:
                for node in ast.walk(stmt):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                            and node.name == arg.id:
                        return node
        return None

    @staticmethod
    def _wrapper_names(tree: ast.Module) -> Set[str]:
        """Bare names that are jit-family wrappers in this module."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if (node.module, alias.name) in _WRAPPER_IMPORTS:
                        names.add(alias.asname or alias.name)
        return names

    @staticmethod
    def _is_wrapper(node: ast.AST, wrappers: Set[str]) -> bool:
        # jax.jit / jax.shard_map / dispatch.counted_jit attribute form
        if isinstance(node, ast.Attribute):
            if node.attr in ("jit", "shard_map"):
                root = node.value
                if isinstance(root, ast.Name) and root.id == "jax":
                    return True
                # jax.experimental.shard_map.shard_map
                if isinstance(root, ast.Attribute):
                    return True
            if node.attr in ("counted_jit", "shard_map_compat"):
                return True
        if isinstance(node, ast.Name) and node.id in wrappers:
            return True
        # functools.partial(jax.jit, ...) — the decorator idiom
        if isinstance(node, ast.Call):
            f = node.func
            is_partial = (isinstance(f, ast.Attribute)
                          and f.attr == "partial") or \
                         (isinstance(f, ast.Name) and f.id == "partial")
            if is_partial and node.args:
                return JitHygienePass._is_wrapper(node.args[0], wrappers)
        return False
