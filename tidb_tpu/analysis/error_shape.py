"""error-shape pass: errors stay typed, coded, and visible.

  * no bare ``except:`` anywhere in tidb_tpu/ (it swallows
    KeyboardInterrupt/SystemExit and every typo alike)
  * no silent swallow: an ``except Exception:`` / ``except
    BaseException:`` handler whose body is just ``pass``/``continue``
    must justify itself inline — either the repo's existing
    ``# noqa: BLE001 — <why>`` idiom or a lint suppression.  Narrow
    exception tuples may swallow freely (they name what they expect).
  * typed user-facing errors carry MySQL error codes: every class in
    ``errors.py`` must resolve a ``code`` attribute through the in-file
    hierarchy (the server's error packets read it).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from tidb_tpu.analysis.core import Pass, Project, SourceFile, Violation

__all__ = ["ErrorShapePass"]

# the repo's established annotation for deliberate broad catches
_BLE_RE = re.compile(r"#\s*noqa:\s*BLE001\s*(?:[-—–]+\s*(.*))?$")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _swallows(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(s, (ast.Pass, ast.Continue)) for s in handler.body)


class ErrorShapePass(Pass):
    id = "error-shape"
    doc = ("no bare except, no silent `except Exception: pass` without an "
           "inline reason, error classes carry MySQL codes")

    def run(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for sf in project.files():
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if node.type is None:
                    out.append(Violation(
                        self.id, sf.rel, node.lineno,
                        "bare `except:` catches SystemExit/KeyboardInterrupt"
                        " and every typo — name the exceptions (or "
                        "`except Exception` with a `# noqa: BLE001 — why`)"))
                    continue
                if _is_broad(node) and _swallows(node) \
                        and not self._annotated(sf, node.lineno):
                    out.append(Violation(
                        self.id, sf.rel, node.lineno,
                        "`except Exception: pass` silently swallows every "
                        "failure — narrow the exception types or annotate "
                        "the except line with `# noqa: BLE001 — <why this "
                        "cleanup path may ignore errors>`"))
            if sf.rel.endswith("errors.py"):
                out.extend(self._check_codes(sf))
        return out

    @staticmethod
    def _annotated(sf: SourceFile, line: int) -> bool:
        for ln in (line, line - 1):
            if 1 <= ln <= len(sf.lines):
                m = _BLE_RE.search(sf.lines[ln - 1])
                if m and (m.group(1) or "").strip():
                    return True
        return False

    def _check_codes(self, sf: SourceFile) -> List[Violation]:
        """Every class in errors.py must resolve `code` via in-file
        bases (user-facing packets render it)."""
        classes: Dict[str, ast.ClassDef] = {
            n.name: n for n in sf.tree.body if isinstance(n, ast.ClassDef)}

        def has_code(cls: ast.ClassDef, seen=frozenset()) -> bool:
            for stmt in cls.body:
                if isinstance(stmt, ast.Assign):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name) and tgt.id == "code":
                            return True
            for base in cls.bases:
                name = base.id if isinstance(base, ast.Name) else None
                if name and name in classes and name not in seen:
                    if has_code(classes[name], seen | {name}):
                        return True
            return False

        out = []
        for name, cls in classes.items():
            if not has_code(cls):
                out.append(Violation(
                    self.id, sf.rel, cls.lineno,
                    f"error class {name} resolves no MySQL `code` "
                    "attribute — the protocol layer would fall back to a "
                    "generic errno for a typed error"))
        return out
