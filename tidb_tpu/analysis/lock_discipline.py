"""lock-discipline pass: static deadlock + unlocked-write detection for
the multi-threaded coordinator layer.

Two checks over the modules that own threading locks (DCN, tracing,
plan cache, statement summary, catalog):

  1. lock ordering — every ``with <lock>:`` nesting contributes an
     acquisition edge (including one level of same-class method calls
     made while holding a lock); a cycle in the resulting graph is a
     statically-provable deadlock candidate and fails the build.

  2. mixed locked/unlocked mutation — an attribute mutated under a lock
     somewhere and WITHOUT one elsewhere is a data race waiting for a
     scheduler: the unlocked site is flagged.  ``__init__`` is exempt
     (construction is single-threaded), as are methods whose name ends
     in ``_locked`` (the repo convention: the caller holds the lock),
     lock/thread-local attributes themselves, and thread-confined state
     documented with a line suppression.

Scope is intra-class and name-based (a mutation through a local alias
``h = self._health[i]; h.state = ...`` is invisible) — the pass trades
depth for zero false positives on the patterns the repo actually uses.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tidb_tpu.analysis.core import Pass, Project, SourceFile, Violation

__all__ = ["LockDisciplinePass", "static_lock_edges"]

_MUTATORS = {
    "append", "extend", "add", "remove", "discard", "pop", "popitem",
    "clear", "update", "insert", "setdefault", "move_to_end",
    "appendleft", "popleft",
}

DEFAULT_MODULES = (
    "tidb_tpu/parallel/dcn.py",
    "tidb_tpu/utils/tracing.py",
    "tidb_tpu/planner/plancache.py",
    "tidb_tpu/utils/stmtsummary.py",
    "tidb_tpu/storage/catalog.py",
    "tidb_tpu/serving/scheduler.py",
    "tidb_tpu/serving/batcher.py",
    # columnar segment store (ISSUE 8): the store's leaf lock guards
    # segment residency/spill state shared across concurrent scans
    "tidb_tpu/columnar/store.py",
    # shuffle exchange (ISSUE 13): the inbox lock guards staged-batch
    # state shared by peer-stage RPC threads and the gather/apply phase
    "tidb_tpu/sharding/shuffle.py",
    # plan feedback (ISSUE 15): the store's leaf lock guards per-digest
    # observations folded by concurrent statement-end harvests
    "tidb_tpu/planner/feedback.py",
    # latency SLOs (ISSUE 16): the digest-latency store's leaf lock
    # guards windows folded at statement end and read at admission
    "tidb_tpu/serving/slo.py",
    # background compaction (ISSUE 17): the worker queue lock is a
    # LEAF under the store lock; snapshot/cutover take the store lock
    # only for pointer swaps — the segment build itself runs unlocked
    "tidb_tpu/columnar/compaction.py",
    # fused device top-k (ISSUE 18): lock-free by contract — the merge
    # state lives on device and the pipeline owns all coordination, so
    # any lock acquired here is a discipline violation by definition
    "tidb_tpu/ops/topk.py",
    # topology gates (ISSUE 19): the gate registry's one lock guards
    # per-table reader/writer counts mutated by every statement and
    # every reshard/membership cutover (fixture: bad_membership_lock.py)
    "tidb_tpu/parallel/membership.py",
)

# NOTE: the serving-tier wait-discipline check (ISSUE 7) moved to
# blocking_under_lock.py (ISSUE 12), which generalizes it — waits are
# one of several blocking-call kinds no registered lock may span.


def _is_threading_ctor(node: ast.AST, names: Sequence[str]) -> bool:
    """True if `node` (or any sub-expression) calls threading.<name>()."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            f = sub.func
            if isinstance(f.value, ast.Name) and f.value.id == "threading" \
                    and f.attr in names:
                return True
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.X` / `self.X[...]` -> 'X' (the owning attribute)."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _ClassScan:
    def __init__(self, sf: SourceFile, cls: ast.ClassDef):
        self.sf = sf
        self.cls = cls
        self.lock_attrs: Set[str] = set()
        self.tls_attrs: Set[str] = set()
        # (attr, method, line, locked, thread_entry)
        self.mutations: List[Tuple[str, str, int, bool]] = []
        self.edges: List[Tuple[str, str, str]] = []   # (A, B, "file:line")
        self.method_acquires: Dict[str, Set[str]] = {}
        self.deferred_calls: List[Tuple[str, str, str]] = []  # (A, method, loc)
        self.thread_targets: Set[str] = set()

    def lock_id(self, expr: ast.AST) -> Optional[str]:
        """Normalized node id for a lock expression, or None."""
        attr = _self_attr(expr)
        if attr is not None:
            if attr in self.lock_attrs or attr.endswith(("lock", "locks")):
                return f"{self.cls.name}.{attr}"
            return None
        # foreign lock (e.g. `with store.lock:`): keep the source text
        if isinstance(expr, ast.Attribute) and \
                expr.attr.endswith(("lock", "locks")):
            return ast.unparse(expr)
        return None

    def scan(self) -> None:
        for stmt in self.cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect_attrs(stmt)
        for stmt in self.cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_method(stmt)

    def _collect_attrs(self, fn: ast.FunctionDef) -> None:
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    and node.value is not None:
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    if _is_threading_ctor(node.value, ("Lock", "RLock",
                                                       "Condition")):
                        self.lock_attrs.add(attr)
                    elif _is_threading_ctor(node.value, ("local",)):
                        self.tls_attrs.add(attr)
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and \
                    node.func.attr == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        t = _self_attr(kw.value)
                        if t is not None:
                            self.thread_targets.add(t)
                        elif isinstance(kw.value, ast.Name):
                            self.thread_targets.add(kw.value.id)

    def _scan_method(self, fn: ast.FunctionDef) -> None:
        acquires: Set[str] = set()

        def walk(stmts, held: Tuple[str, ...]) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                self._scan_mutations(stmt, fn, held)
                self._scan_calls(stmt, held)
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    new = list(held)
                    for item in stmt.items:
                        lid = self.lock_id(item.context_expr)
                        if lid is not None:
                            acquires.add(lid)
                            loc = f"{self.sf.rel}:{item.context_expr.lineno}"
                            for h in new:
                                if h != lid:
                                    self.edges.append((h, lid, loc))
                            new.append(lid)
                    walk(stmt.body, tuple(new))
                elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                    walk(stmt.body, held)
                    walk(stmt.orelse, held)
                elif isinstance(stmt, ast.If):
                    walk(stmt.body, held)
                    walk(stmt.orelse, held)
                elif isinstance(stmt, ast.Try):
                    walk(stmt.body, held)
                    for h in stmt.handlers:
                        walk(h.body, held)
                    walk(stmt.orelse, held)
                    walk(stmt.finalbody, held)

        walk(fn.body, ())
        self.method_acquires[fn.name] = acquires

    def _scan_calls(self, stmt: ast.stmt, held: Tuple[str, ...]) -> None:
        if not held:
            return
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self":
                loc = f"{self.sf.rel}:{node.lineno}"
                self.deferred_calls.append((held[-1], node.func.attr, loc))

    def _scan_mutations(self, stmt: ast.stmt, fn: ast.FunctionDef,
                        held: Tuple[str, ...]) -> None:
        locked = bool(held) or fn.name.endswith("_locked")
        skip = {"__init__"}
        if fn.name in skip:
            return
        attrs: List[Tuple[str, int]] = []
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            flat: List[ast.expr] = []
            for tgt in targets:
                # unpack tuple/list targets: `self.a, self.b = ...`
                # mutates both attributes just as surely as two assigns
                stack = [tgt]
                while stack:
                    t = stack.pop()
                    if isinstance(t, (ast.Tuple, ast.List)):
                        stack.extend(t.elts)
                    elif isinstance(t, ast.Starred):
                        stack.append(t.value)
                    else:
                        flat.append(t)
            for tgt in flat:
                base = tgt
                # peel subscripts/attribute chains to the self.X base
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    a = _self_attr(base)
                    if a is not None:
                        attrs.append((a, tgt.lineno))
                        break
                    base = base.value
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                a = _self_attr(tgt)
                if a is not None:
                    attrs.append((a, tgt.lineno))
        elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr in _MUTATORS:
                a = _self_attr(call.func.value)
                if a is not None:
                    attrs.append((a, call.lineno))
        for attr, line in attrs:
            if attr in self.lock_attrs or attr in self.tls_attrs:
                continue
            self.mutations.append((attr, fn.name, line, locked))


def _scan_modules(project: Project, modules: Sequence[str]
                  ) -> List["_ClassScan"]:
    scans: List[_ClassScan] = []
    for sf in project.files():
        if sf.rel not in modules:
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                cs = _ClassScan(sf, node)
                cs.scan()
                scans.append(cs)
    return scans


def _edges_of(scans: List["_ClassScan"]) -> Dict[str, Dict[str, str]]:
    edges: Dict[str, Dict[str, str]] = {}
    acquires_of: Dict[Tuple[str, str], Set[str]] = {}
    for cs in scans:
        for m, acq in cs.method_acquires.items():
            acquires_of[(cs.cls.name, m)] = acq
    for cs in scans:
        for a, b, loc in cs.edges:
            edges.setdefault(a, {}).setdefault(b, loc)
        for held, method, loc in cs.deferred_calls:
            for b in acquires_of.get((cs.cls.name, method), ()):
                if b != held:
                    edges.setdefault(held, {}).setdefault(
                        b, f"{loc} (via {method}())")
    return edges


def static_lock_edges(root: str,
                      modules: Sequence[str] = DEFAULT_MODULES
                      ) -> Dict[str, Dict[str, str]]:
    """The static acquisition-order graph (A -> {B: site}) over the
    registered lock modules — what the AST can prove. The runtime
    sanitizer (analysis/sanitizer.py) diffs its witnessed orders
    against this: a runtime edge absent here came through a path the
    AST cannot see (a prefetch thread, a scheduler worker, a
    finalizer) and is exactly what the witness exists to surface."""
    project = Project(root)
    mods = tuple(m.replace("/", os.sep) for m in modules)
    return _edges_of(_scan_modules(project, mods))


class LockDisciplinePass(Pass):
    id = "lock-discipline"
    doc = ("no lock-acquisition-order cycles; no attribute mutated both "
           "under a lock and without one")

    def __init__(self, modules: Sequence[str] = DEFAULT_MODULES):
        self.modules = tuple(m.replace("/", os.sep) for m in modules)

    def run(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        scans = _scan_modules(project, self.modules)

        # -- mixed locked/unlocked mutation --------------------------------
        for cs in scans:
            by_attr: Dict[str, List[Tuple[str, int, bool]]] = {}
            for attr, method, line, locked in cs.mutations:
                by_attr.setdefault(attr, []).append((method, line, locked))
            for attr, sites in by_attr.items():
                locked_sites = [s for s in sites if s[2]]
                unlocked_sites = [s for s in sites if not s[2]]
                if not locked_sites or not unlocked_sites:
                    continue
                lm, ll, _ = locked_sites[0]
                for method, line, _ in unlocked_sites:
                    entry = (" (a thread entry point)"
                             if method in cs.thread_targets else "")
                    out.append(Violation(
                        self.id, cs.sf.rel, line,
                        f"self.{attr} is mutated without a lock in "
                        f"{cs.cls.name}.{method}{entry} but under one in "
                        f"{cs.cls.name}.{lm} (line {ll}) — a concurrent "
                        "writer can interleave. Take the lock, rename the "
                        "method *_locked if the caller holds it, or "
                        "suppress with the confinement argument."))

        # -- acquisition-order cycles --------------------------------------
        edges = _edges_of(scans)
        cycle = self._find_cycle(edges)
        if cycle is not None:
            path, locs = cycle
            out.append(Violation(
                self.id,
                locs[0].split(":")[0], int(locs[0].split(":")[1].split()[0]),
                "lock-acquisition-order cycle (static deadlock): "
                + " -> ".join(path)
                + " ; acquisition sites: " + "; ".join(locs)))
        return out

    @staticmethod
    def _find_cycle(edges: Dict[str, Dict[str, str]]
                    ) -> Optional[Tuple[List[str], List[str]]]:
        WHITE, GRAY, BLACK = 0, 1, 2
        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(u: str) -> Optional[List[str]]:
            color[u] = GRAY
            stack.append(u)
            for v in edges.get(u, {}):
                c = color.get(v, WHITE)
                if c == GRAY:
                    return stack[stack.index(v):] + [v]
                if c == WHITE:
                    r = dfs(v)
                    if r is not None:
                        return r
            stack.pop()
            color[u] = BLACK
            return None

        for node in list(edges):
            if color.get(node, WHITE) == WHITE:
                path = dfs(node)
                if path is not None:
                    locs = []
                    for a, b in zip(path, path[1:]):
                        locs.append(edges[a][b])
                    return path, locs
        return None
