"""Runtime invariant sanitizer (ISSUE 12): records what actually
happens and diffs it against the static model.

The static passes prove what the AST can see. This layer witnesses the
rest at runtime, behind the ``tidb_tpu_sanitize`` sysvar (or the
``TIDB_TPU_SANITIZE`` env var for whole-process runs):

  * **lock-order witness** — the registered engine locks (created via
    :func:`tracked_lock`) record every nested acquisition into a
    process-global order graph, across ALL threads: orders threaded
    through prefetch threads, scheduler workers, and weakref finalizers
    that the AST cannot see. The graph is cycle-checked at statement
    end, and :func:`diff_static` diffs it against the static lock graph
    (analysis/lock_discipline.static_lock_edges) — a runtime edge the
    static model lacks is exactly the blind spot this exists to light.
  * **tracker balance** — MemTracker release()/detach() report typed
    findings: a double release (consumed below zero) is fatal; a
    detach-time residual (bytes the statement never release()d) is a
    recorded leak witness (the engine's detach() reclaims it by design,
    so it stays non-fatal but visible).
  * **pin balance** — every ScanPin opened during a statement must be
    closed by statement end; a leaked pin is a fatal finding (the class
    of bug that surfaces later as spurious typed OOM).
  * **host-sync budget** — a per-statement counter of
    ``jax.device_get`` round trips (the sanctioned sync chokepoint is
    patched while enabled), asserted against the statement's declared
    budget (``tidb_tpu_sanitize_sync_budget``).
  * **shared-global witness** — registered process-global writes (e.g.
    ``ops.hash_probe.set_mode``) during ANY in-flight statement are
    fatal findings: the set_mode race documented in PR 10 is the
    founding member of this class.
  * **wire witness** (ISSUE 14) — every request crossing a DCN worker
    socket (``parallel/dcn._send`` calls :func:`note_wire_msg` while
    enabled) is diffed against the committed static protocol model
    (``analysis/wire_protocol.json``): an unknown cmd, an unknown
    field, or a missing handler-required field is a typed finding.
    This is the closed loop that keeps the static extractor honest —
    real traffic the model cannot account for means the extractor
    missed a send site, and the model would otherwise silently rot.
    Surfacing: sends on the statement's own thread (2PC, reshard)
    raise through the statement scope like any fatal finding; sends on
    dispatch/scatter worker threads stay in :func:`report` (the
    own-thread rule — blaming a concurrent statement for another's
    traffic cascades one bug into innocent failures), which is why the
    chaos gate asserts ``report()`` wire-clean rather than relying on
    per-statement raises alone.

Import-time this module is stdlib-only (the analyzer contract: never
pull jax into the CLI); the device_get patch imports jax lazily at
enable() — which only ever runs inside a live engine process.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["enabled", "enable", "disable", "tracked_lock", "TrackedLock",
           "statement_begin", "statement_end", "Finding", "report",
           "diff_static", "check_lock_cycle", "reset",
           "note_tracker_release", "note_tracker_detach",
           "note_pin_open", "note_pin_close", "note_global_write",
           "count_sync", "note_wire_msg", "wire_model",
           "set_wire_model"]

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

_FINDINGS_CAP = 256


def env_gate() -> bool:
    """The TIDB_TPU_SANITIZE env seed, with conventional falsy strings
    honored — `TIDB_TPU_SANITIZE=0` must DISABLE, not enable (a bare
    bool() on the string "0" is True)."""
    v = os.environ.get("TIDB_TPU_SANITIZE", "")
    return v.strip().lower() not in ("", "0", "false", "off", "no")


@dataclass
class Finding:
    kind: str          # lock-cycle | tracker-double-release | ...
    subject: str       # what it happened to (lock names, tracker label)
    detail: str
    fatal: bool = True
    thread: str = ""

    def render(self) -> str:
        sev = "FATAL" if self.fatal else "note"
        return f"[sanitizer:{self.kind}] {sev} {self.subject}: {self.detail}"


class _State:
    def __init__(self):
        self.lock = threading.RLock()
        self.enabled = env_gate()
        self.findings: List[Finding] = []
        # runtime lock graph: a -> {b: "thread/site"} for every b
        # acquired while a was held on the same thread
        self.edges: Dict[str, Dict[str, str]] = {}
        self.active_scopes = 0
        self.dropped = 0
        self._jax_patch = None  # (module, original device_get)


_ST = _State()
_tls = threading.local()


def enabled() -> bool:
    return _ST.enabled


def enable() -> None:
    """Turn the witness on process-wide and patch the sanctioned sync
    chokepoint. Idempotent and STICKY: the first sanitized statement
    enables it for the whole process (the lock graph must span
    sessions/threads to witness cross-session orders), and flipping
    the sysvar off stops per-statement scopes but leaves the witness
    recording until an explicit disable() — debug mode is per-process,
    not per-session (README "Sanitizer mode")."""
    with _ST.lock:
        if _ST.enabled and _ST._jax_patch is not None:
            return
        _ST.enabled = True
        if _ST._jax_patch is None:
            try:
                import jax
            except Exception:  # noqa: BLE001 — CLI/lint contexts have
                # no jax; the lock/tracker witnesses still work
                return
            orig = jax.device_get

            def counted_device_get(x):
                count_sync()
                return orig(x)

            jax.device_get = counted_device_get
            _ST._jax_patch = (jax, orig)


def disable(reset_state: bool = True) -> None:
    with _ST.lock:
        _ST.enabled = False
        if _ST._jax_patch is not None:
            mod, orig = _ST._jax_patch
            mod.device_get = orig
            _ST._jax_patch = None
        if reset_state:
            reset()


def reset() -> None:
    """Drop all witnessed state (tests isolate through this)."""
    with _ST.lock:
        _ST.findings = []
        _ST.edges = {}
        _ST.dropped = 0


def _add_finding(f: Finding) -> None:
    f.thread = threading.current_thread().name
    with _ST.lock:
        if len(_ST.findings) >= _FINDINGS_CAP:
            _ST.dropped += 1
            return
        _ST.findings.append(f)


# -- lock witness -----------------------------------------------------------


def _held() -> List[str]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


class TrackedLock:
    """Wrapper around a threading lock that records nested-acquisition
    order while the sanitizer is enabled. Transparent otherwise (one
    attribute check per acquire). Condition() interop works by
    delegation: ``_release_save``/``_acquire_restore``/``_is_owned``
    resolve to the inner lock, so a cv built over a tracked lock parks
    and resumes exactly like an untracked one (the held stack keeps the
    name across the wait — consistent, since the lock is re-acquired
    before the waiter continues)."""

    __slots__ = ("name", "_lk")

    def __init__(self, name: str, inner):
        self.name = name
        self._lk = inner

    def acquire(self, *args, **kwargs):
        got = self._lk.acquire(*args, **kwargs)
        if got and _ST.enabled:
            held = _held()
            me = self.name
            with _ST.lock:
                for h in set(held):
                    if h != me:
                        _ST.edges.setdefault(h, {}).setdefault(
                            me, threading.current_thread().name)
            held.append(me)
        return got

    def release(self):
        # pop UNCONDITIONALLY: a disable() landing while this thread is
        # inside its critical section must not strand the name on the
        # held stack (a stale entry would mint phantom order edges —
        # and phantom cycles — after the next enable). Only acquire's
        # edge recording is gated on the flag.
        held = getattr(_tls, "held", None)
        if held:
            # remove the LAST occurrence (reentrant locks stack)
            for i in range(len(held) - 1, -1, -1):
                if held[i] == self.name:
                    del held[i]
                    break
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lk.locked()

    def __getattr__(self, name):
        return getattr(self._lk, name)


def tracked_lock(name: str, factory=threading.Lock) -> TrackedLock:
    """A registered engine lock: ``self._lock =
    tracked_lock("SegmentStore._lock")``. Names follow the static
    graph's ``Class.attr`` convention so diff_static lines up."""
    return TrackedLock(name, factory())


def lock_edges() -> Dict[str, Dict[str, str]]:
    with _ST.lock:
        return {a: dict(bs) for a, bs in _ST.edges.items()}


def check_lock_cycle() -> Optional[Finding]:
    """DFS the runtime graph; a cycle is a witnessed deadlock order."""
    from tidb_tpu.analysis.lock_discipline import LockDisciplinePass

    cyc = LockDisciplinePass._find_cycle(lock_edges())
    if cyc is None:
        return None
    path, locs = cyc
    f = Finding("lock-cycle", " -> ".join(path),
                "runtime acquisition-order cycle witnessed across "
                f"threads: {'; '.join(locs)}")
    _add_finding(f)
    return f


def diff_static(root: str = _REPO_ROOT) -> dict:
    """Diff the witnessed lock graph against the static model. Returns
    {"novel": [(a, b, thread)], "static_only": [(a, b)]} — novel edges
    came through paths the AST cannot see (callbacks, worker threads,
    finalizers); they are the witness's yield, not violations."""
    from tidb_tpu.analysis.lock_discipline import static_lock_edges

    static = static_lock_edges(root)
    runtime = lock_edges()
    novel = [(a, b, thr) for a, bs in runtime.items()
             for b, thr in bs.items() if b not in static.get(a, {})]
    static_only = [(a, b) for a, bs in static.items()
                   for b in bs if b not in runtime.get(a, {})]
    return {"novel": sorted(novel), "static_only": sorted(static_only)}


# -- tracker / pin / global hooks ------------------------------------------


def note_tracker_release(label: str, consumed: int) -> None:
    """Called by MemTracker.release when a tracker's balance went
    negative — more bytes released than were ever consumed."""
    _add_finding(Finding(
        "tracker-double-release", label,
        f"released below zero (consumed={consumed}) — some charge was "
        "returned twice"))


def note_tracker_detach(label: str, residual: int) -> None:
    """Called by MemTracker.detach for a nonzero residual: bytes the
    statement consumed and never released. detach() reclaims them (by
    design), so this is a leak WITNESS, not a failure."""
    _add_finding(Finding(
        "tracker-residual", label,
        f"{residual} bytes never release()d before detach "
        "(reclaimed by detach; leak witness)", fatal=False))


def note_pin_open(pin) -> None:
    sc = _current_scope()
    if sc is not None:
        sc.pins[id(pin)] = pin


def note_pin_close(pin) -> None:
    sc = _current_scope()
    if sc is not None:
        sc.pins.pop(id(pin), None)


def note_global_write(name: str, value) -> None:
    """A registered process-global was written. During ANY in-flight
    statement that is a race with every other session reading it at
    trace time (the hash_probe.set_mode class) — fatal."""
    with _ST.lock:
        active = _ST.active_scopes
    if active > 0:
        _add_finding(Finding(
            "shared-global-write", name,
            f"process-global written to {value!r} while {active} "
            "statement(s) were in flight — thread the value through "
            "ExecContext/fragment args instead"))


def count_sync() -> None:
    sc = _current_scope()
    if sc is not None:
        sc.syncs += 1


# -- wire witness (ISSUE 14) ------------------------------------------------


# committed static protocol model (analysis/wire_protocol.json):
# loaded lazily once, stdlib-only. {"loaded": bool, "model": dict|None}
_WIRE = {"loaded": False, "model": None}
_WIRE_MODEL_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "wire_protocol.json")


def wire_model() -> Optional[dict]:
    # the lazy load is guarded: a distributed query's parallel
    # dispatch threads hit their first _send simultaneously, and the
    # outage finding below must record ONCE, not once per thread
    with _ST.lock:
        if _WIRE["loaded"]:
            return _WIRE["model"]
        try:
            import json

            with open(_WIRE_MODEL_PATH, encoding="utf-8") as f:
                _WIRE["model"] = json.load(f)
        except (OSError, ValueError) as e:
            # failing OPEN silently would leave the operator believing
            # the wire witness is armed while a whole finding class is
            # off — record the outage ONCE, non-fatal (the other
            # witnesses still run; a lint/CLI context has no model by
            # design and never reaches here: note_wire_msg only fires
            # from a live dcn._send)
            _WIRE["model"] = None
            _add_finding(Finding(
                "wire-model-unavailable", _WIRE_MODEL_PATH,
                f"static protocol model failed to load ({e}) — the "
                "wire witness is OFF for this process; restore the "
                "committed analysis/wire_protocol.json "
                "(scripts/gen_wire_protocol.py regenerates it)",
                fatal=False))
        _WIRE["loaded"] = True
    return _WIRE["model"]


def set_wire_model(model: Optional[dict]) -> None:
    """Test hook: install a model (None re-loads the committed one on
    next use)."""
    _WIRE["model"] = model
    _WIRE["loaded"] = model is not None


def note_wire_msg(msg) -> None:
    """Called by ``parallel/dcn._send`` (behind the enabled() flag) for
    every frame leaving a socket. Requests — dicts carrying a string
    ``cmd`` — are diffed against the static protocol model; responses
    and handshake bytes pass through untouched. ``_``-prefixed keys are
    server-local annotations, never wire fields."""
    if not isinstance(msg, dict):
        return
    cmd = msg.get("cmd")
    if not isinstance(cmd, str):
        return
    model = wire_model()
    if model is None:
        return
    env = model.get("envelope", {})
    ent = model.get("cmds", {}).get(cmd)
    if ent is None:
        _add_finding(Finding(
            "wire-unknown-cmd", cmd,
            "request crossed a worker socket with a cmd absent from "
            "the static protocol model — a send site the extractor "
            "cannot see (or a dynamically-minted cmd); fix the sender "
            "or regenerate scripts/gen_wire_protocol.py"))
        return
    fields = {k for k in msg
              if isinstance(k, str) and k != "cmd"
              and not k.startswith("_")}
    allowed = set(env.get("sent", ())) | set(env.get("read", ()))
    h = ent.get("handler")
    if h is not None:
        allowed |= set(h.get("required", ())) \
            | set(h.get("conditional", ())) | set(h.get("optional", ()))
    for s in ent.get("senders", ()):
        allowed |= set(s.get("required", ())) | set(s.get("optional", ()))
    for f in sorted(fields - allowed):
        _add_finding(Finding(
            "wire-unknown-field", f"{cmd}.{f}",
            "field crossed a worker socket that the static protocol "
            "model does not know for this cmd — dead wire bytes at "
            "best, a handler the model missed at worst; regenerate "
            "the model or fix the sender"))
    if h is not None:
        for f in sorted(set(h.get("required", ())) - fields):
            _add_finding(Finding(
                "wire-missing-field", f"{cmd}.{f}",
                "request omits a field the handler reads "
                "unconditionally — this exact message raises a remote "
                "KeyError on the worker"))


# -- statement scope --------------------------------------------------------


@dataclass
class _StmtScope:
    sync_budget: Optional[int]
    start_idx: int
    pins: Dict[int, object] = field(default_factory=dict)
    syncs: int = 0


def _current_scope() -> Optional[_StmtScope]:
    scopes = getattr(_tls, "scopes", None)
    return scopes[-1] if scopes else None


def statement_begin(sync_budget: Optional[int] = None) -> _StmtScope:
    scopes = getattr(_tls, "scopes", None)
    if scopes is None:
        scopes = _tls.scopes = []
    with _ST.lock:
        sc = _StmtScope(sync_budget, len(_ST.findings))
        _ST.active_scopes += 1
    scopes.append(sc)
    return sc


def statement_end(scope: _StmtScope) -> List[Finding]:
    """Close the scope and return every finding it produced: leaked
    pins, a blown sync budget, witnessed lock cycles, and any global
    findings recorded while it ran."""
    scopes = getattr(_tls, "scopes", None)
    if scopes and scopes[-1] is scope:
        scopes.pop()
    elif scopes and scope in scopes:
        scopes.remove(scope)
    with _ST.lock:
        _ST.active_scopes = max(_ST.active_scopes - 1, 0)
    out: List[Finding] = []
    for pin in scope.pins.values():
        f = Finding(
            "pin-leak", type(pin).__name__,
            "opened during the statement and never closed — its charges "
            "and segment references outlive the statement (surfaces "
            "later as spurious typed OOM / stuck eviction)")
        _add_finding(f)
        out.append(f)
    if scope.sync_budget is not None and scope.syncs > scope.sync_budget:
        f = Finding(
            "host-sync-budget", "statement",
            f"{scope.syncs} device_get round trips > declared budget "
            f"{scope.sync_budget} — a per-chunk sync storm the "
            "pipelined executor exists to remove")
        _add_finding(f)
        out.append(f)
    cyc = check_lock_cycle()
    if cyc is not None and cyc not in out:
        out.append(cyc)
    # collect global findings recorded while this scope ran, but only
    # those witnessed ON THIS THREAD: statement scopes are per-thread,
    # and blaming statement B for a pin statement A leaked (they merely
    # overlapped) would cascade one bug into typed failures on every
    # innocent concurrent statement. Off-thread findings (prefetch
    # workers, other sessions) stay visible in report().
    me = threading.current_thread().name
    with _ST.lock:
        for f in _ST.findings[scope.start_idx:]:
            if f.thread == me and f not in out:
                out.append(f)
    return out


def report() -> dict:
    """Snapshot for tests/tools: findings + the witnessed lock graph."""
    with _ST.lock:
        findings = list(_ST.findings)
        dropped = _ST.dropped
    return {
        "enabled": _ST.enabled,
        "findings": [
            {"kind": f.kind, "subject": f.subject, "detail": f.detail,
             "fatal": f.fatal, "thread": f.thread} for f in findings],
        "dropped": dropped,
        "lock_edges": lock_edges(),
    }
