"""Engine invariant analyzer (ISSUE 6): AST lint passes over tidb_tpu/.

The paper's premise — TPU-native relational execution — rests on a
handful of code invariants nothing used to enforce:

  * device programs must be module-level and argument-driven (PR 3
    found every join re-tracing because of per-instance jit closures)
  * hot paths must not silently sync the host (ROADMAP items 1 and 3)
  * the multi-threaded DCN/coordinator layer must keep a cycle-free
    lock-acquisition order and never mutate shared state unlocked
  * every acquired resource (pins, tracker charges, cursors, staging
    generators, failpoint arms) must reach its release on every path
    (ISSUE 12: resource-lifecycle)
  * no registered lock may be held across a blocking call — waits,
    device fetches, socket/file I/O, tracker consume (ISSUE 12:
    blocking-under-lock, generalizing PR 7's wait discipline)
  * every registry (metrics, failpoints, sysvars) must stay covered
  * errors must stay typed, coded, and never silently swallowed
  * the DCN dict wire protocol's senders and handler arms must agree
    on cmds and fields, worker re-sends must propagate the statement
    envelope, and the committed protocol model must match a fresh
    extraction (ISSUE 14: protocol-conformance; the runtime wire
    witness in sanitizer.py diffs real traffic against the model)
  * every value a cached device program closes over must be named in
    its cache key (ISSUE 14: cache-key-completeness, generalizing the
    PR 10 hash_probe.set_mode fix)

``scripts/check_invariants.py`` drives the passes (tier-1 via
tests/test_static_analysis.py; ``--json`` for the machine-readable
report, ``--changed <paths>`` for sub-second diff lints).
Suppressions require an inline reason:

    # lint: disable=<pass>[,<pass>] -- <reason>            (line scope)
    # lint: module-disable=<pass> -- <reason>              (file scope)
    # host-sync: <reason>           (host-sync pass only; the annotated
                                     allowlist of intentional syncs)
    # lifecycle: <reason>           (resource-lifecycle pass only; a
                                     documented ownership handoff)

A suppression with no reason is itself a violation, and every
suppression is counted and reported so the allowlist stays visible
(the count is tier-1-asserted, so drift shows up in review).

The runtime half (ISSUE 12) lives in ``analysis/sanitizer.py``: a
debug-mode witness behind ``tidb_tpu_sanitize`` that records lock
orders, tracker/pin balances, and per-statement host-sync counts, and
cross-checks them against the static model (see README "Sanitizer
mode").
"""

from tidb_tpu.analysis.core import (  # noqa: F401
    Driver,
    Project,
    Violation,
    all_passes,
)
