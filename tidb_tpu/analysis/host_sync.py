"""host-sync pass: no silent device→host syncs on the execution tiers.

Every ``int()`` / ``float()`` / ``bool()`` / ``.item()`` /
``np.asarray`` / ``np.array`` applied to a device value blocks the
host on the accelerator.  The executor operators run once per chunk and
the fragment runners once per dispatch, so ANY such sync in
``executor/``/``ops/``/``parallel/`` is a per-chunk round trip
(ROADMAP items 1 and 3: the join's ``probe_count`` sync, the drain
loops).  An *intentional* sync must be visible and justified: annotate
the line with ``# host-sync: <reason>`` and it is allowlisted, counted,
and surfaced in the README table.

Device-ness is a forward dataflow within each function (no fixpoint):

  seeds       calls on ``jnp.*`` / ``jax.*`` (except ``jax.device_get``,
              whose RESULT is host), calls of names imported from
              ``tidb_tpu.ops.*`` / ``tidb_tpu.expression.compiler``
              (the device-kernel modules), and calls of locals bound
              from jit builders (``jax.jit`` / ``counted_jit`` /
              ``cached_jit`` / ``*.get_fragment`` / ``*.build_fn``)
  propagates  through attributes, subscripts, arithmetic, tuples, and
              (tuple-)assignment
  launders    through the sync calls themselves (their result is host)

Host-tier numpy code (spill loaders, drained chunks) stays untainted by
design — the pass guards the *device-result* sync class, not every
np.asarray.  ``jax.device_get`` is the sanctioned explicit fetch: it
moves a whole pytree in ONE transfer and its result is host.

Chunk-loop sync budget (ISSUE 9): even the sanctioned fetch is a
device→host round trip, and one PER CHUNK-LOOP ITERATION re-creates
exactly the ping-pong the pipelined executor exists to remove.  A
``jax.device_get`` lexically inside a ``for``/``while`` loop therefore
requires its own ``# host-sync: <reason>`` annotation — the loop sync
must be *batched* (one fetch per window, like the join probe's deferred
totals), hoisted to finalize, or visibly justified.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from tidb_tpu.analysis.core import Pass, Project, SourceFile, Violation

__all__ = ["HostSyncPass", "annotated_sites"]

_SYNC_BUILTINS = {"int", "float", "bool"}
_DEVICE_MODULE_PREFIXES = ("tidb_tpu.ops", "tidb_tpu.expression.compiler")
_JIT_BUILDER_ATTRS = {"get_fragment", "build_fn"}
_JIT_BUILDER_NAMES = {"cached_jit", "counted_jit"}


def _module_device_names(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """-> (device_fn_names, device_module_aliases) for one module."""
    fns: Set[str] = set()
    mods: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            from_device = node.module.startswith(_DEVICE_MODULE_PREFIXES)
            for alias in node.names:
                full = f"{node.module}.{alias.name}"
                if from_device:
                    # `from tidb_tpu.ops import join_kernels as jk`
                    # imports a MODULE; a plain name import is a kernel fn
                    if full.startswith(_DEVICE_MODULE_PREFIXES) and \
                            "." not in alias.name and \
                            node.module in ("tidb_tpu.ops",):
                        mods.add(alias.asname or alias.name)
                    else:
                        fns.add(alias.asname or alias.name)
                elif full.startswith(_DEVICE_MODULE_PREFIXES):
                    mods.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith(_DEVICE_MODULE_PREFIXES):
                    mods.add(alias.asname or alias.name.split(".")[0])
                if alias.name in ("jax.numpy",):
                    mods.add(alias.asname or "jax")
    return fns, mods


class _FnScan:
    """Forward taint over one function body."""

    def __init__(self, sf: SourceFile, fn: ast.AST,
                 device_fns: Set[str], device_mods: Set[str]):
        self.sf = sf
        self.fn = fn
        self.device_fns = device_fns
        self.device_mods = device_mods
        self.tainted: Set[str] = set()
        self.local_device_fns: Set[str] = set()
        self.hits: List[Tuple[int, str, str]] = []  # (line, kind, detail)
        self._loop_depth = 0  # chunk-loop sync budget (device_get-in-loop)

    # -- expression taint ------------------------------------------------

    def _root_name(self, node: ast.AST) -> str:
        while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
            node = node.func if isinstance(node, ast.Call) else node.value
        return node.id if isinstance(node, ast.Name) else ""

    def _is_device_call(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute):
            root = self._root_name(f)
            if root == "jnp" or root in self.device_mods:
                return True
            if root == "jax":
                # only the array APIs produce device values; jax.devices()
                # / jax.config / jax.device_get results live on host
                txt = ast.unparse(f)
                return txt.startswith("jax.lax.") or txt == "jax.device_put"
            if root == "lax":
                return True
        if isinstance(f, ast.Name):
            if f.id in self.device_fns or f.id in self.local_device_fns:
                return True
        return False

    def _is_jit_builder_call(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr in _JIT_BUILDER_ATTRS:
                return True
            if f.attr == "jit" and self._root_name(f) == "jax":
                return True
        if isinstance(f, ast.Name) and f.id in _JIT_BUILDER_NAMES:
            return True
        return False

    def _is_device_get(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == "device_get":
            return True
        return isinstance(f, ast.Name) and f.id == "device_get"

    def _sync_kind(self, call: ast.Call) -> str:
        """'' or the sync-op name when `call` is a sync operation."""
        f = call.func
        if isinstance(f, ast.Name) and f.id in _SYNC_BUILTINS and call.args:
            return f.id
        if isinstance(f, ast.Attribute):
            if f.attr == "item" and not call.args:
                return ".item()"
            if f.attr in ("asarray", "array") and \
                    isinstance(f.value, ast.Name) and f.value.id == "np":
                return f"np.{f.attr}"
        return ""

    def taint(self, e: ast.AST) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, (ast.Attribute, ast.Subscript, ast.Starred)):
            return self.taint(e.value)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self.taint(x) for x in e.elts)
        if isinstance(e, ast.BinOp):
            return self.taint(e.left) or self.taint(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.taint(e.operand)
        if isinstance(e, ast.BoolOp):
            return any(self.taint(v) for v in e.values)
        if isinstance(e, ast.Compare):
            return self.taint(e.left) or any(
                self.taint(c) for c in e.comparators)
        if isinstance(e, ast.IfExp):
            return self.taint(e.body) or self.taint(e.orelse)
        if isinstance(e, ast.Call):
            if self._sync_kind(e):
                return False  # the sync's own result lives on host
            if self._is_device_call(e):
                return True
            # method on a tainted value (x.sum(), x.astype(...)) stays
            # on device
            if isinstance(e.func, ast.Attribute) and self.taint(e.func.value):
                return True
            return False
        return False

    # -- statement walk ---------------------------------------------------

    def run(self) -> None:
        body = self.fn.body if isinstance(self.fn.body, list) else []
        self._walk(body)

    def _bind(self, target: ast.AST, tainted: bool, device_fn: bool) -> None:
        if isinstance(target, ast.Name):
            self.tainted.discard(target.id)
            self.local_device_fns.discard(target.id)
            if tainted:
                self.tainted.add(target.id)
            if device_fn:
                self.local_device_fns.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind(el, tainted, device_fn)

    def _walk(self, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # separate scope, scanned on its own
            # a device-result sync is flagged wherever it sits, not just
            # in source-level loops: operators run once per chunk, so
            # "outside the loop" in source is still inside one at runtime
            self._scan_exprs(stmt)
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = stmt.value
                t = self.taint(value) if value is not None else False
                dfn = (isinstance(value, ast.Call)
                       and self._is_jit_builder_call(value))
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for tgt in targets:
                    self._bind(tgt, t, dfn)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._bind(stmt.target, self.taint(stmt.iter), False)
                self._loop_depth += 1
                self._walk(stmt.body)
                self._loop_depth -= 1
                self._walk(stmt.orelse)
            elif isinstance(stmt, ast.While):
                self._loop_depth += 1
                self._walk(stmt.body)
                self._loop_depth -= 1
                self._walk(stmt.orelse)
            elif isinstance(stmt, ast.If):
                self._walk(stmt.body)
                self._walk(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if item.optional_vars is not None:
                        self._bind(item.optional_vars,
                                   self.taint(item.context_expr), False)
                self._walk(stmt.body)
            elif isinstance(stmt, ast.Try):
                self._walk(stmt.body)
                for h in stmt.handlers:
                    self._walk(h.body)
                self._walk(stmt.orelse)
                self._walk(stmt.finalbody)

    def _scan_exprs(self, stmt: ast.stmt) -> None:
        """Flag sync calls on tainted values anywhere in `stmt`'s own
        expressions (not descending into nested compound statements —
        the walk visits those itself, with taint state up to date)."""
        compound = (ast.For, ast.AsyncFor, ast.While, ast.If, ast.With,
                    ast.AsyncWith, ast.Try, ast.FunctionDef,
                    ast.AsyncFunctionDef)
        if isinstance(stmt, compound):
            # only the header expressions belong to this statement
            headers: List[ast.AST] = []
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                headers = [stmt.iter]
            elif isinstance(stmt, ast.While):
                headers = [stmt.test]
            elif isinstance(stmt, ast.If):
                headers = [stmt.test]
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                headers = [i.context_expr for i in stmt.items]
            nodes = [n for h in headers for n in ast.walk(h)]
        else:
            nodes = list(ast.walk(stmt))
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            if self._loop_depth > 0 and self._is_device_get(node):
                # chunk-loop sync budget: the sanctioned batch fetch is
                # still one round trip per iteration inside a loop
                self.hits.append((node.lineno, "device_get-in-loop",
                                  ast.unparse(node)[:80]))
                continue
            kind = self._sync_kind(node)
            if not kind:
                continue
            if kind in _SYNC_BUILTINS or kind.startswith("np."):
                arg = node.args[0] if node.args else None
                if arg is None or not self.taint(arg):
                    continue
                detail = ast.unparse(node)
            else:  # .item(): receiver must be tainted
                if not self.taint(node.func.value):
                    continue
                detail = ast.unparse(node)
            self.hits.append((node.lineno, kind, detail[:80]))


def _scan_file(sf: SourceFile) -> List[Tuple[int, str, str]]:
    device_fns, device_mods = _module_device_names(sf.tree)
    hits: List[Tuple[int, str, str]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan = _FnScan(sf, node, device_fns, device_mods)
            scan.run()
            hits.extend(scan.hits)
    return hits


class HostSyncPass(Pass):
    id = "host-sync"
    doc = ("no implicit device→host syncs (int/float/bool/.item()/"
           "np.asarray on device values) in executor/ops/parallel/"
           "serving/columnar; intentional ones carry "
           "`# host-sync: <reason>`")

    SCOPE = ("executor", "ops", "parallel", "serving", "columnar",
             "sharding")

    def run(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for sf in project.files_under(*self.SCOPE):
            used_notes = set()
            for line, kind, detail in _scan_file(sf):
                note = sf.host_sync_note(line)
                if note is not None:
                    used_notes.add(note[0])
                    continue  # annotated allowlist (reported separately)
                if kind == "device_get-in-loop":
                    out.append(Violation(
                        self.id, sf.rel, line,
                        f"per-iteration device fetch `{detail}` inside a "
                        "chunk loop (one round trip per iteration — the "
                        "ping-pong the pipelined executor removes). "
                        "Batch it into one fetch per window, hoist it to "
                        "finalize, or annotate with `# host-sync: "
                        "<reason>`."))
                    continue
                out.append(Violation(
                    self.id, sf.rel, line,
                    f"implicit device→host sync `{detail}` on the hot "
                    f"tier ({kind} forces the device to flush). Batch it "
                    "into one jax.device_get, hoist it off the per-chunk "
                    "path, or annotate the line with `# host-sync: "
                    "<reason>` if the sync is intentional."))
            # an annotation covering no sync is stale: left behind, it
            # would silently pre-allowlist a FUTURE sync on that line —
            # the exact invisible-sync class this pass exists to catch
            for line in sorted(set(sf.host_sync_notes) - used_notes):
                out.append(Violation(
                    self.id, sf.rel, line,
                    "stale host-sync annotation: no device→host sync "
                    "on the governed line — delete it (or re-anchor "
                    "it; a refactor may have moved the sync)"))
        return out


def annotated_sites(project: Project) -> List[Tuple[str, int, str]]:
    """Every `# host-sync:` annotation in scope — the documented
    allowlist of intentional syncs (rendered by check_invariants and
    mirrored in the README table)."""
    out = []
    for sf in project.files_under(*HostSyncPass.SCOPE):
        for line, reason in sorted(sf.host_sync_notes.items()):
            out.append((sf.rel, line, reason))
    return out
