"""cache-key-completeness pass: every value a cached device program is
built from must be named in its cache key (ISSUE 14).

The PR 10 bug class this generalizes: ``hash_probe.set_mode`` wrote a
process global that jitted fragment builders read at TRACE time — a
value that shaped the compiled program but was missing from the
fragment-cache key, so a cache hit could serve a program traced for the
OTHER strategy (and concurrent sessions raced the global). The fix
threaded the mode through ``build_fn`` and into the key; this pass
makes that discipline machine-checked for every signature-keyed cache
site, so the class cannot come back through the next knob.

Registered cache sites:

  * ``cached_jit(ns, key, build, ...)`` (utils/jitcache.py) — the
    executor tier's signature-keyed jit cache;
  * ``<cache>.get_fragment(key, build)`` (parallel/executor.py
    ShardCache) — the collective-fragment cache.

Rule: every *free* name the traced body reads (the ``build`` callable's
closure surface — a lambda's body expression and its default-bound
params, or the local ``def`` a lambda returns) must be *covered* by the
key expression:

  * the name (or, for ``self.attr`` reads, the exact dotted path)
    appears in the key expression — including through local assignment
    chains (``sig = repr((a, b))`` covers ``a``/``b`` when ``sig`` is
    the key; ``key_fns = [compile(e) for e in items]`` is covered when
    ``items`` is); or
  * it is module-level / imported / builtin (static code identity —
    jax already keys on it).

Anything else is a violation: a Python value baked into the traced
program that a key collision can serve STALE. Sysvar reads inside a
traced body (``.sysvars.get(...)`` / ``session_info(...)``) are always
violations — a sysvar is a live knob and must be read outside the
trace and threaded through the key as an argument.

Cross-module mutable globals read by a module-level builder function
remain invisible to this (deliberately shallow) model — that residue is
exactly what the runtime sanitizer's shared-global-write witness and
the wire witness exist to catch.
"""

from __future__ import annotations

import ast
import builtins
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tidb_tpu.analysis.core import Pass, Project, SourceFile, Violation
from tidb_tpu.analysis.jit_hygiene import _bound_names

__all__ = ["CacheKeyCompletenessPass"]

_BUILTINS = set(dir(builtins))


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a pure attribute chain rooted at a Name, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class _Tokens:
    """Name/dotted-path reads of one expression (or body)."""
    names: Set[str] = field(default_factory=set)
    dotted: Set[str] = field(default_factory=set)   # self.x / a.b paths

    def update_from(self, node: ast.AST) -> None:
        # comprehension targets are bound inside the expression — a
        # `[f(e) for e in items]` RHS reads `items`, not `e`
        comp_bound: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.comprehension):
                for t in ast.walk(sub.target):
                    if isinstance(t, ast.Name):
                        comp_bound.add(t.id)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and \
                    isinstance(sub.ctx, ast.Load):
                path = _dotted(sub)
                if path is not None \
                        and path.split(".", 1)[0] not in comp_bound:
                    self.dotted.add(path)
            elif isinstance(sub, ast.Name) and \
                    isinstance(sub.ctx, ast.Load) \
                    and sub.id not in comp_bound:
                self.names.add(sub.id)


def _expr_tokens(node: ast.AST) -> _Tokens:
    t = _Tokens()
    t.update_from(node)
    return t


class _Scope:
    """The enclosing function's dataflow surface: module-level names,
    local assignments (name -> list of RHS token sets), local defs."""

    def __init__(self, sf: SourceFile, fn: ast.AST):
        self.sf = sf
        self.fn = fn
        self.module_names = self._module_names(sf.tree)
        self.assigns: Dict[str, List[_Tokens]] = {}
        self.local_defs: Dict[str, ast.FunctionDef] = {}
        self.imported: Set[str] = set()
        self._collect(fn)

    @staticmethod
    def _module_names(tree: ast.Module) -> Set[str]:
        out: Set[str] = set()
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                out.add(node.name)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    out.add((alias.asname or alias.name).split(".")[0])
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                out.add(node.target.id)
        return out

    def _collect(self, fn: ast.AST) -> None:
        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    self.local_defs[child.name] = child
                    continue  # its body is its own scope
                if isinstance(child, ast.ClassDef):
                    continue
                if isinstance(child, (ast.Import, ast.ImportFrom)):
                    for alias in child.names:
                        self.imported.add(
                            (alias.asname or alias.name).split(".")[0])
                elif isinstance(child, ast.Assign):
                    rhs = _expr_tokens(child.value)
                    for tgt in child.targets:
                        self._bind_target(tgt, rhs)
                elif isinstance(child, (ast.For, ast.AsyncFor)):
                    self._bind_target(child.target,
                                      _expr_tokens(child.iter))
                elif isinstance(child, ast.withitem) and \
                        child.optional_vars is not None:
                    self._bind_target(child.optional_vars,
                                      _expr_tokens(child.context_expr))
                walk(child)

        walk(fn)

    def _bind_target(self, tgt: ast.AST, rhs: _Tokens) -> None:
        if isinstance(tgt, ast.Name):
            self.assigns.setdefault(tgt.id, []).append(rhs)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._bind_target(el, rhs)


class CacheKeyCompletenessPass(Pass):
    id = "cache-key-completeness"
    doc = ("free variables and sysvars read inside cached_jit/"
           "get_fragment traced bodies must appear in the cache key "
           "(the hash_probe.set_mode race class, machine-checked)")

    def run(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for sf in project.files():
            if "cached_jit" not in sf.text \
                    and "get_fragment" not in sf.text:
                continue
            out.extend(self._check_module(sf))
        return out

    # ------------------------------------------------------------------

    def _check_module(self, sf: SourceFile) -> List[Violation]:
        out: List[Violation] = []

        def visit(node: ast.AST, fn_stack: List[ast.AST]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_stack = fn_stack + [node]
            for child in ast.iter_child_nodes(node):
                visit(child, fn_stack)
            if isinstance(node, ast.Call):
                site = self._site(node)
                if site is not None:
                    key_expr, build_expr = site
                    # module-level sites use the module as the scope:
                    # free names there are static identity, but a
                    # sysvar read in the traced body is still a live
                    # knob frozen at trace time
                    scope_fn = fn_stack[-1] if fn_stack else sf.tree
                    out.extend(self._check_site(
                        sf, node, key_expr, build_expr, scope_fn))

        visit(sf.tree, [])
        return out

    @staticmethod
    def _site(call: ast.Call) -> Optional[Tuple[ast.AST, ast.AST]]:
        f = call.func
        if isinstance(f, ast.Name) and f.id == "cached_jit" \
                and len(call.args) >= 3:
            return call.args[1], call.args[2]
        if isinstance(f, ast.Attribute) and f.attr == "cached_jit" \
                and len(call.args) >= 3:
            return call.args[1], call.args[2]
        if isinstance(f, ast.Attribute) and f.attr == "get_fragment" \
                and len(call.args) >= 2:
            return call.args[0], call.args[1]
        return None

    # ------------------------------------------------------------------

    def _check_site(self, sf: SourceFile, call: ast.Call,
                    key_expr: ast.AST, build_expr: ast.AST,
                    fn: ast.AST) -> List[Violation]:
        scope = _Scope(sf, fn)
        key = self._expand_key(_expr_tokens(key_expr), scope)
        free = _Tokens()
        sysvar_reads: List[int] = []
        self._traced_reads(build_expr, scope, free, sysvar_reads,
                           depth=0)
        out: List[Violation] = []
        for line in sysvar_reads:
            out.append(Violation(
                self.id, sf.rel, line,
                "sysvar read inside a traced cache body: the value is "
                "frozen at trace time and a key collision serves it "
                "stale to every later statement — read it outside the "
                "program and thread it through the cache key as an "
                "argument (the hash_probe.set_mode fix shape)"))
        missing = sorted(
            n for n in free.names
            if n not in ("self", "cls")
            and not self._covered_name(n, key, scope, set()))
        missing += sorted(
            d for d in free.dotted
            if d.split(".", 1)[0] in ("self", "cls")
            and not self._covered_dotted(d, key, scope, set()))
        if missing:
            out.append(Violation(
                self.id, sf.rel, call.lineno,
                "cache key does not cover value(s) the traced body "
                f"closes over: {', '.join(missing)}. A key collision "
                "serves a program traced for OTHER values of these "
                "(the hash_probe.set_mode race class) — add them to "
                "the key expression, or suppress with the caller-side "
                "key discipline as the reason."))
        return out

    @staticmethod
    def _expand_key(key: _Tokens, scope: _Scope) -> _Tokens:
        """Close the key's token set over local assignment chains:
        `sig = repr((a, b)); cached_jit(ns, sig, ...)` names a and b in
        the key just as surely as writing the repr inline."""
        frontier = set(key.names)
        seen: Set[str] = set()
        while frontier:
            name = frontier.pop()
            if name in seen:
                continue
            seen.add(name)
            for rhs in scope.assigns.get(name, []):
                key.names |= rhs.names
                key.dotted |= rhs.dotted
                frontier |= rhs.names - seen
        return key

    # -- traced-body surface -----------------------------------------------

    def _traced_reads(self, build: ast.AST, scope: _Scope, free: _Tokens,
                      sysvars: List[int], depth: int) -> None:
        """Free reads of the build callable. A lambda contributes its
        body (minus its own params) plus its default expressions (they
        evaluate at definition time — closure-by-value); a Name
        resolving to a local def contributes that def's free reads."""
        if depth > 4:
            return
        if isinstance(build, ast.Lambda):
            body_free = self._def_free(build, scope)
            free.names |= body_free.names
            free.dotted |= body_free.dotted
            for d in build.args.defaults + [
                    x for x in build.args.kw_defaults if x is not None]:
                free.update_from(d)
            self._find_sysvars(build.body, sysvars)
            # `lambda: local_fn` / `lambda: make_x(a, b)`: a local def
            # the body names is part of the traced program — pull in
            # ITS free reads and discharge the def's own name (code
            # identity, not a value)
            for sub in ast.walk(build.body):
                if isinstance(sub, ast.Name) and \
                        sub.id in scope.local_defs:
                    free.names.discard(sub.id)
                    self._traced_reads(ast.Name(id=sub.id,
                                                ctx=ast.Load()),
                                       scope, free, sysvars, depth + 1)
            return
        if isinstance(build, ast.Name):
            fn = scope.local_defs.get(build.id)
            if fn is not None:
                body_free = self._def_free(fn, scope)
                free.names |= body_free.names
                free.dotted |= body_free.dotted
                self._find_sysvars(fn, sysvars)
            else:
                free.names.add(build.id)
            return
        # anything else (a call expression, an attribute): its reads
        # are the traced surface
        free.update_from(build)
        self._find_sysvars(build, sysvars)

    @staticmethod
    def _def_free(fn: ast.AST, scope: _Scope) -> _Tokens:
        # bound names of the def PLUS those of every nested function in
        # it: a nested lambda's params/locals are not free reads (but a
        # nested scope READING an outer name still surfaces it — token
        # collection walks everything)
        bound = set(_bound_names(fn))
        for sub in ast.walk(fn if isinstance(fn.body, list)
                            else fn.body):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                bound |= _bound_names(sub)
        t = _Tokens()
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            t.update_from(stmt)
        t.names = {n for n in t.names if n not in bound}
        t.dotted = {d for d in t.dotted
                    if d.split(".", 1)[0] not in bound}
        return t

    @staticmethod
    def _find_sysvars(node: ast.AST, out: List[int]) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if isinstance(f, ast.Attribute) and f.attr == "get" \
                    and isinstance(f.value, ast.Attribute) \
                    and f.value.attr == "sysvars":
                out.append(sub.lineno)
            elif (isinstance(f, ast.Name) and f.id == "session_info") \
                    or (isinstance(f, ast.Attribute)
                        and f.attr == "session_info"):
                out.append(sub.lineno)

    # -- coverage ------------------------------------------------------------

    def _covered_name(self, name: str, key: _Tokens, scope: _Scope,
                      seen: Set[str]) -> bool:
        if name in key.names:
            return True
        if name in scope.imported or name in scope.module_names \
                or name in _BUILTINS:
            return True
        if name in seen:
            return False
        seen = seen | {name}
        for rhs in scope.assigns.get(name, []):
            ok = all(self._covered_name(n, key, scope, seen)
                     for n in rhs.names if n not in ("self", "cls"))
            ok = ok and all(self._covered_dotted(d, key, scope, seen)
                            for d in rhs.dotted
                            if d.split(".", 1)[0] in ("self", "cls"))
            if ok and (rhs.names or rhs.dotted):
                return True
        return False

    def _covered_dotted(self, path: str, key: _Tokens, scope: _Scope,
                        seen: Set[str]) -> bool:
        """self/cls attribute reads need the EXACT dotted path in the
        key (a key mentioning self.a must not cover self.b); other
        bases fall back to base-name coverage."""
        if path in key.dotted:
            return True
        base = path.split(".", 1)[0]
        if base not in ("self", "cls"):
            return self._covered_name(base, key, scope, seen)
        return False
