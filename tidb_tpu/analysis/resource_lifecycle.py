"""resource-lifecycle pass: acquire/release pairing on the execution
tiers (ISSUE 12).

The bug class this pass exists for cost PRs 7-10 repeated review
rounds: a resource acquired on the happy path whose release only runs
on the happy path — ``evict_segment`` left its transient pin armed
forever on ENOSPC, staging generators leaked their fill-tracker charge
on abandon, and every such leak surfaces later as a spurious typed OOM
or a stuck eviction, far from the statement that caused it.

The model is deliberately shallow (function-scope, name-based — the
same trade as lock-discipline: depth for zero false positives on the
patterns the repo actually uses). An *acquire* is either a registered
call (``ScanPin(...)``, ``.pin_segment(...)``, ``.consume(...)``,
``.register_spillable(...)``, ``failpoint.enable(...)``,
``._staged_chunks(...)``) or a registered refcount bump
(``X.pins += 1`` / ``X.refs += 1``). Each acquire must be *protected*
by one of:

  * a ``with`` statement (context-managed lifetime);
  * a matching release reachable on the exception path — i.e. at least
    one release for the same resource sits in a ``finally`` block or an
    ``except`` handler of the SAME function (the undo-and-reraise
    pattern counts: that is exactly the ENOSPC fix shape);
  * no in-function release at all, but the enclosing class defines one
    (class-managed lifetime: the object's ``close()``/``release()``
    owns the balance — the runtime sanitizer checks that balance at
    statement end);
  * a ``return`` of the freshly-acquired value (ownership moves to the
    caller);
  * a ``# lifecycle: <reason>`` annotation (documented handoff,
    mirroring the host-sync grammar; stale annotations are flagged).

The dangerous shape this leaves as a violation: a function that BOTH
acquires and releases, with every release on the success path only —
one exception between them and the resource is gone.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Set, Tuple

from tidb_tpu.analysis.core import Pass, Project, SourceFile, Violation

__all__ = ["ResourceLifecyclePass", "ACQUIRE_SPECS", "COUNTER_ATTRS"]


@dataclass(frozen=True)
class AcquireSpec:
    kind: str                  # human label ("scan pin", "tracker charge")
    name: str                  # called attribute/constructor name
    ctor: bool                 # True: Name call (class ctor); False: attr
    releases: Tuple[str, ...]  # attr/function names that release it


ACQUIRE_SPECS: Tuple[AcquireSpec, ...] = (
    AcquireSpec("scan pin", "ScanPin", True, ("close",)),
    AcquireSpec("segment pin", "pin_segment", False, ("unpin_segment",)),
    AcquireSpec("tracker charge", "consume", False,
                ("release", "detach")),
    AcquireSpec("spillable registration", "register_spillable", False,
                ("unregister_spillable",)),
    # failpoint arming outside the context-manager helper must disarm
    # on every path or the next test inherits the fault schedule
    AcquireSpec("failpoint arm", "enable", False, ("disable",)),
    # the staged-chunk generator holds a fill-tracker charge released by
    # its finally: abandoning it un-closed leaks the charge (PR 10's
    # _release_staging fix made this class explicit)
    AcquireSpec("staging generator", "_staged_chunks", False, ("close",)),
    # DCN paged-partial cursors: a drained-or-abandoned cursor must be
    # closed or the worker's cursor cap starves later statements
    AcquireSpec("dcn cursor", "_open_cursor", False, ("_close_cursor",)),
)

# refcount attributes whose += 1 is an acquire and whose any-subtracting
# assignment is a release (the columnar pin/ref protocol)
COUNTER_ATTRS = ("pins", "refs")


def _attr_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _release_kinds_in(node: ast.AST) -> Set[str]:
    """Release names + counter-decrement attrs found anywhere in node."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute):
                out.add(f.attr)
            elif isinstance(f, ast.Name):
                out.add(f.id)
        elif isinstance(sub, (ast.Assign, ast.AugAssign)):
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            for tgt in targets:
                if isinstance(tgt, ast.Attribute) and \
                        tgt.attr in COUNTER_ATTRS:
                    dec = (isinstance(sub, ast.AugAssign)
                           and isinstance(sub.op, ast.Sub))
                    if not dec and sub.value is not None:
                        dec = any(isinstance(b, ast.BinOp)
                                  and isinstance(b.op, ast.Sub)
                                  for b in ast.walk(sub.value))
                    if dec:
                        out.add(f"-{tgt.attr}")
    return out


@dataclass
class _Acquire:
    spec_kind: str
    label: str                 # rendered name of the acquired thing
    line: int
    releases: Tuple[str, ...]  # names that would release it
    protected: bool            # with-context / return handoff


class _FnScan:
    def __init__(self, fn: ast.AST, cls: Optional[ast.ClassDef]):
        self.fn = fn
        self.cls = cls
        self.acquires: List[_Acquire] = []
        # release kinds present anywhere in the function vs only on
        # protected (finally/handler) paths
        self.releases_all: Set[str] = set()
        self.releases_protected: Set[str] = set()

    def run(self) -> None:
        self._walk(self.fn.body, protected=False)

    # -- helpers -----------------------------------------------------------

    def _match_call(self, call: ast.Call) -> Optional[Tuple[AcquireSpec, str]]:
        f = call.func
        for spec in ACQUIRE_SPECS:
            if spec.ctor:
                if isinstance(f, ast.Name) and f.id == spec.name:
                    return spec, spec.name
            else:
                if isinstance(f, ast.Attribute) and f.attr == spec.name:
                    recv = ast.unparse(f.value)
                    if spec.name == "enable" and "failpoint" not in recv:
                        continue  # generic .enable() on non-failpoints
                    return spec, f"{recv}.{spec.name}"
        return None

    def _scan_stmt(self, stmt: ast.stmt, with_ctx: bool, protected: bool
                   ) -> None:
        """Record acquires in one simple statement (headers of compound
        statements come through here too, via their expression parts)."""
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                m = self._match_call(node)
                if m is not None:
                    spec, label = m
                    self.acquires.append(_Acquire(
                        spec.kind, label, node.lineno, spec.releases,
                        protected=with_ctx or protected
                        or isinstance(stmt, ast.Return)))
        # counter bumps: X.pins += 1 (never inside with-headers etc.)
        if isinstance(stmt, ast.AugAssign) and \
                isinstance(stmt.op, ast.Add) and \
                isinstance(stmt.target, ast.Attribute) and \
                stmt.target.attr in COUNTER_ATTRS:
            self.acquires.append(_Acquire(
                "refcount bump", ast.unparse(stmt.target), stmt.lineno,
                (f"-{stmt.target.attr}",), protected=protected))
        for k in _release_kinds_in(stmt):
            self.releases_all.add(k)
            if protected:
                self.releases_protected.add(k)

    def _walk(self, stmts, protected: bool) -> None:
        # with-management applies only to an acquire AS the context
        # expression (scanned with with_ctx=True below) — acquires in a
        # with BODY are deliberately NOT protected by the with
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes scanned on their own
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                # an acquire AS the context expression is with-managed
                for item in stmt.items:
                    hdr = ast.Expr(value=item.context_expr)
                    ast.copy_location(hdr, item.context_expr)
                    self._scan_stmt(hdr, True, protected)
                self._walk(stmt.body, protected)
            elif isinstance(stmt, ast.Try):
                self._walk(stmt.body, protected)
                for h in stmt.handlers:
                    self._walk(h.body, True)
                self._walk(stmt.orelse, protected)
                self._walk(stmt.finalbody, True)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                hdr = ast.Expr(value=stmt.iter)
                ast.copy_location(hdr, stmt.iter)
                self._scan_stmt(hdr, False, protected)
                self._walk(stmt.body, protected)
                self._walk(stmt.orelse, protected)
            elif isinstance(stmt, ast.While):
                self._walk(stmt.body, protected)
                self._walk(stmt.orelse, protected)
            elif isinstance(stmt, ast.If):
                hdr = ast.Expr(value=stmt.test)
                ast.copy_location(hdr, stmt.test)
                self._scan_stmt(hdr, False, protected)
                self._walk(stmt.body, protected)
                self._walk(stmt.orelse, protected)
            else:
                self._scan_stmt(stmt, False, protected)


def _class_release_kinds(cls: Optional[ast.ClassDef],
                         skip_fn: ast.AST) -> Set[str]:
    """Release names defined by OTHER methods of the enclosing class —
    the class-managed-lifetime escape (close()/release() own the
    balance; the runtime sanitizer audits it)."""
    out: Set[str] = set()
    if cls is None:
        return out
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not skip_fn:
            out |= _release_kinds_in(node)
    return out


class ResourceLifecyclePass(Pass):
    id = "resource-lifecycle"
    doc = ("every acquire (pins, tracker charges, cursors, staging "
           "generators, failpoint arms) reaches its release on every "
           "path: finally/with/class-managed, or a `# lifecycle:` "
           "annotated handoff")

    SCOPE = ("executor", "columnar", "parallel", "serving", "sharding")
    # ops/topk.py (ISSUE 18): the device top-k kernels allocate carried
    # merge state the pipeline must release at finalize — the module
    # itself must stay acquisition-free for that contract to hold
    EXTRA_FILES = ("tidb_tpu/utils/memory.py", "tidb_tpu/ops/topk.py")

    def __init__(self, scope: Sequence[str] = SCOPE,
                 extra_files: Sequence[str] = EXTRA_FILES):
        self.scope = tuple(scope)
        self.extra = tuple(f.replace("/", os.sep) for f in extra_files)

    def _files(self, project: Project) -> List[SourceFile]:
        files = list(project.files_under(*self.scope))
        have = {sf.rel for sf in files}
        for sf in project.files():
            if sf.rel in self.extra and sf.rel not in have:
                files.append(sf)
        return files

    def run(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for sf in self._files(project):
            used_notes: Set[int] = set()
            for fn, cls in _functions(sf.tree):
                scan = _FnScan(fn, cls)
                scan.run()
                cls_releases: Optional[Set[str]] = None
                for acq in scan.acquires:
                    note = sf.lifecycle_note(acq.line)
                    if note is not None:
                        used_notes.add(note[0])
                        continue
                    if acq.protected:
                        continue
                    names = set(acq.releases)
                    if names & scan.releases_protected:
                        continue  # release reachable on the except path
                    counter = acq.spec_kind == "refcount bump"
                    if not counter:
                        # class-managed lifetime: close()/release()
                        # elsewhere in the class owns the balance (the
                        # runtime sanitizer audits it at statement end)
                        if cls_releases is None:
                            cls_releases = _class_release_kinds(cls, fn)
                        if names & cls_releases:
                            continue
                    if names & scan.releases_all:
                        out.append(Violation(
                            self.id, sf.rel, acq.line,
                            f"{acq.spec_kind} `{acq.label}` is released "
                            "only on the success path of "
                            f"{fn.name}() — an exception between acquire "
                            "and release leaks it (the evict_segment "
                            "ENOSPC class). Move the release into a "
                            "finally/except, use a context manager, or "
                            "annotate the acquire with `# lifecycle: "
                            "<why the release is guaranteed>`."))
                        continue
                    out.append(Violation(
                        self.id, sf.rel, acq.line,
                        f"{acq.spec_kind} `{acq.label}` in {fn.name}() "
                        "has no matching release on any path "
                        f"(looked for {', '.join(sorted(names))}). "
                        "Release it in a finally, hand it to a class "
                        "that does, or annotate with `# lifecycle: "
                        "<reason>` if ownership moves elsewhere."))
            # stale handoff annotations pre-allowlist a FUTURE acquire —
            # the same invisible-leak class this pass exists to catch
            for line in sorted(set(sf.lifecycle_notes) - used_notes):
                out.append(Violation(
                    self.id, sf.rel, line,
                    "stale lifecycle annotation: no registered acquire "
                    "on the governed line — delete it (or re-anchor it; "
                    "a refactor may have moved the acquire)"))
        return out


def lifecycle_sites(project: Project):
    """Every `# lifecycle:` annotation in scope — the documented
    allowlist of ownership handoffs (rendered by check_invariants
    --syncs beside the host-sync table, counted in the --json report,
    and pinned tier-1 so drift is visible like any suppression)."""
    p = ResourceLifecyclePass()
    out = []
    for sf in p._files(project):
        for line, reason in sorted(sf.lifecycle_notes.items()):
            out.append((sf.rel, line, reason))
    return out


def _functions(tree: ast.Module):
    """Yield (function, enclosing_class_or_None) pairs, outermost class
    attribution only (nested defs attribute to their lexical class)."""
    out = []

    def visit(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((child, cls))
                visit(child, cls)
            else:
                visit(child, cls)

    visit(tree, None)
    return out
