"""blocking-under-lock pass: no registered lock held across a blocking
call (ISSUE 12 — the machine-checked form of the columnar "store lock
is a LEAF" rule).

PR 7's wait-discipline check proved the shape on the serving tier (a
``cv.wait()`` parked with a foreign lock held stalls every statement
behind that lock for the whole gather window). This pass generalizes
it across every module that owns threading locks: while a
``with <lock>:`` body is executing, none of these may run —

  * ``wait()`` / ``wait_for()`` on anything but the held cv itself
    (Condition.wait releases only its OWN lock);
  * ``jax.device_get`` — a device→host sync can stall for a full
    accelerator round trip (and on a tunneled TPU, ~500 ms);
  * socket I/O (``recv``/``sendall``/``accept``/``connect``/…) and
    file I/O (``open``, ``np.save``/``np.load``, spill-file
    ``save``/``load``, ``rmtree``);
  * ``MemTracker.consume`` — it re-enters spill (disk I/O) past the
    budget, so holding any lock across it holds that lock across an
    arbitrary eviction;
  * ``spill()`` / ``time.sleep`` / thread ``join`` / queue gets.

Calls are also propagated ONE level through same-class methods
(``self.m()`` under a lock where ``m`` blocks is flagged at the call
site), mirroring lock-discipline's deferred-acquire edges.

Intentional exceptions are suppressions with reasons (``# lint:
disable=blocking-under-lock -- <why>``) so each one is a documented,
counted decision — e.g. utils/memory's budget-exceeded path, which
deliberately trades concurrency for correctness under the account
lock.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tidb_tpu.analysis.core import Pass, Project, SourceFile, Violation

__all__ = ["BlockingUnderLockPass", "DEFAULT_MODULES"]

DEFAULT_MODULES = (
    "tidb_tpu/parallel/dcn.py",
    "tidb_tpu/utils/tracing.py",
    "tidb_tpu/planner/plancache.py",
    "tidb_tpu/utils/stmtsummary.py",
    "tidb_tpu/storage/catalog.py",
    "tidb_tpu/serving/scheduler.py",
    "tidb_tpu/serving/batcher.py",
    "tidb_tpu/columnar/store.py",
    "tidb_tpu/executor/pipeline.py",
    "tidb_tpu/utils/memory.py",
    # shuffle exchange (ISSUE 13): the placement and inbox locks are
    # LEAVES — a shuffle send under them would stall every stage/gather
    # behind one slow peer socket (fixture: bad_shuffle_lock.py)
    "tidb_tpu/sharding/shuffle.py",
    "tidb_tpu/sharding/placement.py",
    # plan feedback (ISSUE 15): the store lock is a LEAF — fold/read
    # only, no planning, device work, or I/O may run under it
    "tidb_tpu/planner/feedback.py",
    # latency SLOs (ISSUE 16): same leaf contract — the metric gauge
    # updates and eviction cleanup run after the lock is released
    "tidb_tpu/serving/slo.py",
    # background compaction (ISSUE 17): the whole point of the worker
    # is rebuild-outside-locks — encode/spill I/O under the store or
    # queue lock would stall every scan behind the rebuild it exists
    # to hide (fixture: bad_compaction_lock.py)
    "tidb_tpu/columnar/compaction.py",
    # fused device top-k (ISSUE 18): the kernels are pure and lock-free
    # by contract — any lock (or device fetch under one) appearing here
    # means per-chunk merge state leaked host-side coordination
    # (fixture: bad_topk_sync.py covers the host-sync half)
    "tidb_tpu/ops/topk.py",
    # topology gates (ISSUE 19): Condition.wait released-while-waiting
    # is the one sanctioned blocking call; anything else under the
    # registry lock (an RPC, a fingerprint build) would stall EVERY
    # statement's gate acquire behind one cutover
    "tidb_tpu/parallel/membership.py",
)

# attribute names whose call blocks the thread
_BLOCKING_ATTRS = {
    "device_get": "device fetch",
    "recv": "socket recv", "recv_into": "socket recv",
    "sendall": "socket send", "accept": "socket accept",
    "connect": "socket connect", "makefile": "socket I/O",
    "sleep": "sleep",
    "consume": "tracker charge (re-enters spill past the budget)",
    "spill": "spill I/O",
    "rmtree": "file I/O",
}
# save/load block only on file-ish receivers (np / spill files) — a
# plain dict .get or config .load elsewhere is not I/O
_IO_SAVE_LOAD_ROOTS = ("np", "numpy")


def _is_lockish(expr: ast.AST) -> Optional[str]:
    """Normalized name when `expr` looks like a lock/condition object."""
    if not isinstance(expr, (ast.Attribute, ast.Name)):
        return None
    text = ast.unparse(expr)
    leaf = text.rsplit(".", 1)[-1].lower()
    if "lock" in leaf or leaf in ("cv", "cond") or leaf.endswith("_cv") \
            or "condition" in leaf:
        return text
    return None


def _blocking_kind(node: ast.Call) -> Optional[Tuple[str, str]]:
    """(kind label, rendered call) when `node` is a blocking call."""
    f = node.func
    if isinstance(f, ast.Name):
        if f.id == "open":
            return "file open", "open(...)"
        if f.id == "device_get":
            return "device fetch", "device_get(...)"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    recv = ast.unparse(f.value)
    root = recv.split(".", 1)[0].split("[", 1)[0]
    if f.attr in ("wait", "wait_for"):
        return "blocking wait", f"{recv}.{f.attr}(...)"
    if f.attr in _BLOCKING_ATTRS:
        return _BLOCKING_ATTRS[f.attr], f"{recv}.{f.attr}(...)"
    if f.attr in ("save", "load") and (
            root in _IO_SAVE_LOAD_ROOTS or "spill" in recv.lower()):
        return "file I/O", f"{recv}.{f.attr}(...)"
    if f.attr == "join" and ("thread" in recv.lower()
                             or "worker" in recv.lower()
                             or any(kw.arg == "timeout"
                                    for kw in node.keywords)):
        return "thread join", f"{recv}.join(...)"
    if f.attr in ("get", "put") and "queue" in recv.lower():
        return "queue wait", f"{recv}.{f.attr}(...)"
    return None


def _walk_own(fn: ast.AST):
    """ast.walk that does not descend into nested function/class
    definitions (their bodies execute in a later scope)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class BlockingUnderLockPass(Pass):
    id = "blocking-under-lock"
    doc = ("no registered lock held across a blocking call (waits, "
           "device fetches, socket/file I/O, tracker consume/spill) — "
           "the columnar leaf-lock rule, machine-checked")

    def __init__(self, modules: Sequence[str] = DEFAULT_MODULES):
        self.modules = tuple(m.replace("/", os.sep) for m in modules)

    def run(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for sf in project.files():
            if sf.rel not in self.modules:
                continue
            # pre-scan: per-class map of method -> blocking calls inside
            # it, for the one-level self.m() propagation
            method_blocks: Dict[Tuple[str, str], List[str]] = {}
            for cls in [n for n in ast.walk(sf.tree)
                        if isinstance(n, ast.ClassDef)]:
                for m in cls.body:
                    if not isinstance(m, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        continue
                    kinds = []
                    # nested defs run LATER (usually outside the caller's
                    # lock scope): only the method's own statements count
                    for node in _walk_own(m):
                        if isinstance(node, ast.Call):
                            bk = _blocking_kind(node)
                            if bk is not None:
                                kinds.append(f"{bk[0]} ({bk[1]})")
                    if kinds:
                        method_blocks[(cls.name, m.name)] = kinds
            for cls in [n for n in ast.walk(sf.tree)
                        if isinstance(n, ast.ClassDef)]:
                for m in cls.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._walk(sf, m.body, (), out,
                                   method_blocks, cls.name)
            # module-level functions (no self-propagation there)
            for node in sf.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._walk(sf, node.body, (), out, method_blocks, None)
        return out

    # -- held-lock walk ----------------------------------------------------

    def _walk(self, sf: SourceFile, stmts, held: Tuple[str, ...], out,
              method_blocks, cls_name: Optional[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # closure bodies run later, outside this scope
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new = list(held)
                for item in stmt.items:
                    for sub in ast.walk(item.context_expr):
                        self._flag(sf, sub, held, out, method_blocks,
                                   cls_name)
                    lid = _is_lockish(item.context_expr)
                    if lid is not None:
                        new.append(lid)
                self._walk(sf, stmt.body, tuple(new), out, method_blocks,
                           cls_name)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                for sub in ast.walk(stmt.iter):
                    self._flag(sf, sub, held, out, method_blocks, cls_name)
                self._walk(sf, stmt.body, held, out, method_blocks, cls_name)
                self._walk(sf, stmt.orelse, held, out, method_blocks,
                           cls_name)
            elif isinstance(stmt, ast.While):
                for sub in ast.walk(stmt.test):
                    self._flag(sf, sub, held, out, method_blocks, cls_name)
                self._walk(sf, stmt.body, held, out, method_blocks, cls_name)
                self._walk(sf, stmt.orelse, held, out, method_blocks,
                           cls_name)
            elif isinstance(stmt, ast.If):
                for sub in ast.walk(stmt.test):
                    self._flag(sf, sub, held, out, method_blocks, cls_name)
                self._walk(sf, stmt.body, held, out, method_blocks, cls_name)
                self._walk(sf, stmt.orelse, held, out, method_blocks,
                           cls_name)
            elif isinstance(stmt, ast.Try):
                self._walk(sf, stmt.body, held, out, method_blocks, cls_name)
                for h in stmt.handlers:
                    self._walk(sf, h.body, held, out, method_blocks,
                               cls_name)
                self._walk(sf, stmt.orelse, held, out, method_blocks,
                           cls_name)
                self._walk(sf, stmt.finalbody, held, out, method_blocks,
                           cls_name)
            elif isinstance(stmt, ast.Match):
                for sub in ast.walk(stmt.subject):
                    self._flag(sf, sub, held, out, method_blocks, cls_name)
                for case in stmt.cases:
                    if case.guard is not None:
                        for sub in ast.walk(case.guard):
                            self._flag(sf, sub, held, out, method_blocks,
                                       cls_name)
                    self._walk(sf, case.body, held, out, method_blocks,
                               cls_name)
            else:
                for sub in ast.walk(stmt):
                    self._flag(sf, sub, held, out, method_blocks, cls_name)

    def _flag(self, sf: SourceFile, node, held: Tuple[str, ...], out,
              method_blocks, cls_name: Optional[str]) -> None:
        if not held or not isinstance(node, ast.Call):
            return
        bk = _blocking_kind(node)
        if bk is not None:
            kind, call = bk
            if kind == "blocking wait":
                # Condition.wait releases its OWN lock: only FOREIGN
                # held locks are the hazard (PR 7's gather-window rule)
                recv = ast.unparse(node.func.value)
                others = [h for h in held if h != recv]
                if not others:
                    return
                out.append(Violation(
                    self.id, sf.rel, node.lineno,
                    f"blocking {node.func.attr}() on `{recv}` while "
                    f"holding {', '.join(sorted(set(others)))} — a "
                    "gather-window wait must not park the thread with "
                    "another lock held (it stalls every statement and "
                    "batch dispatch behind that lock for the whole "
                    "window). Release the outer lock before waiting."))
                return
            out.append(Violation(
                self.id, sf.rel, node.lineno,
                f"{kind} `{call}` while holding "
                f"{', '.join(sorted(set(held)))} — registered locks are "
                "LEAVES: release the lock before blocking (or suppress "
                "with a reason if the stall is a deliberate design "
                "decision)."))
            return
        # one-level propagation: a same-class method that blocks, called
        # while the lock is held — matched by name on ANY receiver, not
        # just `self` (the account-lock walk calls `node._on_exceed()`
        # on each ancestor tracker; those are still this class)
        f = node.func
        if cls_name is not None and isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name):
            kinds = method_blocks.get((cls_name, f.attr))
            if kinds:
                out.append(Violation(
                    self.id, sf.rel, node.lineno,
                    f"{f.value.id}.{f.attr}() called while holding "
                    f"{', '.join(sorted(set(held)))} and its body blocks: "
                    f"{kinds[0]}"
                    + (f" (+{len(kinds) - 1} more)" if len(kinds) > 1
                       else "")
                    + " — registered locks are LEAVES; move the blocking "
                    "work outside the lock or suppress with a reason."))
