"""registry-coverage passes: metrics, failpoints, sysvars.

The two proven single-purpose checkers (``scripts/check_metrics.py``,
``scripts/check_failpoints.py``) live here now as driver passes; the
scripts remain as thin CLI shims with their original function surfaces
(``collect``/``check``/``scan``/``main``) so existing tier-1 tests and
muscle memory keep working.

The sysvar pass is new: every ``tidb_*`` sysvar the engine reads
(``sysvars.get("tidb_...")``) must be registered in
``session/sysvars.py``; every registered ``tidb_*`` sysvar must be read
somewhere (a dead sysvar is a silent no-op knob — worse than an error)
and documented in README.md.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

from tidb_tpu.analysis.core import Pass, Project, Violation

__all__ = ["MetricsCoveragePass", "FailpointCoveragePass",
           "SysvarCoveragePass", "metrics_problems", "failpoint_scan",
           "plan_feedback_surfaces", "observability_surfaces",
           "elastic_surfaces"]


# ---------------------------------------------------------------------------
# plan-feedback surfaces (ISSUE 15)
# ---------------------------------------------------------------------------

# every user-visible surface the plan-feedback layer must keep alive,
# as (repo-relative file, required marker). check_invariants --json
# reports the count so a refactor that silently drops one (renames the
# I_S table, loses the endpoint, un-registers the sysvar/metric) is a
# STATIC diff, caught before any runtime test notices.
_PLAN_FEEDBACK_SURFACES: Tuple[Tuple[str, str], ...] = (
    ("tidb_tpu/storage/catalog.py", 'if name == "plan_feedback"'),
    ("tidb_tpu/server/status.py", '"/plan_feedback"'),
    ("tidb_tpu/utils/metrics.py", '"tidb_tpu_plan_est_drift"'),
    ("tidb_tpu/session/sysvars.py", '"tidb_tpu_plan_feedback"'),
    ("tidb_tpu/utils/execdetails.py", '"drift"'),
    ("tidb_tpu/storage/catalog.py", '("worst_drift", FLOAT64)'),
)


def _surfaces_present(project: Project,
                      pairs: Tuple[Tuple[str, str], ...]
                      ) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    for rel, marker in pairs:
        path = os.path.join(project.root, rel)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        if marker in src:
            out.append((rel, marker))
    return out


def plan_feedback_surfaces(project: Project) -> List[Tuple[str, str]]:
    """The plan-feedback surfaces present in this tree: each registered
    (file, marker) pair whose marker still appears in the file's
    source. A full tree has all of them; the count is pinned tier-1."""
    return _surfaces_present(project, _PLAN_FEEDBACK_SURFACES)


# every user-visible surface of the ISSUE 16 observability plane
# (cluster metrics, resource profiles, latency SLOs), same contract as
# the plan-feedback list: a refactor that drops a surface is a static
# diff in check_invariants --json before any runtime test notices.
_OBSERVABILITY_SURFACES: Tuple[Tuple[str, str], ...] = (
    ("tidb_tpu/storage/catalog.py", 'if name == "cluster_metrics"'),
    ("tidb_tpu/storage/catalog.py", 'if name == "digest_latency"'),
    ("tidb_tpu/server/status.py", 'scope=cluster'),
    ("tidb_tpu/server/status.py", '"/slo"'),
    ("tidb_tpu/parallel/dcn.py", '"metrics_snapshot"'),
    ("tidb_tpu/utils/metrics.py", '"tidb_tpu_digest_p99_seconds"'),
    ("tidb_tpu/utils/metrics.py", '"tidb_tpu_xfer_bytes_total"'),
    ("tidb_tpu/utils/metrics.py", '"tidb_tpu_compile_seconds_total"'),
    ("tidb_tpu/session/sysvars.py", '"tidb_tpu_slo_target_ms"'),
    ("tidb_tpu/session/sysvars.py", '"tidb_tpu_sched_slo_shed"'),
    ("tidb_tpu/serving/slo.py", "should_shed"),
    ("tidb_tpu/storage/catalog.py", '("xfer_bytes", INT64)'),
)


def observability_surfaces(project: Project) -> List[Tuple[str, str]]:
    """The ISSUE 16 observability surfaces present in this tree (same
    marker contract as plan_feedback_surfaces)."""
    return _surfaces_present(project, _OBSERVABILITY_SURFACES)


# every user-visible surface of the ISSUE 19 elastic-topology plane
# (online reshard, membership lifecycle, recovery entry points, the
# cluster_info I_S table, metrics, gate sysvar), same contract as the
# two lists above: a refactor that drops one is a static diff in
# check_invariants --json before any runtime test notices.
_ELASTIC_SURFACES: Tuple[Tuple[str, str], ...] = (
    ("tidb_tpu/parallel/dcn.py", "def reshard"),
    ("tidb_tpu/parallel/dcn.py", "def recover_reshard"),
    ("tidb_tpu/parallel/dcn.py", "def add_worker"),
    ("tidb_tpu/parallel/dcn.py", "def remove_worker"),
    ("tidb_tpu/parallel/dcn.py", "def reshard_progress_rows"),
    ("tidb_tpu/parallel/membership.py", "CLUSTER_GATE"),
    ("tidb_tpu/storage/catalog.py", 'if name == "cluster_info"'),
    ("tidb_tpu/utils/metrics.py", '"tidb_tpu_reshard_shards_total"'),
    ("tidb_tpu/utils/metrics.py", '"tidb_tpu_reshard_active"'),
    ("tidb_tpu/utils/metrics.py", '"tidb_tpu_membership_total"'),
    ("tidb_tpu/session/sysvars.py", '"tidb_tpu_reshard_gate_wait_ms"'),
)


def elastic_surfaces(project: Project) -> List[Tuple[str, str]]:
    """The ISSUE 19 elastic-topology surfaces present in this tree
    (same marker contract as plan_feedback_surfaces)."""
    return _surfaces_present(project, _ELASTIC_SURFACES)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def metrics_collect(root: str):
    """Import the metrics module from `root` and return (module,
    registered collectors)."""
    sys.path.insert(0, root)
    try:
        import importlib

        mod = importlib.import_module("tidb_tpu.utils.metrics")
    finally:
        sys.path.pop(0)
    # metric registration is import-global: if tidb_tpu was already
    # imported from a DIFFERENT checkout (this analyzer's own repo —
    # the shims import it at module load), a `--root` pointing
    # elsewhere would silently check the wrong repo's metrics against
    # the target's README. Refuse loudly instead.
    src = os.path.realpath(getattr(mod, "__file__", "") or "")
    want = os.path.realpath(os.path.join(root, "tidb_tpu"))
    if not src.startswith(want + os.sep):
        raise RuntimeError(
            f"cannot check metrics for root {root!r}: tidb_tpu is "
            f"already imported from {src} in this process. Run the "
            "checker from inside the target checkout instead.")
    with mod.REGISTRY.lock:
        metrics = list(mod.REGISTRY.metrics)
    return mod, metrics


def metrics_problems(root: str, readme_path: str
                     ) -> Tuple[List[str], List[str]]:
    """-> (problems, metric_names): every registered collector renders,
    carries help, is documented in README; duplicates are errors."""
    mod, metrics = metrics_collect(root)
    rendered = mod.render_prometheus()
    try:
        with open(readme_path, encoding="utf-8") as f:
            readme = f.read()
    except OSError as e:
        return [f"README unreadable: {e}"], []

    problems = []
    seen: Dict[str, object] = {}
    for m in metrics:
        if m.name in seen:
            problems.append(
                f"DUPLICATE metric name {m.name!r} (registered twice)")
        seen[m.name] = m
        if not (m.help or "").strip():
            problems.append(f"metric {m.name!r} has no help string")
        if f"# HELP {m.name} " not in rendered:
            problems.append(
                f"metric {m.name!r} missing from render_prometheus() output")
        if m.name not in readme:
            problems.append(
                f"ORPHAN metric {m.name!r}: not mentioned in README.md")
    return problems, sorted(seen)


class MetricsCoveragePass(Pass):
    id = "metrics-coverage"
    doc = ("every registered metric renders on /metrics, carries help, "
           "and is documented in README")

    def run(self, project: Project) -> List[Violation]:
        readme = os.path.join(project.root, "README.md")
        rel = os.path.join("tidb_tpu", "utils", "metrics.py")
        try:
            problems, _names = metrics_problems(project.root, readme)
        except RuntimeError as e:
            # wrong-checkout refusal from metrics_collect: report it as
            # a violation so the pure-AST passes still render theirs
            return [Violation(self.id, rel, 1, str(e))]
        return [Violation(self.id, rel, 1, p) for p in problems]


# ---------------------------------------------------------------------------
# failpoints (ported verbatim from scripts/check_failpoints.py)
# ---------------------------------------------------------------------------

_SITE_RE = re.compile(r"""\binject\(\s*(['"])([^'"]+)\1\s*\)""")
_SITE_DYN_RE = re.compile(r"""\binject\(\s*[^'")]""")
_ARM_RE = re.compile(r"""\b(?:failpoint|enable)\(\s*(['"])([^'"]+)\1""")

_SELF = {"failpoint.py", "check_failpoints.py"}


def _py_files(root: str, subdir: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(os.path.join(root, subdir)):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out.extend(os.path.join(dirpath, f) for f in filenames
                   if f.endswith(".py") and f not in _SELF)
    return sorted(out)


def failpoint_scan(root: str) -> Tuple[Dict[str, List[str]],
                                       Dict[str, List[str]], List[str]]:
    """-> (sites, armed, dynamic_sites): name -> ["file:line", ...].

    A site also counts as ARMED (covered) when its exact name appears
    as a string literal anywhere under tests/ — chaos grids arm
    failpoints through parametrized lists, so requiring the literal
    inside the failpoint() call itself would misreport every grid as
    uncovered.  The DEAD direction stays strict: only names inside
    literal failpoint()/enable() calls can be dead."""
    sites: Dict[str, List[str]] = {}
    armed: Dict[str, List[str]] = {}
    dynamic: List[str] = []
    for path in _py_files(root, "tidb_tpu"):
        rel = os.path.relpath(path, root)
        with open(path, encoding="utf-8") as f:
            for ln, line in enumerate(f, 1):
                for m in _SITE_RE.finditer(line):
                    sites.setdefault(m.group(2), []).append(f"{rel}:{ln}")
                if _SITE_DYN_RE.search(line) and "def inject" not in line:
                    dynamic.append(f"{rel}:{ln}")
    test_blobs: List[Tuple[str, str]] = []
    for sub in ("tests", "tidb_tpu", "scripts"):
        for path in _py_files(root, sub):
            rel = os.path.relpath(path, root)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            if sub == "tests":
                test_blobs.append((rel, text))
            for ln, line in enumerate(text.splitlines(), 1):
                for m in _ARM_RE.finditer(line):
                    armed.setdefault(m.group(2), []).append(f"{rel}:{ln}")
    for name in sites:
        if name in armed:
            continue
        for rel, text in test_blobs:
            if f'"{name}"' in text or f"'{name}'" in text:
                armed.setdefault(name, []).append(f"{rel} (mention)")
                break
    return sites, armed, dynamic


class FailpointCoveragePass(Pass):
    id = "failpoint-coverage"
    doc = ("no dead (siteless) armed failpoints, no non-literal inject() "
           "names")

    def run(self, project: Project) -> List[Violation]:
        sites, armed, dynamic = failpoint_scan(project.root)
        out: List[Violation] = []
        for name in sorted(set(armed) - set(sites)):
            for loc in armed[name]:
                path, _, line = loc.partition(":")
                out.append(Violation(
                    self.id, path, int(line.split()[0]) if line else 1,
                    f"DEAD failpoint {name!r}: armed here but no inject() "
                    "site exists (a refactor moved or renamed the call "
                    "site?)"))
        for loc in dynamic:
            path, _, line = loc.partition(":")
            out.append(Violation(
                self.id, path, int(line) if line else 1,
                "non-literal inject() name cannot be statically checked"))
        return out


# ---------------------------------------------------------------------------
# sysvars
# ---------------------------------------------------------------------------


class SysvarCoveragePass(Pass):
    id = "sysvar-coverage"
    doc = ("every tidb_* sysvar read is registered; every registered one "
           "is read somewhere and documented in README")

    SYSVARS_REL = os.path.join("tidb_tpu", "session", "sysvars.py")

    def run(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        reg_path = os.path.join(project.root, self.SYSVARS_REL)
        registered: Dict[str, int] = {}
        if not os.path.exists(reg_path):
            return [Violation(self.id, self.SYSVARS_REL, 1,
                              "sysvar registry module not found")]
        sf = project.file(reg_path)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "SysVar" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                registered[node.args[0].value] = node.lineno

        reads: Dict[str, List[Tuple[str, int]]] = {}
        for mod in project.files():
            for node in ast.walk(mod.tree):
                for name in self._read_names(node):
                    reads.setdefault(name, []).append((mod.rel, node.lineno))

        for name, sites in sorted(reads.items()):
            if not name.startswith("tidb_"):
                continue
            if name not in registered:
                rel, line = sites[0]
                out.append(Violation(
                    self.id, rel, line,
                    f"sysvar {name!r} is read here but not registered in "
                    "session/sysvars.py — SET/SHOW would reject it and the "
                    "read raises at runtime"))
        readme = ""
        readme_path = os.path.join(project.root, "README.md")
        if os.path.exists(readme_path):
            with open(readme_path, encoding="utf-8") as f:
                readme = f.read()
        for name, line in sorted(registered.items()):
            if not name.startswith("tidb_"):
                continue
            if name not in reads:
                out.append(Violation(
                    self.id, self.SYSVARS_REL, line,
                    f"dead sysvar {name!r}: registered but never read by "
                    "the engine — a silent no-op knob. Wire it or delete "
                    "it."))
            if name not in readme:
                out.append(Violation(
                    self.id, self.SYSVARS_REL, line,
                    f"sysvar {name!r} is not documented in README.md"))
        return out

    @staticmethod
    def _read_names(node: ast.AST) -> List[str]:
        """`<...>sysvars.get("name")` / `SYSVARS.get("name")` -> names.
        Conditional reads (`get("a" if x else "b")`) yield both arms."""
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get" and node.args):
            return []
        recv_txt = ast.unparse(node.func.value)
        if recv_txt != "SYSVARS" and not recv_txt.endswith("sysvars"):
            return []
        arg = node.args[0]
        arms = ([arg.body, arg.orelse] if isinstance(arg, ast.IfExp)
                else [arg])
        return [a.value for a in arms
                if isinstance(a, ast.Constant) and isinstance(a.value, str)]
