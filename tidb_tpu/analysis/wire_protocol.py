"""protocol-conformance pass: static model of the DCN dict wire
protocol, diffed in both directions (ISSUE 14).

The DCN tier's wire protocol is untyped dicts dispatched on a string
``cmd`` (``parallel/dcn.py`` ``Worker._handle``). PR 12 made it a real
distributed protocol — shuffle exchange, 2PC, reshard — and the failure
mode of an untyped protocol is silent: a sender/handler field mismatch
is a remote ``KeyError`` on a worker, invisible until a chaos test
happens to cross that arm. This pass extracts both directions of the
protocol from the AST and diffs them:

  * **send sites** — every ``{"cmd": <literal>}`` dict literal in the
    protocol modules, tracking field additions in the same function
    (``msg["k"] = ...``, ``msg.update(k=...)``, and ``msg["cmd"] = ...``
    re-dispatch forks like the partial_paged -> shuffle_gather switch).
    Fields added under extra conditions are *optional*; literal keys and
    same-branch additions are *required*.
  * **handler arms** — ``_handle``'s ``if cmd == ...`` dispatch, with
    each arm's ``msg[...]`` (required) / ``msg.get(...)`` (optional)
    reads collected transitively through the helper methods the arm
    hands ``msg`` to (``_partial_paged`` -> ``_run_sql`` etc.); reads
    nested under further conditions count as *conditional* (provable
    neither way).
  * **envelope** — fields the transport injects into EVERY message
    (``dict(msg, trace_id=...)`` on a parameter in ``_call``) and the
    fields the server preamble reads before dispatch (``_serve_conn`` /
    ``_handle`` top level). ``_``-prefixed keys are server-local
    annotations, never wire fields.

Violations: a cmd sent with no handler arm; a handler's unconditional
``msg[...]`` read of a field some sender omits (the remote KeyError); a
sent field no handler read ever touches (dead wire bytes); a handler
arm no site sends (dead arm); a *worker-side re-send* (a cmd literal
inside the handler class — the shuffle_scatter peer re-dispatch) that
does not propagate the statement envelope (``trace_id`` +
``deadline_s``); and a non-literal ``cmd`` value (the model — and the
runtime wire witness built on it — can only protect what it can name).

The extracted model is committed as ``analysis/wire_protocol.json``
(the runtime wire witness in ``analysis/sanitizer.py`` diffs real
traffic against it) and rendered as ``docs/WIRE_PROTOCOL.md``; this
pass re-extracts on every run and flags drift, so the committed model
can never silently rot behind the code.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tidb_tpu.analysis.core import Pass, Project, SourceFile, Violation

__all__ = ["ProtocolConformancePass", "extract_model", "to_wire_model",
           "render_markdown", "MODEL_REL_PATH", "DOC_REL_PATH",
           "ENVELOPE_REQUIRED"]

# the modules that ARE the wire protocol: every {"cmd": ...} literal in
# them is a send site, and the class defining _handle is the server
SEND_MODULES = ("tidb_tpu/parallel/dcn.py", "tidb_tpu/sharding/shuffle.py")

# committed artifacts (repo-relative); the pass checks them for drift
MODEL_REL_PATH = "tidb_tpu/analysis/wire_protocol.json"
DOC_REL_PATH = "docs/WIRE_PROTOCOL.md"

# the statement envelope a worker-side re-send must propagate: the
# coordinator's trace context and the statement's remaining budget
# (ISSUE 14 — the shuffle_scatter peer re-dispatch rule)
ENVELOPE_REQUIRED = ("trace_id", "deadline_s")

MODEL_SCHEMA = 1


# ---------------------------------------------------------------------------
# extraction model
# ---------------------------------------------------------------------------


@dataclass
class SendSite:
    cmd: str
    path: str                  # repo-relative
    line: int
    fn: str                    # "Class.method" / "function"
    required: Set[str] = field(default_factory=set)
    optional: Set[str] = field(default_factory=set)
    in_handler_class: bool = False   # a worker re-dispatch site

    def fields(self) -> Set[str]:
        return self.required | self.optional


@dataclass
class HandlerArm:
    cmd: str
    path: str
    line: int
    fn: str
    required: Set[str] = field(default_factory=set)     # msg[...] uncond.
    conditional: Set[str] = field(default_factory=set)  # msg[...] under if
    optional: Set[str] = field(default_factory=set)     # msg.get(...)

    def reads(self) -> Set[str]:
        return self.required | self.conditional | self.optional


@dataclass
class ProtocolModel:
    senders: List[SendSite] = field(default_factory=list)
    handlers: Dict[str, HandlerArm] = field(default_factory=dict)
    envelope_sent: Set[str] = field(default_factory=set)
    envelope_read: Set[str] = field(default_factory=set)
    problems: List[Violation] = field(default_factory=list)


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_wire_field(name: str) -> bool:
    # "_"-prefixed keys are server-local annotations (e.g.
    # _deadline_mono anchored at receipt), never wire fields
    return name != "cmd" and not name.startswith("_")


# ---------------------------------------------------------------------------
# send-site extraction
# ---------------------------------------------------------------------------


# a branch frame: ("if", id(node), arm_index) — contexts are stacks of
# frames from the function root; Try/loop bodies add an ("opt",) frame
# (their execution isn't provable, so additions there are optional)
_Ctx = Tuple[Tuple, ...]


def _compatible(a: _Ctx, b: _Ctx) -> bool:
    """Two contexts can both be live unless they take DIFFERENT arms of
    the SAME if statement."""
    for fa, fb in zip(a, b):
        if fa == fb:
            continue
        if fa[0] == "if" and fb[0] == "if" and fa[1] == fb[1] \
                and fa[2] != fb[2]:
            return False
        return True
    return True


def _is_prefix(a: _Ctx, b: _Ctx) -> bool:
    return len(a) <= len(b) and b[:len(a)] == a


@dataclass
class _Variant:
    """One (dict variable, cmd) in flight inside a function."""
    cmd: str
    line: int
    ctx: _Ctx
    required: Set[str]
    optional: Set[str]
    excluded: List[_Ctx] = field(default_factory=list)  # forked-away branches

    def add(self, name: str, ctx: _Ctx) -> None:
        if any(_is_prefix(e, ctx) for e in self.excluded):
            return  # the dict is a different cmd in that branch
        if not _compatible(self.ctx, ctx):
            return
        if not _is_wire_field(name):
            return
        if ctx == self.ctx:
            self.required.add(name)
        else:
            self.optional.add(name)


class _SendScan:
    """Collect send sites from one function body (linear walk with a
    branch-context stack)."""

    def __init__(self, sf: SourceFile, fn_name: str,
                 in_handler_class: bool, model: ProtocolModel):
        self.sf = sf
        self.fn_name = fn_name
        self.in_handler_class = in_handler_class
        self.model = model
        self.vars: Dict[str, List[_Variant]] = {}
        self.params: Set[str] = set()

    # -- helpers -----------------------------------------------------------

    def _dict_cmd(self, node: ast.AST) -> Optional[ast.Dict]:
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if _const_str(k) == "cmd":
                    return node
        return None

    def _literal_fields(self, d: ast.Dict) -> Tuple[Optional[str], Set[str]]:
        cmd = None
        fields: Set[str] = set()
        for k, v in zip(d.keys, d.values):
            name = _const_str(k)
            if name is None:
                if k is None:
                    self.model.problems.append(Violation(
                        ProtocolConformancePass.id, self.sf.rel, d.lineno,
                        "wire message built with **-expansion: the "
                        "static protocol model cannot name its fields"))
                continue
            if name == "cmd":
                cmd = _const_str(v)
                if cmd is None:
                    self.model.problems.append(Violation(
                        ProtocolConformancePass.id, self.sf.rel,
                        d.lineno,
                        "non-literal cmd value in a wire message: the "
                        "protocol model (and the runtime wire witness) "
                        "can only protect cmds it can name"))
            elif _is_wire_field(name):
                fields.add(name)
        return cmd, fields

    def _emit(self, var: _Variant) -> None:
        self.model.senders.append(SendSite(
            var.cmd, self.sf.rel, var.line, self.fn_name,
            set(var.required), set(var.optional),
            in_handler_class=self.in_handler_class))

    # -- walk --------------------------------------------------------------

    def run(self, fn: ast.AST) -> None:
        a = fn.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            self.params.add(arg.arg)
        self._walk(fn.body, ())
        for variants in self.vars.values():
            for v in variants:
                self._emit(v)

    def _walk(self, stmts: List[ast.stmt], ctx: _Ctx) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes are their own functions
            self._scan_stmt(stmt, ctx)
            if isinstance(stmt, ast.If):
                frame = ("if", id(stmt), 0)
                self._walk(stmt.body, ctx + (frame,))
                self._walk(stmt.orelse, ctx + (("if", id(stmt), 1),))
            elif isinstance(stmt, ast.Try):
                self._walk(stmt.body, ctx + (("opt", id(stmt)),))
                for h in stmt.handlers:
                    self._walk(h.body, ctx + (("opt", id(h)),))
                self._walk(stmt.orelse, ctx + (("opt", id(stmt), 2),))
                self._walk(stmt.finalbody, ctx)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self._walk(stmt.body, ctx + (("opt", id(stmt)),))
                self._walk(stmt.orelse, ctx)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._walk(stmt.body, ctx)

    def _scan_stmt(self, stmt: ast.stmt, ctx: _Ctx) -> None:
        # 1) tracked creation: `msg = {...,"cmd": c,...}`
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            d = self._dict_cmd(stmt.value)
            if d is not None:
                cmd, fields = self._literal_fields(d)
                if cmd is not None:
                    name = stmt.targets[0].id
                    self.vars.setdefault(name, []).append(_Variant(
                        cmd, d.lineno, ctx, fields, set()))
                return
        # 2) field add / cmd fork: `msg["k"] = v`
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Subscript) \
                and isinstance(stmt.targets[0].value, ast.Name):
            var = stmt.targets[0].value.id
            key = _const_str(stmt.targets[0].slice)
            variants = self.vars.get(var)
            if variants is not None and key is not None:
                if key == "cmd":
                    new_cmd = _const_str(stmt.value)
                    if new_cmd is None:
                        self.model.problems.append(Violation(
                            ProtocolConformancePass.id, self.sf.rel,
                            stmt.lineno,
                            "non-literal cmd re-assignment on a wire "
                            "message: the protocol model cannot name "
                            "the re-dispatched cmd"))
                        return
                    # fork: the dict is `new_cmd` in this branch from
                    # here on; the originals never see this branch
                    fork = _Variant(new_cmd, stmt.lineno, ctx,
                                    set(), set())
                    for v in variants:
                        if _compatible(v.ctx, ctx):
                            fork.required |= v.required
                            fork.optional |= v.optional
                            v.excluded.append(ctx)
                    variants.append(fork)
                else:
                    for v in variants:
                        v.add(key, ctx)
                return
        # 3) `msg.update(k=..., ...)` / `msg.update({...})`
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr == "update" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in self.vars:
                for kw in call.keywords:
                    if kw.arg is not None:
                        for v in self.vars[f.value.id]:
                            v.add(kw.arg, ctx)
                for arg in call.args:
                    if isinstance(arg, ast.Dict):
                        for k in arg.keys:
                            name = _const_str(k)
                            if name is not None:
                                for v in self.vars[f.value.id]:
                                    v.add(name, ctx)
                return
        # 3b) transport envelope injection: `msg = dict(msg, k=...)`
        # REBINDING a message parameter — the _call/_run_scatter idiom.
        # Only this exact shape counts: an arbitrary dict() rewrap
        # elsewhere in the module is ordinary code, and treating it as
        # envelope would silently widen the runtime witness allowlist
        # for every cmd
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.Call) \
                and isinstance(stmt.value.func, ast.Name) \
                and stmt.value.func.id == "dict" \
                and stmt.value.args \
                and isinstance(stmt.value.args[0], ast.Name) \
                and stmt.value.args[0].id == stmt.targets[0].id \
                and stmt.targets[0].id in self.params \
                and stmt.value.keywords:
            self.model.envelope_sent.update(
                kw.arg for kw in stmt.value.keywords
                if kw.arg is not None and _is_wire_field(kw.arg))
            return
        # 4) everything else: untracked literals + dict() rewraps.
        # Compound statements contribute only their HEADER expressions
        # here — their bodies come back through _walk, so scanning the
        # whole subtree would double-count every nested site.
        if isinstance(stmt, (ast.If, ast.While)):
            nodes = list(ast.walk(stmt.test))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            nodes = list(ast.walk(stmt.iter))
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            nodes = [n for item in stmt.items
                     for n in ast.walk(item.context_expr)]
        elif isinstance(stmt, ast.Try):
            nodes = []
        else:
            nodes = list(ast.walk(stmt))
        for node in nodes:
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "dict" and node.args and \
                    isinstance(node.args[0], ast.Name) and node.keywords:
                base = node.args[0].id
                kws = [kw.arg for kw in node.keywords
                       if kw.arg is not None and _is_wire_field(kw.arg)]
                if base in self.vars:
                    for v in self.vars[base]:
                        for k in kws:
                            # a rewrap's lifetime is the expression —
                            # always an optional augmentation
                            v.optional.add(k)
            d = self._dict_cmd(node)
            if d is not None and not (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.value is d):
                cmd, fields = self._literal_fields(d)
                if cmd is not None:
                    self.model.senders.append(SendSite(
                        cmd, self.sf.rel, d.lineno, self.fn_name,
                        fields, set(),
                        in_handler_class=self.in_handler_class))


# ---------------------------------------------------------------------------
# handler extraction
# ---------------------------------------------------------------------------


class _HandlerScan:
    """Reads of the msg parameter per dispatch arm, followed through
    helper methods the arm hands msg to (one class, memoized)."""

    def __init__(self, sf: SourceFile, cls: ast.ClassDef,
                 module_fns: Dict[str, ast.FunctionDef]):
        self.sf = sf
        self.cls = cls
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.module_fns = module_fns
        # method name -> (required, conditional, optional) of its own
        # msg-param reads incl. transitive helper calls
        self._memo: Dict[str, Tuple[Set[str], Set[str], Set[str]]] = {}

    # -- msg reads in a statement list ------------------------------------

    def _reads(self, stmts: List[ast.stmt], var: str, cond: bool,
               req: Set[str], con: Set[str], opt: Set[str],
               stack: Tuple[str, ...]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            self._scan_expr(stmt, var, cond, req, con, opt, stack,
                            headers_only=True)
            if isinstance(stmt, ast.If):
                self._reads(stmt.body, var, True, req, con, opt, stack)
                self._reads(stmt.orelse, var, True, req, con, opt, stack)
            elif isinstance(stmt, ast.Try):
                # a try body's reads are attempted (KeyError can fire);
                # handlers/orelse are conditional
                self._reads(stmt.body, var, cond, req, con, opt, stack)
                for h in stmt.handlers:
                    self._reads(h.body, var, True, req, con, opt, stack)
                self._reads(stmt.orelse, var, True, req, con, opt, stack)
                self._reads(stmt.finalbody, var, cond, req, con, opt,
                            stack)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self._reads(stmt.body, var, True, req, con, opt, stack)
                self._reads(stmt.orelse, var, True, req, con, opt, stack)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._reads(stmt.body, var, cond, req, con, opt, stack)

    def _scan_expr(self, stmt: ast.stmt, var: str, cond: bool,
                   req: Set[str], con: Set[str], opt: Set[str],
                   stack: Tuple[str, ...], headers_only: bool) -> None:
        """Reads in one statement's expressions. For compound
        statements only the header expressions are scanned here (their
        bodies come back through _reads with the right cond flag)."""
        if headers_only and isinstance(stmt, ast.If):
            nodes = list(ast.walk(stmt.test))
        elif headers_only and isinstance(stmt, (ast.For, ast.AsyncFor)):
            nodes = list(ast.walk(stmt.iter))
        elif headers_only and isinstance(stmt, ast.While):
            nodes = list(ast.walk(stmt.test))
        elif headers_only and isinstance(stmt, (ast.With, ast.AsyncWith)):
            nodes = [n for item in stmt.items
                     for n in ast.walk(item.context_expr)]
        elif headers_only and isinstance(stmt, ast.Try):
            nodes = []
        else:
            nodes = list(ast.walk(stmt))
        for node in nodes:
            # msg["field"] loads (stores are server-local annotations)
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == var and \
                    isinstance(node.ctx, ast.Load):
                name = _const_str(node.slice)
                if name is not None and _is_wire_field(name):
                    (con if cond else req).add(name)
            # msg.get("field"[, default])
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == var and node.args:
                name = _const_str(node.args[0])
                if name is not None and _is_wire_field(name):
                    opt.add(name)
            # helper delegation: self._meth(..., msg, ...) or f(msg)
            elif isinstance(node, ast.Call):
                self._delegate(node, var, cond, req, con, opt, stack)

    def _delegate(self, call: ast.Call, var: str, cond: bool,
                  req: Set[str], con: Set[str], opt: Set[str],
                  stack: Tuple[str, ...]) -> None:
        target: Optional[ast.FunctionDef] = None
        skip_self = 0
        f = call.func
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self":
            target = self.methods.get(f.attr)
            skip_self = 1
        elif isinstance(f, ast.Name):
            target = self.module_fns.get(f.id)
        if target is None or target.name in stack:
            return
        # which parameter receives our msg variable?
        param: Optional[str] = None
        names = [a.arg for a in target.args.args][skip_self:]
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Name) and arg.id == var \
                    and i < len(names):
                param = names[i]
                break
        if param is None:
            for kw in call.keywords:
                if isinstance(kw.value, ast.Name) and kw.value.id == var:
                    param = kw.arg
                    break
        if param is None:
            return
        sub = self._fn_reads(target, param, stack + (target.name,))
        if cond:
            con.update(sub[0])
        else:
            req.update(sub[0])
        con.update(sub[1])
        opt.update(sub[2])

    def _fn_reads(self, fn: ast.FunctionDef, param: str,
                  stack: Tuple[str, ...]
                  ) -> Tuple[Set[str], Set[str], Set[str]]:
        key = f"{fn.name}:{param}"
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        req: Set[str] = set()
        con: Set[str] = set()
        opt: Set[str] = set()
        self._memo[key] = (req, con, opt)  # cycle guard
        self._reads(fn.body, param, False, req, con, opt, stack)
        return req, con, opt

    # -- arms --------------------------------------------------------------

    def arms(self, model: ProtocolModel) -> None:
        handle = self.methods.get("_handle")
        if handle is None:
            return
        args = [a.arg for a in handle.args.args]
        msg = args[1] if len(args) > 1 and args[0] == "self" else args[0]
        # the dispatch variable: `cmd = msg["cmd"]`
        cmd_var = None
        for stmt in handle.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Subscript) \
                    and isinstance(stmt.value.value, ast.Name) \
                    and stmt.value.value.id == msg \
                    and _const_str(stmt.value.slice) == "cmd":
                cmd_var = stmt.targets[0].id
        fn_name = f"{self.cls.name}._handle"
        for stmt in handle.body:
            arm_cmds = self._arm_cmds(stmt, cmd_var)
            if arm_cmds is None:
                # preamble/epilogue: envelope reads (deadline anchoring,
                # trace context) apply to every cmd
                req: Set[str] = set()
                con: Set[str] = set()
                opt: Set[str] = set()
                self._reads([stmt], msg, False, req, con, opt, ())
                self._scan_expr(stmt, msg, False, req, con, opt, (),
                                headers_only=False)
                model.envelope_read |= req | con | opt
                continue
            req, con, opt = set(), set(), set()
            self._scan_expr(stmt, msg, False, req, con, opt, (),
                            headers_only=True)
            self._reads(stmt.body, msg, False, req, con, opt, ())
            for c in arm_cmds:
                model.handlers[c] = HandlerArm(
                    c, self.sf.rel, stmt.lineno, fn_name,
                    set(req), set(con), set(opt))
        # the server preamble outside _handle (_serve_conn's trace
        # context peek on the freshly-received frame)
        self._serve_conn_reads(model)

    def _arm_cmds(self, stmt: ast.stmt,
                  cmd_var: Optional[str]) -> Optional[List[str]]:
        if cmd_var is None or not isinstance(stmt, ast.If) \
                or not isinstance(stmt.test, ast.Compare):
            return None
        t = stmt.test
        if not (isinstance(t.left, ast.Name) and t.left.id == cmd_var
                and len(t.ops) == 1):
            return None
        if isinstance(t.ops[0], ast.Eq):
            c = _const_str(t.comparators[0])
            return [c] if c is not None else None
        if isinstance(t.ops[0], ast.In) and \
                isinstance(t.comparators[0], (ast.Tuple, ast.List)):
            out = []
            for el in t.comparators[0].elts:
                c = _const_str(el)
                if c is not None:
                    out.append(c)
            return out or None
        return None

    def _serve_conn_reads(self, model: ProtocolModel) -> None:
        serve = self.methods.get("_serve_conn")
        if serve is None:
            return
        # the received-frame variable: `X = _recv(...)`
        var = None
        for node in ast.walk(serve):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name) \
                    and node.value.func.id == "_recv":
                var = node.targets[0].id
        if var is None:
            return
        req: Set[str] = set()
        con: Set[str] = set()
        opt: Set[str] = set()
        # "_handle" rides the stack so delegation into the dispatcher
        # is NOT followed: its per-arm reads are per-cmd, not envelope
        self._reads(serve.body, var, True, req, con, opt,
                    ("_serve_conn", "_handle"))
        model.envelope_read |= req | con | opt


# ---------------------------------------------------------------------------
# extraction driver
# ---------------------------------------------------------------------------


def extract_model(project: Project,
                  modules: Tuple[str, ...] = SEND_MODULES) -> ProtocolModel:
    model = ProtocolModel()
    wanted = {os.path.normpath(m) for m in modules}
    files = [sf for sf in project.files()
             if os.path.normpath(sf.rel) in wanted]
    for sf in files:
        module_fns = {n.name: n for n in sf.tree.body
                      if isinstance(n, ast.FunctionDef)}
        # the handler class: the one defining _handle
        handler_cls = None
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef) and any(
                    isinstance(m, ast.FunctionDef) and m.name == "_handle"
                    for m in node.body):
                handler_cls = node
                break
        # send sites, function by function (so field additions resolve
        # in their own scope)
        def visit(node, cls_name: Optional[str], in_handler: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name, child is handler_cls)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    fn_name = (f"{cls_name}.{child.name}" if cls_name
                               else child.name)
                    scan = _SendScan(sf, fn_name, in_handler, model)
                    scan.run(child)
                    visit(child, cls_name, in_handler)
                else:
                    visit(child, cls_name, in_handler)

        visit(sf.tree, None, False)
        if handler_cls is not None:
            _HandlerScan(sf, handler_cls, module_fns).arms(model)
    return model


# ---------------------------------------------------------------------------
# serialized model + docs rendering
# ---------------------------------------------------------------------------


def to_wire_model(model: ProtocolModel) -> dict:
    """Deterministic, line-number-free form of the model: what gets
    committed as wire_protocol.json and what the runtime wire witness
    loads. Function-level site names keep the file stable across
    unrelated edits to the protocol modules."""
    cmds: Dict[str, dict] = {}
    for s in sorted(model.senders, key=lambda s: (s.cmd, s.fn, s.line)):
        ent = cmds.setdefault(s.cmd, {"handler": None, "senders": []})
        site = {"fn": s.fn,
                "required": sorted(s.required),
                "optional": sorted(s.optional)}
        if site not in ent["senders"]:
            ent["senders"].append(site)
    for c, h in sorted(model.handlers.items()):
        ent = cmds.setdefault(c, {"handler": None, "senders": []})
        ent["handler"] = {"fn": h.fn,
                          "required": sorted(h.required),
                          "conditional": sorted(h.conditional),
                          "optional": sorted(h.optional)}
    return {
        "schema": MODEL_SCHEMA,
        "envelope": {"sent": sorted(model.envelope_sent),
                     "read": sorted(model.envelope_read)},
        "cmds": {c: cmds[c] for c in sorted(cmds)},
    }


def render_markdown(wire: dict) -> str:
    """docs/WIRE_PROTOCOL.md: the generated wire-protocol reference
    (cmd -> sender sites -> handler -> required/optional fields)."""
    out = [
        "# DCN wire-protocol reference",
        "",
        "**GENERATED** by `scripts/gen_wire_protocol.py` from the static",
        "protocol model (`tidb_tpu/analysis/wire_protocol.py`); the",
        "`protocol-conformance` pass and a tier-1 drift test assert this",
        "file matches a fresh extraction — edit the protocol code, then",
        "regenerate, never edit this file by hand.",
        "",
        "Transport envelope — injected into every message by the",
        "transport layer, consumed by the server preamble:",
        "",
        f"- sent: {', '.join('`%s`' % f for f in wire['envelope']['sent']) or '(none)'}",
        f"- read: {', '.join('`%s`' % f for f in wire['envelope']['read']) or '(none)'}",
        "",
        "| cmd | sender site(s) | handler | required fields | optional fields |",
        "|---|---|---|---|---|",
    ]
    for cmd, ent in wire["cmds"].items():
        senders = ent["senders"]
        h = ent["handler"]
        sender_cell = "<br>".join(
            f"`{s['fn']}`" for s in senders) or "*(none in tree)*"
        if h is None:
            handler_cell, req_cell, opt_cell = "*(no arm)*", "", ""
        else:
            handler_cell = f"`{h['fn']}`"
            req_cell = ", ".join(f"`{f}`" for f in h["required"]) or "—"
            opt = sorted(set(h["optional"]) | set(h["conditional"]))
            opt_cell = ", ".join(f"`{f}`" for f in opt) or "—"
        out.append(f"| `{cmd}` | {sender_cell} | {handler_cell} "
                   f"| {req_cell} | {opt_cell} |")
    out.append("")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


class ProtocolConformancePass(Pass):
    id = "protocol-conformance"
    doc = ("DCN dict protocol statically modeled: senders and handler "
           "arms agree on cmds and fields; worker re-sends propagate "
           "the statement envelope; committed model is drift-checked")

    def __init__(self, modules: Tuple[str, ...] = SEND_MODULES,
                 model_path: Optional[str] = MODEL_REL_PATH,
                 doc_path: Optional[str] = DOC_REL_PATH):
        self.modules = modules
        self.model_path = model_path
        self.doc_path = doc_path

    def run(self, project: Project) -> List[Violation]:
        model = extract_model(project, self.modules)
        out: List[Violation] = list(model.problems)
        out.extend(self._diff(model))
        out.extend(self._drift(project, model))
        return out

    # -- the two-direction diff -------------------------------------------

    def _diff(self, model: ProtocolModel) -> List[Violation]:
        out: List[Violation] = []
        sent_cmds = {s.cmd for s in model.senders}
        # union of reads per cmd (for the dead-field direction)
        for s in model.senders:
            h = model.handlers.get(s.cmd)
            if h is None:
                out.append(Violation(
                    self.id, s.path, s.line,
                    f"cmd {s.cmd!r} is sent here but Worker._handle has "
                    "no arm for it — the worker raises `unknown dcn "
                    "command` at runtime"))
                continue
            for f in sorted(h.required - s.required):
                out.append(Violation(
                    self.id, s.path, s.line,
                    f"send site of {s.cmd!r} omits field {f!r} that the "
                    f"handler ({h.fn}) reads unconditionally — a remote "
                    "KeyError on the worker"))
            reads = h.reads() | model.envelope_read
            for f in sorted(s.fields() - reads):
                out.append(Violation(
                    self.id, s.path, s.line,
                    f"field {f!r} of {s.cmd!r} is sent but no handler "
                    "read ever touches it — dead wire bytes (delete it, "
                    "or the handler forgot to consume it)"))
            if s.in_handler_class:
                # transport-level injection (_call's trace context)
                # does NOT exempt worker re-sends: peer hops ride
                # _peer_call/_send, which inject nothing — the fields
                # must be on the literal (or its same-scope additions)
                missing = [f for f in ENVELOPE_REQUIRED
                           if f not in s.fields()]
                if missing:
                    out.append(Violation(
                        self.id, s.path, s.line,
                        f"worker-side re-send of {s.cmd!r} does not "
                        "propagate the statement envelope "
                        f"({', '.join(missing)}): a fan-out hop must "
                        "carry the coordinator's trace context and "
                        "remaining deadline (ISSUE 14 rule)"))
        for c, h in sorted(model.handlers.items()):
            if c not in sent_cmds:
                out.append(Violation(
                    self.id, h.path, h.line,
                    f"handler arm for {c!r} has no send site in the "
                    "protocol modules — dead arm (delete it, or "
                    "suppress with the out-of-tree caller as the "
                    "reason)"))
        # envelope fields nobody reads anywhere are dead on EVERY wire
        # message
        all_reads = model.envelope_read | {
            f for h in model.handlers.values() for f in h.reads()}
        for f in sorted(model.envelope_sent - all_reads):
            out.append(Violation(
                self.id, self.modules[0], 1,
                f"transport-injected envelope field {f!r} is read by "
                "no handler or server preamble — dead wire bytes on "
                "every message"))
        return out

    # -- drift vs the committed artifacts ---------------------------------

    def _drift(self, project: Project,
               model: ProtocolModel) -> List[Violation]:
        out: List[Violation] = []
        if self.model_path is None:
            return out
        wire = to_wire_model(model)
        path = os.path.join(project.root, self.model_path)
        try:
            with open(path, encoding="utf-8") as f:
                committed = json.load(f)
        except (OSError, ValueError):
            committed = None
        if committed != wire:
            out.append(Violation(
                self.id, self.model_path, 1,
                "committed wire-protocol model does not match a fresh "
                "extraction — run `python scripts/gen_wire_protocol.py` "
                "and commit the result (the runtime wire witness diffs "
                "real traffic against this file; it must never rot)"))
        if self.doc_path is not None:
            doc = os.path.join(project.root, self.doc_path)
            try:
                with open(doc, encoding="utf-8") as f:
                    have = f.read()
            except OSError:
                have = None
            if have != render_markdown(wire):
                out.append(Violation(
                    self.id, self.doc_path, 1,
                    "generated wire-protocol reference is stale — run "
                    "`python scripts/gen_wire_protocol.py`"))
        return out
