"""Pass driver: source model, suppression parsing, violation report.

Design mirrors the two proven single-purpose checkers
(scripts/check_metrics.py, scripts/check_failpoints.py), generalized:
a ``Project`` lazily parses every ``tidb_tpu/`` module once; each
``Pass`` walks the shared ASTs and returns ``Violation``s; the
``Driver`` applies the suppression rules and renders one report.

Everything here is stdlib-only (ast + tokenize) — the analyzer must
never import the engine's device stack (jax) so a full run stays well
under the tier-1 10s budget.  The registry passes that DO need a live
import (metrics rendering) import only leaf modules.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["Violation", "SourceFile", "Project", "Pass", "Driver",
           "all_passes"]

# grammar (see package docstring): lint disables carry the pass list
# and a `--`-separated reason; host-sync annotations carry a reason;
# lifecycle annotations (ISSUE 12) document an acquire whose release
# deliberately lives elsewhere (ownership handoff) — same shape as
# host-sync: `# lifecycle: <why the release is guaranteed elsewhere>`
_DISABLE_RE = re.compile(
    r"#\s*lint:\s*(module-)?disable=([a-z0-9_,-]+)\s*(?:--\s*(.*))?$")
_HOST_SYNC_RE = re.compile(r"#\s*host-sync:\s*(.*)$")
_LIFECYCLE_RE = re.compile(r"#\s*lifecycle:\s*(.*)$")


@dataclass
class Violation:
    pass_id: str
    path: str          # repo-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


@dataclass
class Suppression:
    pass_id: str
    path: str
    line: int          # line the comment sits on
    target: int        # code line the directive governs
    reason: str
    module_wide: bool = False
    used: bool = False


class SourceFile:
    """One parsed module: text, AST, and its comment directives."""

    def __init__(self, root: str, path: str):
        self.root = root
        self.path = path                       # absolute
        self.rel = os.path.relpath(path, root)
        with open(path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.rel)
        self.suppressions: List[Suppression] = []
        self.host_sync_notes: Dict[int, str] = {}   # line -> reason
        self.lifecycle_notes: Dict[int, str] = {}   # line -> reason
        # line -> innermost statement span (start, end): a directive
        # trailing a multi-line statement must govern the whole
        # statement, not just the physical line the comment sits on
        self._spans: Dict[int, Tuple[int, int]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.stmt) and node.end_lineno is not None:
                span = (node.lineno, node.end_lineno)
                for ln in range(span[0], span[1] + 1):
                    prev = self._spans.get(ln)
                    if prev is None or \
                            span[1] - span[0] < prev[1] - prev[0]:
                        self._spans[ln] = span
        self._parse_comments()

    def _same_stmt(self, a: int, b: int) -> bool:
        if a == b:
            return True
        sa = self._spans.get(a)
        return sa is not None and sa == self._spans.get(b)

    def _parse_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            comments = [(t.start[0], t.string) for t in toks
                        if t.type == tokenize.COMMENT]
        except tokenize.TokenError:
            comments = []
        for line, text in comments:
            m = _DISABLE_RE.search(text)
            if m:
                module_wide = bool(m.group(1))
                reason = self._absorb_reason(
                    (m.group(3) or "").strip(), line)
                target = self._target_line(line)
                for pid in m.group(2).split(","):
                    self.suppressions.append(Suppression(
                        pid.strip(), self.rel, line, target, reason,
                        module_wide=module_wide))
                continue
            m = _HOST_SYNC_RE.search(text)
            if m:
                self.host_sync_notes[self._target_line(line)] = \
                    self._absorb_reason(m.group(1).strip(), line)
                continue
            m = _LIFECYCLE_RE.search(text)
            if m:
                self.lifecycle_notes[self._target_line(line)] = \
                    self._absorb_reason(m.group(1).strip(), line)

    def _absorb_reason(self, reason: str, line: int) -> str:
        """A standalone directive's reason may wrap onto following
        comment-only lines; join them so the rendered report carries
        the whole sentence, not its first fragment."""
        before = (self.lines[line - 1].split("#", 1)[0]
                  if line <= len(self.lines) else "")
        if before.strip():
            return reason  # trailing form: one line by definition
        for ln in range(line + 1, len(self.lines) + 1):
            text = self.lines[ln - 1].strip()
            if not text.startswith("#"):
                break
            if _DISABLE_RE.search(text) or _HOST_SYNC_RE.search(text) \
                    or _LIFECYCLE_RE.search(text):
                break  # a new directive starts its own reason
            reason = f"{reason} {text.lstrip('#').strip()}".strip()
        return reason

    def _target_line(self, line: int) -> int:
        """The code line a comment directive governs: its own line when
        the comment trails code, else the next non-comment code line
        (a standalone directive may wrap onto continuation comments)."""
        text = self.lines[line - 1] if line <= len(self.lines) else ""
        before = text.split("#", 1)[0]
        if before.strip():
            return line
        for ln in range(line + 1, len(self.lines) + 1):
            stripped = self.lines[ln - 1].strip()
            if stripped and not stripped.startswith("#"):
                return ln
        return line

    def suppression_for(self, pass_id: str, line: int
                        ) -> Optional[Suppression]:
        """A directive suppresses violations on the code line it
        governs (trailing-comment line, or the statement following a
        standalone comment) — or anywhere in that line's statement,
        so a directive trailing a wrapped call still covers a
        violation anchored to the call's first line — or module-wide."""
        for s in self.suppressions:
            if s.pass_id != pass_id:
                continue
            if s.module_wide or self._same_stmt(s.target, line):
                return s
        return None

    def host_sync_note(self, line: int) -> Optional[Tuple[int, str]]:
        if line in self.host_sync_notes:
            return line, self.host_sync_notes[line]
        for ln, reason in self.host_sync_notes.items():
            if self._same_stmt(ln, line):
                return ln, reason
        return None

    def lifecycle_note(self, line: int) -> Optional[Tuple[int, str]]:
        """`# lifecycle:` handoff annotation governing `line` (same
        statement-span rules as host_sync_note)."""
        if line in self.lifecycle_notes:
            return line, self.lifecycle_notes[line]
        for ln, reason in self.lifecycle_notes.items():
            if self._same_stmt(ln, line):
                return ln, reason
        return None


class Project:
    """Lazily-parsed view of the repo: every .py under <root>/tidb_tpu.

    ``restrict`` (repo-relative paths) narrows the listing to a changed
    subset — the ``--changed`` incremental mode parses (and checks) only
    those files, which is what makes a diff lint land in well under a
    second for the builder loop."""

    def __init__(self, root: str, restrict: Optional[List[str]] = None):
        self.root = os.path.abspath(root)
        self._files: Dict[str, SourceFile] = {}
        self._listing: Optional[List[str]] = None
        self.restrict = (None if restrict is None else
                         {os.path.normpath(r) for r in restrict})

    def paths(self) -> List[str]:
        if self._listing is None:
            out = []
            pkg = os.path.join(self.root, "tidb_tpu")
            for dirpath, dirnames, filenames in os.walk(pkg):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                out.extend(os.path.join(dirpath, f)
                           for f in filenames if f.endswith(".py"))
            if self.restrict is not None:
                out = [p for p in out
                       if os.path.normpath(os.path.relpath(p, self.root))
                       in self.restrict]
            self._listing = sorted(out)
        return self._listing

    def file(self, path: str) -> SourceFile:
        sf = self._files.get(path)
        if sf is None:
            sf = self._files[path] = SourceFile(self.root, path)
        return sf

    def files(self) -> List[SourceFile]:
        return [self.file(p) for p in self.paths()]

    def files_under(self, *subdirs: str) -> List[SourceFile]:
        wanted = tuple(os.path.join("tidb_tpu", d) + os.sep
                       for d in subdirs)
        return [sf for sf in self.files()
                if sf.rel.startswith(wanted)]


class Pass:
    """One invariant: ``run(project)`` returns raw (pre-suppression)
    violations. ``id`` is the name used in suppression directives."""

    id = "base"
    doc = ""

    def run(self, project: Project) -> List[Violation]:  # pragma: no cover
        raise NotImplementedError


@dataclass
class PassReport:
    pass_id: str
    violations: List[Violation] = field(default_factory=list)   # unsuppressed
    suppressed: List[Tuple[Violation, Suppression]] = field(
        default_factory=list)
    problems: List[Violation] = field(default_factory=list)     # bad directives
    seconds: float = 0.0        # wall clock of this pass's run()


class Driver:
    """Run passes, apply suppressions, render the report."""

    def __init__(self, root: str, passes: Optional[List[Pass]] = None,
                 changed: Optional[List[str]] = None):
        self.project = Project(root, restrict=changed)
        self.passes = passes if passes is not None else all_passes()

    def run(self) -> List[PassReport]:
        import time as _time

        reports = []
        # directives are validated against the FULL pass registry, not
        # just the selected subset — `--pass error-shape` must not
        # misreport every jit-hygiene suppression as unknown
        known = {p.id for p in all_passes()} | {p.id for p in self.passes}
        for p in self.passes:
            rep = PassReport(p.id)
            t0 = _time.perf_counter()
            for v in p.run(self.project):
                sf = self._file_for(v)
                sup = sf.suppression_for(p.id, v.line) if sf else None
                if sup is not None:
                    sup.used = True
                    rep.suppressed.append((v, sup))
                else:
                    rep.violations.append(v)
            rep.seconds = _time.perf_counter() - t0
            reports.append(rep)
        # directive hygiene rides the first report: a suppression that
        # names no reason, an unknown pass id, or a line-level directive
        # that no longer suppresses anything (the flagged code was fixed
        # or the target line drifted) is itself a violation. Module-wide
        # disables are exempt from staleness — they are prophylactic
        # (e.g. a bench file that happens to be clean today).
        selected = {p.id for p in self.passes}
        hygiene = PassReport("suppressions")
        for sf in self.project.files():
            for s in sf.suppressions:
                if s.pass_id not in known:
                    hygiene.problems.append(Violation(
                        "suppressions", sf.rel, s.line,
                        f"unknown pass {s.pass_id!r} in lint directive"))
                if not s.reason:
                    hygiene.problems.append(Violation(
                        "suppressions", sf.rel, s.line,
                        "suppression without a reason "
                        "(use `-- <why>` after the pass list)"))
                if (not s.module_wide and not s.used
                        and s.pass_id in selected):
                    hygiene.problems.append(Violation(
                        "suppressions", sf.rel, s.line,
                        f"stale suppression: no {s.pass_id} violation on "
                        "the governed line — delete the directive (or "
                        "re-anchor it; a refactor may have moved the "
                        "code it covered)"))
            for line, reason in sf.host_sync_notes.items():
                if not reason:
                    hygiene.problems.append(Violation(
                        "suppressions", sf.rel, line,
                        "host-sync annotation without a reason"))
            for line, reason in sf.lifecycle_notes.items():
                if not reason:
                    hygiene.problems.append(Violation(
                        "suppressions", sf.rel, line,
                        "lifecycle annotation without a reason"))
        reports.append(hygiene)
        return reports

    def _file_for(self, v: Violation) -> Optional[SourceFile]:
        path = os.path.join(self.project.root, v.path)
        try:
            return self.project.file(path)
        except (OSError, SyntaxError):
            return None

    @staticmethod
    def render(reports: List[PassReport]) -> Tuple[str, int]:
        """-> (text, exit_code)."""
        out: List[str] = []
        bad = 0
        n_sup = 0
        for rep in reports:
            issues = rep.violations + rep.problems
            if issues:
                bad += len(issues)
                out.append(f"[{rep.pass_id}] {len(issues)} violation(s):")
                out.extend(f"  {v.render()}" for v in issues)
            n_sup += len(rep.suppressed)
            for v, s in rep.suppressed:
                out.append(f"[{rep.pass_id}] suppressed at {v.path}:{v.line}"
                           f" -- {s.reason}")
        status = ("FAILED" if bad else "ok")
        out.append(f"invariants {status}: {bad} violation(s), "
                   f"{n_sup} suppressed (each with a recorded reason)")
        return "\n".join(out), (1 if bad else 0)

    # JSON report schema version: bump on breaking shape changes — the
    # builder loop and tier-1 round-trip test both pin it
    JSON_SCHEMA = 1

    def to_json(self, reports: List[PassReport]) -> dict:
        """Machine-readable report: violations, suppressions, per-pass
        timings, and the annotated-allowlist counts (host-sync syncs +
        lifecycle handoffs — annotations are allowlist entries exactly
        like suppressions, so drift in them must be machine-visible
        too). The shape round-trips through json (tier-1 asserted) so
        external builder loops can consume it without scraping."""
        def _viol(v: Violation) -> dict:
            return {"pass": v.pass_id, "path": v.path.replace(os.sep, "/"),
                    "line": v.line, "message": v.message}

        passes = []
        n_bad = 0
        n_sup = 0
        for rep in reports:
            issues = rep.violations + rep.problems
            n_bad += len(issues)
            n_sup += len(rep.suppressed)
            passes.append({
                "id": rep.pass_id,
                "seconds": round(rep.seconds, 4),
                "violations": [_viol(v) for v in rep.violations],
                "problems": [_viol(v) for v in rep.problems],
                "suppressed": [
                    {"pass": rep.pass_id,
                     "path": v.path.replace(os.sep, "/"), "line": v.line,
                     "reason": s.reason} for v, s in rep.suppressed],
            })
        from tidb_tpu.analysis.host_sync import annotated_sites
        from tidb_tpu.analysis.registry import (elastic_surfaces,
                                                observability_surfaces,
                                                plan_feedback_surfaces)
        from tidb_tpu.analysis.resource_lifecycle import lifecycle_sites

        return {
            "schema": Driver.JSON_SCHEMA,
            "ok": n_bad == 0,
            "violation_count": n_bad,
            "suppression_count": n_sup,
            "host_sync_annotation_count": len(annotated_sites(self.project)),
            "lifecycle_annotation_count": len(lifecycle_sites(self.project)),
            # ISSUE 15: the plan-feedback layer's user-visible surfaces
            # (I_S table, endpoint, metric, sysvar, EXPLAIN drift
            # column, slow-log column) counted statically — drift here
            # means a surface was silently dropped in a refactor
            "plan_feedback_surface_count":
                len(plan_feedback_surfaces(self.project)),
            # ISSUE 16: the observability plane's user-visible surfaces
            # (cluster_metrics/digest_latency I_S tables, scope=cluster
            # render, /slo endpoint, metrics_snapshot cmd, profile
            # columns, SLO sysvars/consumer) counted the same way
            "observability_surface_count":
                len(observability_surfaces(self.project)),
            # ISSUE 19: the elastic-topology plane's surfaces (online
            # reshard + recovery, membership lifecycle, cluster_info
            # I_S table, reshard/membership metrics, gate sysvar)
            # counted the same way
            "elastic_surface_count":
                len(elastic_surfaces(self.project)),
            "passes": passes,
        }


# AST-only passes (no live engine import): the set the --changed
# incremental mode runs over a diff — the registry passes need the whole
# tree (a changed subset can't prove sysvar/metric coverage either way),
# and protocol-conformance is registry-shaped too: a diff that excludes
# dcn.py would see a protocol with no handler and flag everything
AST_PASS_IDS = ("jit-hygiene", "host-sync", "lock-discipline",
                "resource-lifecycle", "blocking-under-lock",
                "cache-key-completeness", "error-shape")


def all_passes() -> List[Pass]:
    from tidb_tpu.analysis.blocking_under_lock import BlockingUnderLockPass
    from tidb_tpu.analysis.cache_key import CacheKeyCompletenessPass
    from tidb_tpu.analysis.error_shape import ErrorShapePass
    from tidb_tpu.analysis.host_sync import HostSyncPass
    from tidb_tpu.analysis.jit_hygiene import JitHygienePass
    from tidb_tpu.analysis.lock_discipline import LockDisciplinePass
    from tidb_tpu.analysis.registry import (
        FailpointCoveragePass,
        MetricsCoveragePass,
        SysvarCoveragePass,
    )
    from tidb_tpu.analysis.resource_lifecycle import ResourceLifecyclePass
    from tidb_tpu.analysis.wire_protocol import ProtocolConformancePass

    return [
        JitHygienePass(),
        HostSyncPass(),
        LockDisciplinePass(),
        ResourceLifecyclePass(),
        BlockingUnderLockPass(),
        ProtocolConformancePass(),
        CacheKeyCompletenessPass(),
        MetricsCoveragePass(),
        FailpointCoveragePass(),
        SysvarCoveragePass(),
        ErrorShapePass(),
    ]
