"""ctypes loader for the native TPC-H generator (native/tpch_gen.cpp).

Builds the shared library on demand (g++ is part of the toolchain; no
pybind11 in this image, so the boundary is a plain C ABI over int64
buffers). Returns None when the toolchain or build is unavailable — the
numpy generator in tpch.py is the fallback and the oracle.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

__all__ = ["load_native", "native_orders_lineitem"]

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRC = os.path.join(_NATIVE_DIR, "tpch_gen.cpp")
_LIB = os.path.join(_NATIVE_DIR, "libtpchgen.so")

_lib = None
_load_failed = False


def load_native() -> Optional[ctypes.CDLL]:
    """Build (if stale) and load the generator library; None on failure."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    try:
        if not os.path.exists(_SRC):
            raise FileNotFoundError(_SRC)
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            try:  # one build recipe: the Makefile (honors CXX/CXXFLAGS)
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR, "libtpchgen.so"],
                    check=True, capture_output=True, timeout=120,
                )
            except (FileNotFoundError, subprocess.CalledProcessError):
                subprocess.run(  # make absent: the Makefile's default recipe
                    ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-Wall",
                     "-o", _LIB, _SRC],
                    check=True, capture_output=True, timeout=120,
                )
        lib = ctypes.CDLL(_LIB)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.tpch_sizes.argtypes = [ctypes.c_double, ctypes.c_uint64, i64p, i64p]
        lib.tpch_sizes.restype = None
        lib.tpch_gen.argtypes = (
            [ctypes.c_double, ctypes.c_uint64]
            + [ctypes.c_int64] * 4
            + [i64p] * 25
        )
        lib.tpch_gen.restype = None
        _lib = lib
        return _lib
    except Exception:  # noqa: BLE001 — fall back to the numpy generator
        _load_failed = True
        return None


def native_orders_lineitem(sf: float, seed: int, npart: int, nsupp: int,
                           ncust: int, nclerk: int):
    """Generate orders+lineitem columns natively. Returns (orders dict,
    lineitem dict) of int64 numpy arrays, or None if unavailable."""
    import numpy as np

    lib = load_native()
    if lib is None:
        return None
    no = ctypes.c_int64()
    nl = ctypes.c_int64()
    lib.tpch_sizes(sf, seed, ctypes.byref(no), ctypes.byref(nl))
    no, nl = no.value, nl.value

    def buf(n):
        return np.zeros(n, dtype=np.int64)

    o = {k: buf(no) for k in (
        "o_orderkey", "o_custkey", "o_totalprice", "o_orderdate",
        "o_shippriority", "o_status_code", "o_priority_code",
        "o_clerk_code", "o_comment_code")}
    l = {k: buf(nl) for k in (
        "l_orderkey", "l_partkey", "l_suppkey", "l_linenumber",
        "l_quantity", "l_extendedprice", "l_discount", "l_tax",
        "l_returnflag_code", "l_linestatus_code", "l_shipdate",
        "l_commitdate", "l_receiptdate", "l_instruct_code",
        "l_shipmode_code", "l_comment_code")}

    def p(a):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))

    lib.tpch_gen(
        sf, seed, npart, nsupp, ncust, nclerk,
        p(o["o_orderkey"]), p(o["o_custkey"]), p(o["o_totalprice"]),
        p(o["o_orderdate"]), p(o["o_shippriority"]), p(o["o_status_code"]),
        p(o["o_priority_code"]), p(o["o_clerk_code"]), p(o["o_comment_code"]),
        p(l["l_orderkey"]), p(l["l_partkey"]), p(l["l_suppkey"]),
        p(l["l_linenumber"]), p(l["l_quantity"]), p(l["l_extendedprice"]),
        p(l["l_discount"]), p(l["l_tax"]), p(l["l_returnflag_code"]),
        p(l["l_linestatus_code"]), p(l["l_shipdate"]), p(l["l_commitdate"]),
        p(l["l_receiptdate"]), p(l["l_instruct_code"]), p(l["l_shipmode_code"]),
        p(l["l_comment_code"]),
    )
    return o, l
