"""The layered storage-engine boundary.

Ref counterpart: the reference's kv/ abstraction — its SQL layer talks
to a pluggable Storage (TiKV / mockstore / unistore) through one
interface, so engines swap without the layers above noticing. Here the
swap point is the TABLE ENGINE behind the catalog: everything above
(planner, executors, txn layer, DDL, statistics) reaches tables only
through the surface named by `TABLE_ENGINE_API`, so an object providing
that surface is a storage engine, full stop.

Two engines ship:
  * ``columnar`` — `storage.table.Table`: read-optimized dense columnar
    arrays with MVCC version ranges (the default; what the TPU scan
    kernels want).
  * ``delta`` — `storage.delta.DeltaTable`: write-optimized memtable +
    columnar base (the TiFlash delta-tree shape). Row-at-a-time INSERTs
    buffer as converted host rows — deferring the per-statement
    dictionary merge and columnar append that make string-heavy trickle
    ingest O(n) per row — and compact into the base in one bulk append
    on any read (or at the row threshold).

CREATE TABLE ... ENGINE=delta selects the engine per table;
`make_table` is the factory the catalog calls.
"""

from __future__ import annotations

from tidb_tpu.errors import SchemaError

# The executor/planner/txn-facing surface of a table engine. This is a
# NAMED CONTRACT (kept in sync by tests/test_engines.py::test_contract):
# a new engine must provide every attribute here with Table's semantics.
TABLE_ENGINE_API = frozenset({
    # identity / shape
    "schema", "n", "version", "live_rows", "engine",
    # columnar payload access (scan surface)
    "data", "valid", "dicts", "column_slice", "live_mask",
    # MVCC metadata
    "begin_ts", "end_ts",
    # write surface
    "insert_rows", "insert_columns", "ingest_encoded", "update_rows",
    "truncate",
    # txn lifecycle
    "txn_commit", "txn_rollback",
    # indexes / point access
    "indexes", "index_lookup", "create_index", "drop_index",
    # maintenance
    "gc", "add_column", "drop_column", "modify_count",
    "maintenance_stats",
})

ENGINES = ("columnar", "delta")


def make_table(schema, engine=None):
    """Factory for the per-table storage engine (the catalog's single
    construction point; ref: kv.Storage selection at startup)."""
    from tidb_tpu.storage.delta import DeltaTable
    from tidb_tpu.storage.table import Table

    eng = (engine or "columnar").lower()
    if eng in ("columnar", "innodb", "tiflash"):  # accepted aliases
        return Table(schema)
    if eng == "delta":
        return DeltaTable(Table(schema))
    raise SchemaError(f"unknown storage engine {engine!r} "
                      f"(supported: {', '.join(ENGINES)})")


def conforms(table) -> list:
    """Names from TABLE_ENGINE_API the object is missing (empty = a
    valid engine)."""
    return sorted(n for n in TABLE_ENGINE_API if not hasattr(table, n))
