"""Host-side storage layer (ref: kv/, store/mockstore, table/, meta/).

The reference keeps durable data in TiKV (reached over gRPC) and stands it
in with an in-process mock for tests. Here the storage tier is host
columnar partitions feeding the device:

  table.py    -- TableSchema + Table: append-only columnar segments with a
                 tombstone mask (delete) and in-place update; per-string-
                 column sorted dictionaries; partition slicing for chips
  catalog.py  -- databases -> tables; DDL entry points; schema versioning

A C++ native engine (native/) can back Table's column buffers; the numpy
implementation is the reference semantics and the test stand-in (the
mockstore role).
"""

from tidb_tpu.storage.table import ColumnInfo, Table, TableSchema
from tidb_tpu.storage.catalog import Catalog, Database

__all__ = ["ColumnInfo", "Table", "TableSchema", "Catalog", "Database"]
