"""Catalog: databases -> tables (ref: infoschema/ + meta/ + ddl DDL entry).

In-memory, schema-versioned. DDL here is synchronous (the reference's
online multi-phase schema change exists because many stateless SQL nodes
share storage; a single-process engine can flip schema atomically — the
schema_version counter preserves the observable contract that sessions can
detect schema changes)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tidb_tpu.errors import DuplicateTableError, SchemaError
from tidb_tpu.storage.table import ColumnInfo, Table, TableSchema

__all__ = ["Database", "Catalog"]


@dataclass
class Database:
    name: str
    tables: Dict[str, Table] = field(default_factory=dict)


class Catalog:
    def __init__(self):
        import threading

        # statement-granularity lock for multi-threaded front-ends (the wire
        # server): the host storage layer is single-writer by design, like
        # the reference's per-region leaseholder
        self.lock = threading.RLock()
        self.databases: Dict[str, Database] = {"test": Database("test")}
        self.schema_version = 0
        # cluster-wide GLOBAL sysvars (ref: mysql.global_variables)
        self.global_vars: Dict[str, object] = {}
        # timestamp oracle + txn id allocator (ref: PD TSO; monotonically
        # increasing, shared by every table in this catalog)
        self._ts = 0
        self._txn_id = 0

    def next_ts(self) -> int:
        self._ts += 1
        return self._ts

    @property
    def current_ts(self) -> int:
        return self._ts

    def next_txn_id(self) -> int:
        self._txn_id += 1
        return self._txn_id

    # -- databases ---------------------------------------------------------

    def create_database(self, name: str, if_not_exists: bool = False):
        if name in self.databases:
            if if_not_exists:
                return
            raise DuplicateTableError(f"database {name!r} exists")
        self.databases[name] = Database(name)
        self.schema_version += 1

    def drop_database(self, name: str, if_exists: bool = False):
        if name not in self.databases:
            if if_exists:
                return
            raise SchemaError(f"no database {name!r}")
        del self.databases[name]
        self.schema_version += 1

    def database(self, name: str) -> Database:
        db = self.databases.get(name)
        if db is None:
            raise SchemaError(f"no database {name!r}")
        return db

    # -- tables ------------------------------------------------------------

    def create_table(self, db: str, schema: TableSchema, if_not_exists: bool = False) -> Table:
        d = self.database(db)
        if schema.name in d.tables:
            if if_not_exists:
                return d.tables[schema.name]
            raise DuplicateTableError(f"table {schema.name!r} exists")
        t = Table(schema)
        t.ts_source = self.next_ts
        d.tables[schema.name] = t
        self.schema_version += 1
        return t

    def drop_table(self, db: str, name: str, if_exists: bool = False):
        d = self.database(db)
        if name not in d.tables:
            if if_exists:
                return
            raise SchemaError(f"no table {db}.{name}")
        del d.tables[name]
        self.schema_version += 1

    def table(self, db: str, name: str) -> Table:
        d = self.database(db)
        t = d.tables.get(name)
        if t is None:
            raise SchemaError(f"no table {db}.{name}")
        return t

    def has_table(self, db: str, name: str) -> bool:
        return name in self.databases.get(db, Database(db)).tables

    def tables(self, db: str) -> List[str]:
        return sorted(self.database(db).tables.keys())

    def rename_table(self, db: str, old: str, new: str):
        d = self.database(db)
        if old not in d.tables:
            raise SchemaError(f"no table {db}.{old}")
        if new in d.tables:
            raise DuplicateTableError(f"table {new!r} exists")
        t = d.tables.pop(old)
        t.schema.name = new
        d.tables[new] = t
        self.schema_version += 1
