"""Catalog: databases -> tables (ref: infoschema/ + meta/ + ddl DDL entry).

In-memory, schema-versioned. DDL here is synchronous (the reference's
online multi-phase schema change exists because many stateless SQL nodes
share storage; a single-process engine can flip schema atomically — the
schema_version counter preserves the observable contract that sessions can
detect schema changes)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tidb_tpu.errors import DuplicateTableError, ExecutionError, SchemaError
from tidb_tpu.storage.table import ColumnInfo, Table, TableSchema

__all__ = ["Database", "Catalog"]


@dataclass
class Database:
    name: str
    tables: Dict[str, Table] = field(default_factory=dict)
    # views: name -> (explicit column names or None, SELECT ast, sql text)
    views: Dict[str, tuple] = field(default_factory=dict)


class Catalog:
    def __init__(self):
        # statement-granularity lock for multi-threaded front-ends (the wire
        # server): the host storage layer is single-writer by design, like
        # the reference's per-region leaseholder. Registered with the
        # sanitizer's runtime lock-order witness (ISSUE 12).
        from tidb_tpu.analysis import sanitizer as _san

        self.lock = _san.tracked_lock("Catalog.lock", threading.RLock)
        self.databases: Dict[str, Database] = {"test": Database("test")}
        # extension points (ref: plugin/ — per-process plugin list)
        from tidb_tpu.plugin import PluginRegistry

        self.plugins = PluginRegistry()
        # global plan bindings (ref: bindinfo — mysql.bind_info)
        from tidb_tpu.bindinfo import BindHandle

        self.bind_handle = BindHandle("global")
        # DDL owner election + job queue (ref: owner/ + ddl/ job rows);
        # workers register per server instance — empty means inline DDL
        from tidb_tpu.owner import Election

        self.ddl_owner = Election()
        self.ddl_workers: Dict[str, object] = {}
        self._ddl_jobs: list = []
        self._ddl_job_id = 0
        self._ddl_qlock = threading.Lock()
        self.schema_version = 0
        # cluster-wide GLOBAL sysvars (ref: mysql.global_variables)
        self.global_vars: Dict[str, object] = {}
        # timestamp oracle + txn id allocator (ref: PD TSO; monotonically
        # increasing, shared by every table in this catalog)
        self._ts = 0
        self._txn_id = 0
        # open transactions: marker -> read_ts (drives the GC safepoint)
        self._open_txns: Dict[int, int] = {}
        # 2PC status records: marker -> ("committed", ts) | ("aborted", 0)
        # present only between commit/abort point and secondary completion
        self._txn_status: Dict[int, tuple] = {}
        # user accounts: name -> mysql_native_password stage-2 hash
        # (SHA1(SHA1(password)), like mysql.user.authentication_string);
        # "" means empty password. Ref: privilege/'s MySQLPrivilege.
        self.users: Dict[str, bytes] = {"root": b""}
        from tidb_tpu.privilege import Privileges

        self.privileges = Privileges()
        # recent slow statements, surfaced via
        # information_schema.slow_query (ref: the slow-query log +
        # INFORMATION_SCHEMA.SLOW_QUERY)
        from collections import deque

        self.slow_queries = deque(maxlen=128)
        # per-digest statement aggregates, surfaced via
        # information_schema.statements_summary and /statements (ref:
        # the statements-summary tables fed by stmtsummary/)
        from tidb_tpu.utils.stmtsummary import StmtSummary

        self.stmt_summary = StmtSummary()
        # instance-wide digest-keyed plan cache (ref: the prepared plan
        # cache + tidb_enable_non_prepared_plan_cache); sessions probe
        # it from _run_select. Imported lazily: planner pulls in the
        # whole optimizer stack at import time.
        from tidb_tpu.planner.plancache import PlanCache

        self.plan_cache = PlanCache()
        # live sessions for SHOW PROCESSLIST / KILL (ref: server/'s
        # connection registry); weak values — a dropped session vanishes
        import weakref

        self.processes = weakref.WeakValueDictionary()
        self._conn_id = 0
        self._conn_id_lock = threading.Lock()
        # lock-free reader registry (ISSUE 18 recluster): autocommit
        # SELECTs never enter _open_txns, yet a CLUSTER BY permute moves
        # the physical rows they read without any lock. Statements
        # register their execution window here (reader_enter/exit), scan
        # executors and paged cursors additionally count open scans
        # (scan_enter/exit — a DCN cursor outlives its statement), and
        # recluster runs ONLY while this registry is quiescent, holding
        # _readers_lock so no new reader can start mid-permute. Order:
        # Catalog.lock -> Catalog.readers, both leaf-short except the
        # permute itself (the intended compaction pause).
        self._readers_lock = _san.tracked_lock(
            "Catalog.readers", threading.Lock)
        self._stmt_readers: Dict[int, int] = {}  # thread ident -> depth
        self._open_scans = 0
        # SegmentStores whose CLUSTER BY permute is due; performed at
        # the next quiescent statement boundary (run_pending_reclusters)
        self._recluster_pending: list = []

    @property
    def schema_version(self) -> int:
        return self._schema_version

    @schema_version.setter
    def schema_version(self, v: int) -> None:
        self._schema_version = int(v)
        # eager plan-cache invalidation: entries pin table objects (and
        # their column arrays), so waiting for the next cache probe
        # would keep DROPped tables alive indefinitely
        pc = getattr(self, "plan_cache", None)
        if pc is not None:
            pc.on_schema_change(self._schema_version)
        # the device buffer cache pins table objects the same way plan
        # cache entries do — a schema change clears it just as eagerly
        # (lazy import: the catalog must stay importable without jax)
        import sys

        # getattr-guarded: sys.modules can surface a module ANOTHER
        # thread is mid-importing (the dict entry lands before the body
        # finishes); a missing global just means the cache doesn't
        # exist yet — nothing to invalidate
        pipe = sys.modules.get("tidb_tpu.executor.pipeline")
        cache = getattr(pipe, "DEVICE_CACHE", None)
        if cache is not None:
            cache.on_schema_change()
        # plan feedback (ISSUE 15): recorded est-vs-actual truth was
        # measured against plans over the OLD schema — same eager
        # invalidation rule (and the same hook) as the plan cache.
        # Lazy like the device cache: the catalog stays importable
        # without pulling the planner stack in.
        fb = sys.modules.get("tidb_tpu.planner.feedback")
        store = getattr(fb, "STORE", None)
        if store is not None:
            store.on_schema_change()

    def processlist_rows(self, viewer_user=None, with_state=False):
        """Live-session rows for SHOW PROCESSLIST and
        information_schema.processlist — ONE implementation so the
        privilege filter and field derivations can never diverge. A
        viewer without the SUPER/PROCESS privilege sees only their own
        threads (MySQL)."""
        import time as _time

        all_users = (viewer_user is None
                     or self.privileges.has(viewer_user, "super"))
        rows = []
        for cid in sorted(self.processes.keys()):
            sess = self.processes.get(cid)
            if sess is None or (not all_users
                                and sess.user != viewer_user):
                continue
            sql_now = getattr(sess, "_current_sql", None)
            row = [cid, sess.user, "localhost", sess.db,
                   "Query" if sql_now else "Sleep",
                   int(_time.time() - sess._current_t0) if sql_now else 0]
            if with_state:
                row.append("" if sql_now else None)
            row.append((sql_now or "")[:100] or None)
            rows.append(tuple(row))
        return rows

    def next_conn_id(self) -> int:
        # its own tiny lock: the catalog statement lock can be held for
        # a whole long statement, and session CREATION must never block
        # behind it (the wire server handshakes on a fresh thread)
        with self._conn_id_lock:
            self._conn_id += 1
            return self._conn_id

    def submit_ddl(self, sql: str, db: str):
        """Enqueue a DDL job for the elected owner's worker."""
        from tidb_tpu.owner import DDLJob

        with self._ddl_qlock:
            self._ddl_job_id += 1
            job = DDLJob(self._ddl_job_id, sql, db)
            self._ddl_jobs.append(job)
        return job

    def next_ddl_job(self, worker_id: str = ""):
        with self._ddl_qlock:
            for j in self._ddl_jobs:
                if j.state == "queued":
                    j.state = "running"  # claimed atomically: a lease
                    # change between campaign() and here must not let
                    # two workers run the same job
                    j.claimed_by = worker_id
                    return j
            # opportunistic pruning of finished history
            self._ddl_jobs = [j for j in self._ddl_jobs if not j.done.is_set()]
        return None

    def reclaim_ddl_jobs(self) -> int:
        """Requeue jobs claimed by a worker that is gone (owner died
        mid-execution; the new owner picks them up)."""
        n = 0
        with self._ddl_qlock:
            for j in self._ddl_jobs:
                if (j.state == "running" and j.claimed_by
                        and j.claimed_by not in self.ddl_workers):
                    j.state = "queued"
                    j.claimed_by = None
                    n += 1
        return n

    def drain_ddl_jobs(self, reason: str) -> None:
        """Fail every unfinished job (no workers remain to run them)."""
        with self._ddl_qlock:
            for j in self._ddl_jobs:
                if not j.done.is_set():
                    j.fail(ExecutionError(reason))
            self._ddl_jobs = []

    def next_ts(self) -> int:
        self._ts += 1
        return self._ts

    @property
    def current_ts(self) -> int:
        return self._ts

    def next_txn_id(self) -> int:
        self._txn_id += 1
        return self._txn_id

    # -- transactions / GC safepoint ---------------------------------------
    # (ref: PD's TSO + GC safepoint advance: the safepoint is the oldest
    # snapshot any open txn can read; versions ended at/below it are dead)

    def begin_txn(self) -> tuple:
        """Allocate (marker, read_ts) and register the txn as open."""
        from tidb_tpu.storage.table import TXN_TS_BASE

        marker = TXN_TS_BASE + self.next_txn_id()
        read_ts = self.current_ts
        self._open_txns[marker] = read_ts
        return marker, read_ts

    def end_txn(self, marker: int) -> None:
        self._open_txns.pop(marker, None)

    # -- lock-free reader registry (CLUSTER BY permute safety) --------------
    # Readers of the live column arrays take no lock (the MVCC design:
    # committed rows are stable under concurrent APPENDS). A physical
    # permute breaks that invariant, so it may only run while nothing is
    # reading: statements bracket themselves with reader_enter/exit, scan
    # executors (and the paged cursors that outlive a statement) with
    # scan_enter/exit, and run_pending_reclusters refuses unless both
    # counts are zero — holding _readers_lock across the permute so no
    # new reader can begin mid-move.

    def reader_enter(self) -> None:
        ident = threading.get_ident()
        with self._readers_lock:
            self._stmt_readers[ident] = self._stmt_readers.get(ident, 0) + 1

    def reader_exit(self) -> None:
        ident = threading.get_ident()
        with self._readers_lock:
            d = self._stmt_readers.get(ident, 0) - 1
            if d <= 0:
                self._stmt_readers.pop(ident, None)
            else:
                self._stmt_readers[ident] = d

    def scan_enter(self) -> None:
        with self._readers_lock:
            self._open_scans += 1

    def scan_exit(self) -> None:
        with self._readers_lock:
            self._open_scans = max(self._open_scans - 1, 0)

    def note_recluster_due(self, store) -> None:
        """A scan noticed a CLUSTER BY permute is due (fold cadence).
        Queue it; the permute runs at a statement boundary, never on the
        reader path that noticed it."""
        with self._readers_lock:
            if store not in self._recluster_pending:
                self._recluster_pending.append(store)

    def run_pending_reclusters(self) -> None:
        """Perform queued CLUSTER BY permutes if the world is quiescent
        (no open txns, no registered statement windows, no open scans).
        Called at statement boundaries with the calling thread NOT
        registered. Stores whose permute still refuses (e.g. another
        session's open txn) stay queued for a later boundary."""
        if not self._recluster_pending:
            return
        with self.lock:
            if self._open_txns:
                return
            done = []
            with self._readers_lock:
                if self._stmt_readers or self._open_scans:
                    return
                # _readers_lock HELD across the permute: a new reader
                # blocks in reader_enter until rows stop moving
                for store in self._recluster_pending:
                    if store.recluster_now(quiesced=True):
                        done.append(store)
            for store in done:
                self._recluster_pending.remove(store)

    # -- 2PC status records (the Percolator primary; ref: txn status in
    # TiKV consulted by lock resolution) ------------------------------------

    def commit_point(self, marker: int) -> int:
        """THE atomic commit: after this status write the txn is
        committed regardless of crashes. Returns the commit ts."""
        ts = self.next_ts()
        self._txn_status[marker] = ("committed", ts)
        return ts

    def abort_point(self, marker: int) -> None:
        self._txn_status[marker] = ("aborted", 0)

    def finish_txn(self, marker: int) -> None:
        """All secondaries applied: drop the status record + the open
        registration."""
        self._txn_status.pop(marker, None)
        self.end_txn(marker)

    def txn_status(self, marker: int):
        return self._txn_status.get(marker)

    def has_stale_txns(self) -> bool:
        """Any decided txn with possibly-unapplied residue? (O(1) —
        status records are dropped in finish_txn on the success path.)"""
        return bool(self._txn_status)

    def resolve_locks(self) -> int:
        """Finish crashed commits/aborts (the resolve-lock flow): any
        marker with a recorded decision but unapplied table residue gets
        its markers rewritten (commit) or erased (rollback) via the
        logless full-scan paths, which are idempotent. Returns resolved
        txn count."""
        n = 0
        for marker, (st, ts) in list(self._txn_status.items()):
            for db in self.databases.values():
                for t in db.tables.values():
                    if st == "committed":
                        t.txn_commit(marker, ts)
                    else:
                        t.txn_rollback(marker)
                    t.release_locks(marker)  # crashed FOR UPDATE locks
            self.finish_txn(marker)
            n += 1
        return n

    def safepoint(self) -> int:
        """Oldest snapshot any open txn can read. NOTE: today's GC
        drivers refuse to run with open txns at all (their write logs
        hold physical row positions — see Table.gc), so when GC actually
        runs this equals current_ts; the min() is the contract for a
        future log-remapping GC that can run under open snapshots."""
        return min(self._open_txns.values(), default=self._ts)

    def log_slow_query(self, db: str, sql: str, duration_s: float,
                       digest: str = "", plan_digest: str = "",
                       max_mem: int = 0, dispatches: int = 0,
                       segs_scanned: int = 0, segs_pruned: int = 0,
                       trace_id: str = "", disposition: str = "",
                       worst_drift: float = 0.0,
                       worst_drift_op: str = "",
                       xfer_bytes: int = 0, compile_ms: float = 0.0,
                       spill_bytes: int = 0,
                       compaction_wait_ms: float = 0.0) -> None:
        """One slow-log row. `trace_id` joins the row to the kept trace
        in information_schema.cluster_trace / /trace?id= (tail sampling
        retains every over-threshold statement's trace, so the id is
        live). `disposition` is "" for a completed statement or
        "error:<Type>" for one that died mid-execution (deadline, kill,
        runtime error) — those used to be invisible here.
        `segs_scanned`/`segs_pruned`: columnar segments staged vs
        zone-map-skipped across the statement's scans — a slow scan
        with zero pruning on a range predicate is the "no clustering /
        stale zone maps" signature. `worst_drift`/`worst_drift_op`: the
        statement's worst per-operator actual/est row ratio and the
        operator that earned it (plan feedback, ISSUE 15) — a slow
        statement with a hundredfold drift is a PLANNING problem, not
        an execution one, findable without tracing."""
        import logging
        import time

        self.slow_queries.append((
            time.strftime("%Y-%m-%d %H:%M:%S"), db, round(duration_s, 4),
            sql.strip()[:2048], digest, plan_digest, int(max_mem),
            int(dispatches), int(segs_scanned), int(segs_pruned),
            trace_id, disposition, worst_drift_op, round(worst_drift, 4),
            int(xfer_bytes), round(float(compile_ms), 3), int(spill_bytes),
            round(float(compaction_wait_ms), 3),
        ))
        logging.getLogger("tidb_tpu.slowlog").warning(
            "slow query (%.3fs) db=%s digest=%s mem=%d dispatches=%d "
            "segs=%d/%d trace=%s%s: %s",
            duration_s, db, digest, max_mem, dispatches, segs_scanned,
            segs_scanned + segs_pruned, trace_id,
            f" [{disposition}]" if disposition else "",
            sql.strip()[:512])

    def gc(self) -> Dict[str, int]:
        """Reclaim dead MVCC versions in every table. Conservative: a
        no-op while any txn is open (open write logs hold physical row
        positions; see Table.gc contract). Returns table -> reclaimed."""
        if self._open_txns:
            return {}
        sp = self.safepoint()
        out: Dict[str, int] = {}
        for db in self.databases.values():
            for name, t in db.tables.items():
                r = t.gc(sp)
                if r:
                    out[f"{db.name}.{name}"] = r
        if out:
            from tidb_tpu.utils.metrics import GC_RECLAIMED

            GC_RECLAIMED.inc(sum(out.values()))
        return out

    def maybe_auto_analyze(self, tables, ratio: float = 0.5,
                           min_rows: int = 1024) -> int:
        """Stats lifecycle (ref: statistics auto-analyze): re-collect a
        touched table's statistics when the rows modified since the last
        ANALYZE cross ratio * analyzed row count (or the table has grown
        past min_rows with no stats at all). Runs inline after commit —
        the single-process analogue of the reference's stats-owner
        background worker. Returns how many tables were analyzed."""
        from tidb_tpu.statistics import analyze_table

        done = 0
        for t in tables:
            mc = getattr(t, "modify_count", 0)
            stats = getattr(t, "stats", None)
            if stats is None:
                # maintenance_stats: threshold probe that must not force
                # a delta-engine compaction on every commit
                if t.maintenance_stats()[0] < min_rows or mc == 0:
                    continue
            elif mc < ratio * max(stats.n_rows, min_rows):
                continue
            analyze_table(t)
            t.modify_count = 0
            done += 1
        return done

    def auto_gc(self, tables=None, min_dead: int = 4096,
                ratio: float = 0.3) -> Dict[str, int]:
        """Opportunistic GC after DML: compact tables whose dead-version
        count crossed the threshold (the auto-GC worker analogue).
        `tables` limits the scan to the tables a txn touched — the
        threshold check costs an O(n) liveness pass per table, which
        must not be paid for every table on every commit."""
        if self._open_txns:
            return {}
        sp = self.safepoint()
        if tables is None:
            tables = [t for db in self.databases.values()
                      for t in db.tables.values()]
        out: Dict[str, int] = {}
        for t in tables:
            phys, dead = t.maintenance_stats()
            if dead >= min_dead and dead >= ratio * phys:
                r = t.gc(sp)
                if r:
                    out[t.schema.name] = r
        if out:
            from tidb_tpu.utils.metrics import GC_RECLAIMED

            GC_RECLAIMED.inc(sum(out.values()))
        return out

    # -- databases ---------------------------------------------------------

    def create_database(self, name: str, if_not_exists: bool = False):
        if name in self.databases:
            if if_not_exists:
                return
            raise DuplicateTableError(f"database {name!r} exists")
        self.databases[name] = Database(name)
        self.schema_version += 1

    def drop_database(self, name: str, if_exists: bool = False):
        if name not in self.databases:
            if if_exists:
                return
            raise SchemaError(f"no database {name!r}")
        dropped = set(self.databases[name].tables.values())
        # FK hygiene matching drop_table: refuse when a table here is
        # referenced from OUTSIDE the database; release the back-edges
        # dropped children hold on external parents
        for t in dropped:
            for child, _fk in getattr(t, "referencing", ()):
                if child is not t and child not in dropped:
                    raise SchemaError(
                        f"cannot drop database {name!r}: "
                        f"{t.schema.name!r} is referenced by a foreign "
                        "key outside it")
        for t in dropped:
            for fk in getattr(t, "foreign_keys", ()):
                if fk.parent not in dropped:
                    fk.parent.referencing = [
                        (c, f) for c, f in fk.parent.referencing
                        if c is not t]
        del self.databases[name]
        self.schema_version += 1

    def database(self, name: str) -> Database:
        if name.lower() == "information_schema":
            return self._info_schema_db()
        db = self.databases.get(name)
        if db is None:
            raise SchemaError(f"no database {name!r}")
        return db

    # -- tables ------------------------------------------------------------

    def create_table(self, db: str, schema: TableSchema,
                     if_not_exists: bool = False,
                     engine: str = None,
                     foreign_keys=None) -> Table:
        d = self.database(db)
        if schema.name in d.tables:
            if if_not_exists:
                return d.tables[schema.name]
            raise DuplicateTableError(f"table {schema.name!r} exists")
        if schema.name in d.views:
            if if_not_exists:
                # MySQL: IF NOT EXISTS is satisfied by any object in the
                # shared table/view namespace — warning, nothing created
                return None
            raise DuplicateTableError(f"view {schema.name!r} exists")
        from tidb_tpu.storage.kvapi import make_table

        t = make_table(schema, engine)
        t.ts_source = self.next_ts
        t.txn_guard = self  # recluster's writer-lock + open-txn gate
        # two-pass: every FK spec must RESOLVE before any back-edge is
        # written — a failure after partial wiring would leave phantom
        # references blocking DROP of the parents forever
        resolved = [self._resolve_foreign_key(db, t, spec)
                    for spec in foreign_keys or ()]
        for parent, fk in resolved:
            t.foreign_keys.append(fk)
            parent.referencing.append((t, fk))
        d.tables[schema.name] = t
        self.schema_version += 1
        return t

    def _resolve_foreign_key(self, db: str, child, spec):
        """Resolve one FOREIGN KEY spec (multi-column, with referential
        actions; ref: ddl foreign-key jobs) WITHOUT mutating anything.
        The referenced column list must carry a matching unique index —
        the same requirement MySQL effectively imposes for well-defined
        parent probes."""
        from tidb_tpu.storage.table import FKInfo

        cols, ref, ref_cols = spec[:3]
        on_delete = spec[3] if len(spec) > 3 else "restrict"
        on_update = spec[4] if len(spec) > 4 else "restrict"
        if len(cols) != len(ref_cols) or not cols:
            raise SchemaError(
                "FOREIGN KEY column count must match REFERENCES")
        for c in cols:
            child.schema.col(c)  # raises if absent
        parent = self.table(ref.schema or db, ref.name)
        for c in ref_cols:
            parent.schema.col(c)
        unique_on_ref = any(
            ix.unique and ix.columns == list(ref_cols)
            for ix in parent.indexes.values())
        if not unique_on_ref:
            raise SchemaError(
                f"foreign key target {ref.name}.({', '.join(ref_cols)}) "
                "must be a PRIMARY KEY or matching UNIQUE index")
        for c, pc in zip(cols, ref_cols):
            cc, pcc = child.schema.col(c), parent.schema.col(pc)
            if (cc.type_.is_dict_encoded and pcc.type_.is_dict_encoded
                    and cc.coll != pcc.coll):
                # FK matching compares fold keys; mixed collations would
                # compare apples to oranges (MySQL requires identical
                # collations on FK column pairs too)
                raise SchemaError(
                    f"foreign key column {c!r} collation {cc.coll!r} must "
                    f"match referenced {pc!r} collation {pcc.coll!r}")
        fk = FKInfo(columns=list(cols), parent=parent,
                    parent_cols=list(ref_cols),
                    name=f"fk_{child.schema.name}_{'_'.join(cols)}",
                    parent_db=ref.schema or db,
                    on_delete=on_delete, on_update=on_update)
        return parent, fk

    def drop_table(self, db: str, name: str, if_exists: bool = False):
        d = self.database(db)
        if name not in d.tables:
            if if_exists:
                return
            raise SchemaError(f"no table {db}.{name}")
        t = d.tables[name]
        if any(child is not t for child, _fk in t.referencing):
            raise SchemaError(
                f"cannot drop {name!r}: referenced by a foreign key")
        # a dropped child releases its back-edges on every parent
        for fk in getattr(t, "foreign_keys", ()):
            fk.parent.referencing = [
                (c, f) for c, f in fk.parent.referencing if c is not t]
        # columnar segment store: release spilled payloads promptly
        # (a weakref finalizer on the store backstops GC'd tables)
        store = getattr(t, "_segment_store", None)
        if store is not None:
            try:
                store.close()
            except Exception:  # noqa: BLE001 — cleanup must not block DROP
                pass
        del d.tables[name]
        self.schema_version += 1

    def table(self, db: str, name: str) -> Table:
        if db.lower() == "information_schema":
            t = self._info_schema_table(name.lower())
            if t is None:
                raise SchemaError(f"no table {db}.{name}")
            return t
        d = self.database(db)
        t = d.tables.get(name)
        if t is None:
            raise SchemaError(f"no table {db}.{name}")
        return t

    def has_table(self, db: str, name: str) -> bool:
        if db.lower() == "information_schema":
            return name.lower() in _INFO_TABLES
        return name in self.databases.get(db, Database(db)).tables

    def tables(self, db: str) -> List[str]:
        return sorted(self.database(db).tables.keys())

    # -- views (ref: the view half of ddl/ + infoschema; a view is a
    # stored SELECT expanded at plan time like a derived table) ---------

    def create_view(self, db: str, name: str, columns, stmt, sql: str,
                    or_replace: bool = False) -> None:
        d = self.database(db)
        if name in d.tables:
            raise DuplicateTableError(f"table {name!r} exists")
        if name in d.views and not or_replace:
            raise DuplicateTableError(f"view {name!r} exists")
        d.views[name] = (tuple(columns) if columns else None, stmt, sql)
        self.schema_version += 1

    def drop_view(self, db: str, name: str, if_exists: bool = False) -> None:
        d = self.database(db)
        if name not in d.views:
            if if_exists:
                return
            raise SchemaError(f"no view {db}.{name}")
        del d.views[name]
        self.schema_version += 1

    def view(self, db: str, name: str):
        d = self.databases.get(db)
        return d.views.get(name) if d is not None else None

    def rename_table(self, db: str, old: str, new: str):
        d = self.database(db)
        if old not in d.tables:
            raise SchemaError(f"no table {db}.{old}")
        if new in d.tables:
            raise DuplicateTableError(f"table {new!r} exists")
        if new in d.views:
            raise DuplicateTableError(f"view {new!r} exists")
        t = d.tables.pop(old)
        t.schema.name = new
        d.tables[new] = t
        self.schema_version += 1

    # -- users (ref: privilege/ — authentication only; grants are a
    # later tier) ----------------------------------------------------------

    @staticmethod
    def native_hash(password: str) -> bytes:
        """mysql_native_password stage-2 hash (what the server stores)."""
        import hashlib

        if not password:
            return b""
        return hashlib.sha1(hashlib.sha1(password.encode()).digest()).digest()

    def create_user(self, user: str, password: str = "",
                    if_not_exists: bool = False) -> None:
        if user in self.users:
            if if_not_exists:
                return  # MySQL: existing account (and password) untouched
            raise DuplicateTableError(f"user {user!r} exists")
        self.users[user] = self.native_hash(password)

    def drop_user(self, user: str, if_exists: bool = False) -> None:
        if user not in self.users:
            if if_exists:
                return
            raise SchemaError(f"no user {user!r}")
        del self.users[user]

    def set_password(self, user: str, password: str) -> None:
        if user not in self.users:
            raise SchemaError(f"no user {user!r}")
        self.users[user] = self.native_hash(password)

    def verify_user(self, user: str, token: bytes, salt: bytes) -> bool:
        """Check a mysql_native_password scramble:
        token = SHA1(password) XOR SHA1(salt + SHA1(SHA1(password)))."""
        import hashlib

        stage2 = self.users.get(user)
        if stage2 is None:
            return False
        if stage2 == b"":
            return token in (b"", b"\x00" * 20)
        if len(token) != 20:
            return False
        mix = hashlib.sha1(salt + stage2).digest()
        stage1 = bytes(a ^ b for a, b in zip(token, mix))
        return hashlib.sha1(stage1).digest() == stage2

    # -- INFORMATION_SCHEMA (ref: infoschema/'s virtual memtables) ----------
    # Read-only views over catalog metadata, materialized per access so
    # they always reflect the current schema version.

    def _info_schema_db(self) -> Database:
        # listing=True: a SHOW TABLES / schema walk materializes every
        # info table — dcn_worker_stats must not fan RPCs out to live
        # clusters just to report that it exists
        d = Database("information_schema")
        for name in _INFO_TABLES:
            d.tables[name] = self._info_schema_table(name, listing=True)
        return d

    def _info_schema_table(self, name: str, viewer=None,
                           listing: bool = False):
        from tidb_tpu.types import FLOAT64, INT64, STRING

        def make(cols, rows):
            schema = TableSchema(
                name, [ColumnInfo(c, t, not_null=False) for c, t in cols])
            t = Table(schema)
            if rows:
                t.insert_rows(rows, begin_ts=0)
            return t

        if name == "schemata":
            return make(
                [("catalog_name", STRING), ("schema_name", STRING)],
                [("def", n) for n in sorted(self.databases)]
                + [("def", "information_schema")],
            )
        if name == "tables":
            rows = []
            for dbn in sorted(self.databases):
                for tn in sorted(self.databases[dbn].tables):
                    t = self.databases[dbn].tables[tn]
                    rows.append(("def", dbn, tn, "BASE TABLE", t.live_rows))
                for vn in sorted(self.databases[dbn].views):
                    rows.append(("def", dbn, vn, "VIEW", 0))
            return make(
                [("table_catalog", STRING), ("table_schema", STRING),
                 ("table_name", STRING), ("table_type", STRING),
                 ("table_rows", INT64)],
                rows,
            )
        if name == "columns":
            rows = []
            for dbn in sorted(self.databases):
                for tn in sorted(self.databases[dbn].tables):
                    t = self.databases[dbn].tables[tn]
                    pk = set(t.schema.primary_key or [])
                    for i, c in enumerate(t.schema.columns):
                        rows.append((
                            dbn, tn, c.name, i + 1,
                            c.type_.kind.name.lower(),
                            "NO" if c.not_null else "YES",
                            "PRI" if c.name in pk else "",
                        ))
            return make(
                [("table_schema", STRING), ("table_name", STRING),
                 ("column_name", STRING), ("ordinal_position", INT64),
                 ("data_type", STRING), ("is_nullable", STRING),
                 ("column_key", STRING)],
                rows,
            )
        if name == "key_column_usage":
            rows = []
            for dbn in sorted(self.databases):
                for tn in sorted(self.databases[dbn].tables):
                    t = self.databases[dbn].tables[tn]
                    for idx in t.indexes.values():
                        if not idx.unique:
                            continue
                        for i, cname in enumerate(idx.columns):
                            rows.append(("def", dbn, idx.name, dbn, tn,
                                         cname, i + 1, None, None, None))
                    for fk in getattr(t, "foreign_keys", ()):
                        for i, (c, pc) in enumerate(
                                zip(fk.columns, fk.parent_cols)):
                            rows.append(("def", dbn, fk.name, dbn, tn,
                                         c, i + 1, fk.parent_db,
                                         fk.parent.schema.name, pc))
            return make(
                [("constraint_catalog", STRING),
                 ("constraint_schema", STRING), ("constraint_name", STRING),
                 ("table_schema", STRING), ("table_name", STRING),
                 ("column_name", STRING), ("ordinal_position", INT64),
                 ("referenced_table_schema", STRING),
                 ("referenced_table_name", STRING),
                 ("referenced_column_name", STRING)],
                rows,
            )
        if name == "referential_constraints":
            rows = []
            for dbn in sorted(self.databases):
                for tn in sorted(self.databases[dbn].tables):
                    t = self.databases[dbn].tables[tn]
                    for fk in getattr(t, "foreign_keys", ()):
                        rows.append(
                            ("def", dbn, fk.name, tn,
                             fk.parent_db, fk.parent.schema.name,
                             fk.on_update.replace("_", " ").upper(),
                             fk.on_delete.replace("_", " ").upper()))
            return make(
                [("constraint_catalog", STRING),
                 ("constraint_schema", STRING), ("constraint_name", STRING),
                 ("table_name", STRING),
                 ("unique_constraint_schema", STRING),
                 ("referenced_table_name", STRING),
                 ("update_rule", STRING), ("delete_rule", STRING)],
                rows,
            )
        if name == "partitions":
            rows = []
            for dbn in sorted(self.databases):
                for tn in sorted(self.databases[dbn].tables):
                    pi = self.databases[dbn].tables[tn].schema.partition
                    if pi is None:
                        rows.append(("def", dbn, tn, None, None, None, None))
                        continue
                    for p in range(pi.count()):
                        desc = None
                        if pi.kind == "range":
                            u = pi.uppers[p]
                            desc = "MAXVALUE" if u is None else str(u)
                        rows.append(("def", dbn, tn, pi.part_name(p), p + 1,
                                     pi.kind.upper(), desc))
            return make(
                [("table_catalog", STRING), ("table_schema", STRING),
                 ("table_name", STRING), ("partition_name", STRING),
                 ("partition_ordinal_position", INT64),
                 ("partition_method", STRING),
                 ("partition_description", STRING)],
                rows,
            )
        if name == "processlist":
            rows = self.processlist_rows(viewer_user=viewer,
                                         with_state=True)
            return make(
                [("id", INT64), ("user", STRING), ("host", STRING),
                 ("db", STRING), ("command", STRING), ("time", INT64),
                 ("state", STRING), ("info", STRING)],
                rows,
            )
        if name == "slow_query":
            return make(
                [("time", STRING), ("db", STRING), ("query_time", FLOAT64),
                 ("query", STRING), ("digest", STRING),
                 ("plan_digest", STRING), ("max_mem", INT64),
                 ("dispatches", INT64), ("segs_scanned", INT64),
                 ("segs_pruned", INT64), ("trace_id", STRING),
                 ("disposition", STRING), ("worst_drift_op", STRING),
                 ("worst_drift", FLOAT64), ("xfer_bytes", INT64),
                 ("compile_ms", FLOAT64), ("spill_bytes", INT64),
                 ("compaction_wait_ms", FLOAT64)],
                list(self.slow_queries),
            )
        if name == "cluster_trace":
            # one row per span of every KEPT trace (the process-global
            # tail-sampled store) — joinable against slow_query.trace_id
            # and the /metrics exemplars
            from tidb_tpu.utils import tracing

            rows = []
            for t in tracing.STORE.traces():
                ts = _time_strftime(t.start_ts)
                keep = ",".join(t.keep_reasons)
                for s in list(t.spans):
                    rows.append((
                        t.trace_id, ts, keep, s.span_id, s.parent_id,
                        s.name, s.proc or "local", s.start_us,
                        max(s.dur_us, 0), ";".join(s.notes)))
            return make(
                [("trace_id", STRING), ("time", STRING), ("keep", STRING),
                 ("span_id", INT64), ("parent_span_id", INT64),
                 ("name", STRING), ("proc", STRING), ("start_us", INT64),
                 ("duration_us", INT64), ("annotations", STRING)],
                rows,
            )
        if name == "dcn_worker_stats":
            # per-worker failure-domain counters of every live Cluster
            # in this process (PR 4's Cluster.worker_stats() was Python-
            # API-only; this makes it joinable from SQL)
            rows = []
            if not listing:
                from tidb_tpu.parallel.dcn import clusters_alive

                for ci, cl in enumerate(clusters_alive()):
                    try:
                        rows.extend((ci,) + r
                                    for r in cl.worker_stats_rows())
                    except Exception:  # noqa: BLE001 — a dying cluster
                        continue       # must not fail the whole read
            return make(
                [("cluster", INT64), ("worker", INT64),
                 ("endpoint", STRING), ("state", STRING),
                 ("executed", INT64), ("cancelled", INT64),
                 ("deadline_exceeded", INT64), ("cancel_rpcs", INT64),
                 ("pages", INT64), ("open_cursors", INT64),
                 ("shards_owned", INT64), ("shard_bytes", INT64),
                 ("shuffle_bytes_in", INT64),
                 ("shuffle_bytes_out", INT64),
                 ("reconnects", INT64), ("replica", INT64),
                 ("error", STRING)],
                rows,
            )
        if name == "scheduler_stats":
            # serving-tier counters of every live statement scheduler in
            # this process: one summary row per scheduler (digest = '')
            # plus one row per coalesced digest. Guarded like
            # dcn_worker_stats: a SHOW TABLES / schema walk (listing)
            # must not touch live schedulers just to report existence.
            rows = []
            if not listing:
                from tidb_tpu.serving import schedulers_alive

                for si, sch in enumerate(schedulers_alive()):
                    try:
                        d = sch.stats_dict()
                    except Exception:  # noqa: BLE001 — a dying scheduler
                        continue       # must not fail the whole read
                    rows.append((
                        si, "", d["workers"], d["queue_depth"],
                        d["inflight_batches"], d["admitted"],
                        d["rejected"], d["timed_out"], d["batches"],
                        d["coalesced_stmts"], d["mem_consumed"],
                        d["mem_budget"],
                        "draining" if d["draining"] else "running"))
                    for dg, cnt in sorted(d["coalesce_by_digest"].items()):
                        rows.append((si, dg, None, None, None, None, None,
                                     None, None, cnt, None, None, ""))
            return make(
                [("scheduler", INT64), ("digest", STRING),
                 ("workers", INT64), ("queue_depth", INT64),
                 ("inflight_batches", INT64), ("admitted", INT64),
                 ("rejected", INT64), ("timed_out", INT64),
                 ("batches", INT64), ("coalesced_stmts", INT64),
                 ("mem_consumed", INT64), ("mem_budget", INT64),
                 ("state", STRING)],
                rows,
            )
        if name == "statements_summary":
            return make(
                [("digest", STRING), ("stmt_type", STRING),
                 ("digest_text", STRING), ("plan_digest", STRING),
                 ("exec_count", INT64), ("sum_latency", FLOAT64),
                 ("avg_latency", FLOAT64), ("max_latency", FLOAT64),
                 ("p95_latency", FLOAT64), ("max_mem", INT64),
                 ("rows_sent", INT64), ("errors", INT64),
                 ("dispatches", INT64), ("fragments", INT64),
                 ("first_seen", STRING), ("last_seen", STRING),
                 ("plan_cache_hits", INT64), ("sum_plan_latency", FLOAT64),
                 ("max_drift", FLOAT64), ("mean_drift", FLOAT64),
                 ("worst_drift_op", STRING), ("xfer_bytes", INT64),
                 ("compile_ms", FLOAT64), ("spill_bytes", INT64)],
                self.stmt_summary.rows(),
            )
        if name == "plan_feedback":
            # per-operator est-vs-actual truth of every recorded
            # (digest, plan) — the SQL face of the plan-feedback store
            # (ISSUE 15). No listing guard needed: the store is local
            # process memory, reading it fans out nothing.
            from tidb_tpu.planner.feedback import STORE as _fb_store

            return make(
                [("digest", STRING), ("plan_digest", STRING),
                 ("variant", STRING), ("execs", INT64),
                 ("warm_execs", INT64), ("best_warm_ms", FLOAT64),
                 ("eager_partial", INT64), ("fused_probe", INT64),
                 ("op", STRING), ("est_rows", FLOAT64),
                 ("actual_rows", FLOAT64), ("drift", FLOAT64),
                 ("op_execs", INT64)],
                _fb_store.rows(),
            )
        if name == "cluster_metrics":
            # the fleet metrics plane (ISSUE 16): the SAME scrape
            # entries /metrics?scope=cluster renders, as SQL rows —
            # per-worker samples, the merged worker='fleet' view, and
            # an error row per unreachable worker. Guarded like
            # dcn_worker_stats: a SHOW TABLES / schema walk (listing)
            # must not scrape a live fleet just to report existence.
            rows = []
            if not listing:
                from tidb_tpu.parallel.dcn import fleet_metrics_entries
                from tidb_tpu.utils.metrics import cluster_rows

                rows = cluster_rows(fleet_metrics_entries())
            return make(
                [("worker", STRING), ("metric", STRING),
                 ("labels", STRING), ("value", FLOAT64),
                 ("error", STRING)],
                rows,
            )
        if name == "cluster_info":
            # topology / online-reshard progress (ISSUE 19): a fleet
            # summary row per live coordinator plus one row per moved
            # shard of every in-flight reshard — operators watch
            # cutover progress and spot a fault-fenced shard (state =
            # "cutover") here. No listing guard needed: local
            # coordinator memory, reading it fans out nothing.
            rows = []
            if not listing:
                from tidb_tpu.parallel.dcn import clusters_alive

                for cl in clusters_alive():
                    try:
                        rows.extend(cl.reshard_progress_rows())
                    except Exception:  # noqa: BLE001 — a dying
                        continue       # coordinator shows no rows
            return make(
                [("table_name", STRING), ("shard", INT64),
                 ("state", STRING), ("dst_worker", INT64),
                 ("old_version", INT64), ("new_version", INT64),
                 ("workers", INT64), ("draining", INT64)],
                rows,
            )
        if name == "digest_latency":
            # per-digest latency SLO store (ISSUE 16): sliding-window
            # percentiles + burn ratio against tidb_tpu_slo_target_ms.
            # No listing guard needed: local process memory.
            from tidb_tpu.serving.slo import STORE as _slo_store

            return make(
                [("digest", STRING), ("digest_text", STRING),
                 ("window_n", INT64), ("execs", INT64),
                 ("p50_ms", FLOAT64), ("p95_ms", FLOAT64),
                 ("p99_ms", FLOAT64), ("target_ms", FLOAT64),
                 ("breaches", INT64), ("burn_ratio", FLOAT64),
                 ("last_seen", STRING)],
                _slo_store.rows(),
            )
        if name == "statistics":
            rows = []
            for dbn in sorted(self.databases):
                for tn in sorted(self.databases[dbn].tables):
                    t = self.databases[dbn].tables[tn]
                    for idx in t.indexes.values():
                        for i, cname in enumerate(idx.columns):
                            rows.append((
                                dbn, tn, 0 if idx.unique else 1,
                                idx.name, i + 1, cname,
                            ))
            return make(
                [("table_schema", STRING), ("table_name", STRING),
                 ("non_unique", INT64), ("index_name", STRING),
                 ("seq_in_index", INT64), ("column_name", STRING)],
                rows,
            )
        return None


def _time_strftime(ts: float) -> str:
    import time

    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))


_INFO_TABLES = ("schemata", "tables", "columns", "statistics", "slow_query",
                "key_column_usage", "referential_constraints",
                "partitions", "processlist", "statements_summary",
                "cluster_trace", "dcn_worker_stats", "scheduler_stats",
                "plan_feedback", "cluster_metrics", "digest_latency",
                "cluster_info")


class SessionCatalog:
    """Per-session overlay adding a TEMPORARY-table namespace over the
    shared catalog (ref: MySQL temporary tables — session-local, shadow
    permanent tables by name, vanish with the connection). Everything
    except table resolution/creation/drop delegates to the base; the
    planner and executors only ever resolve through `table()`, so temp
    tables flow through every downstream path unchanged."""

    def __init__(self, base: "Catalog"):
        while isinstance(base, SessionCatalog):
            base = base._base
        object.__setattr__(self, "_base", base)
        object.__setattr__(self, "_temp", {})  # (db, name) -> Table
        # bumped on every temp create/drop: temp DDL never advances the
        # shared schema_version, so the plan cache keys on this instead
        # (a dropped-and-recreated temp table must never serve the old
        # table object's cached plan)
        object.__setattr__(self, "_temp_epoch", 0)
        object.__setattr__(self, "_viewer", None)  # weakref to Session

    def __getattr__(self, name):
        return getattr(self._base, name)

    def __setattr__(self, name, value):
        # attribute writes always land on the shared base — a proxy-local
        # shadow (e.g. schema_version) would silently fork the catalog
        setattr(self._base, name, value)

    @property
    def base(self) -> "Catalog":
        return self._base

    def table(self, db: str, name: str) -> Table:
        t = self._temp.get((db, name))
        if t is not None:
            return t
        if (db.lower() == "information_schema"
                and name.lower() == "processlist"):
            # viewer-aware: a session without SUPER sees only its own
            # threads, same as SHOW PROCESSLIST (round-5 review)
            viewer = self._viewer() if self._viewer is not None else None
            # always returns a Table — never fall through to the
            # base path, whose viewer-less build is unfiltered
            return self._base._info_schema_table(
                "processlist",
                viewer=getattr(viewer, "user", None) or "")
        return self._base.table(db, name)

    def tables(self, db: str):
        out = list(self._base.tables(db))
        out.extend(n for (d, n) in self._temp if d == db and n not in out)
        return out

    def create_temp_table(self, db: str, schema: TableSchema,
                          if_not_exists: bool = False,
                          engine: str = None) -> Table:
        if (db, schema.name) in self._temp:
            if if_not_exists:
                return self._temp[(db, schema.name)]
            raise DuplicateTableError(
                f"temporary table {schema.name!r} exists")
        from tidb_tpu.storage.kvapi import make_table

        t = make_table(schema, engine)
        t.ts_source = self._base.next_ts
        t.txn_guard = self._base
        self._temp[(db, schema.name)] = t
        object.__setattr__(self, "_temp_epoch", self._temp_epoch + 1)
        return t

    def drop_table(self, db: str, name: str, if_exists: bool = False):
        if (db, name) in self._temp:
            del self._temp[(db, name)]
            object.__setattr__(self, "_temp_epoch", self._temp_epoch + 1)
            return
        return self._base.drop_table(db, name, if_exists=if_exists)

    def drop_temp_tables(self) -> None:
        """Connection end: the whole temp namespace vanishes."""
        self._temp.clear()
        object.__setattr__(self, "_temp_epoch", self._temp_epoch + 1)
