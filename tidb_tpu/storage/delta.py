"""Write-optimized table engine: row memtable over a columnar base.

Ref counterpart: the TiFlash delta-tree shape (and, one level down, the
LSM memtable of the reference's TiKV storage) — fresh writes land in a
cheap row-format buffer; a compaction pass folds them into the
read-optimized columnar base in bulk.

Why it exists here: the columnar `Table` pays per-INSERT costs that are
fine at bulk-load granularity but quadratic for row-at-a-time ingest —
most painfully the sorted-dictionary merge for string columns, which
can remap every existing code whenever one new string arrives. The
delta engine converts each INSERT's values at statement time (so type /
NOT-NULL errors still surface on the right statement), buffers them as
host rows, and compacts into the base with ONE bulk columnar append
(one dictionary merge, one version bump) on the first read or at the
row threshold.

Semantics preserved:
  * visibility — every read path compacts first, so SELECT after INSERT
    (same or different txn) sees the rows with their correct MVCC
    timestamps; buffered txn writes carry their marker and commit /
    rollback rewrites them in place without forcing a compaction;
  * statement-accurate errors — value conversion, NOT NULL, and
    auto-increment assignment happen at buffer time;
  * uniqueness — tables with any unique index (or a primary key) write
    through: deferred unique checks would raise on the wrong statement.

The engine is selected per table: CREATE TABLE ... ENGINE=delta
(`storage.kvapi.make_table`).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from tidb_tpu.errors import ExecutionError

# attribute reads that must NOT trigger compaction (schema-shaped or
# engine bookkeeping; everything else sees the post-compaction state)
_PASSTHROUGH = {
    "schema", "indexes", "ts_source", "stats", "ndv_sketch",
    "modify_count", "to_device_value", "engine",
    # schema-derived reads: must not force a compaction per statement
    "insertable_names", "generated", "foreign_keys", "checks",
}

_OWN = {"_base", "_cols", "_ts", "_logs", "_count"}

FLUSH_ROWS = 4096


class DeltaTable:
    """Memtable + columnar base. Conforms to `kvapi.TABLE_ENGINE_API`
    by construction: intercepted writes/txn hooks here, everything else
    delegates to the base `Table` after compaction."""

    engine = "delta"

    def __init__(self, base):
        object.__setattr__(self, "_base", base)
        object.__setattr__(self, "_cols", {})
        object.__setattr__(self, "_ts", [])
        object.__setattr__(self, "_logs", [])  # per-row TableTxnLog or None
        object.__setattr__(self, "_count", 0)

    # -- engine plumbing ---------------------------------------------------

    def __getattr__(self, name):
        base = object.__getattribute__(self, "_base")
        if name not in _PASSTHROUGH:
            self._compact()
        return getattr(base, name)

    def __setattr__(self, name, value):
        if name in _OWN:
            object.__setattr__(self, name, value)
        else:
            setattr(self._base, name, value)

    @property
    def buffered_rows(self) -> int:
        """Rows in the memtable (diagnostics / tests)."""
        return self._count

    def maintenance_stats(self):
        """Threshold probe WITHOUT compaction: buffered rows are live
        rows-to-be; the base's dead count is unaffected by the buffer."""
        base = self._base
        return base.n + self._count, base.n - base.live_rows

    @property
    def modify_count(self) -> int:
        """Auto-analyze churn including still-buffered rows (they ARE
        modifications; compaction moves the count into the base)."""
        return self._base.modify_count + self._count

    def _bufferable(self) -> bool:
        # deferred unique/FK enforcement would raise on the wrong
        # statement; constrained tables write through
        base = self._base
        return not (any(ix.unique for ix in base.indexes.values())
                    or base.foreign_keys or base.referencing
                    or base.checks)

    # -- write surface -----------------------------------------------------

    def insert_rows(self, rows, columns=None, begin_ts=None, log=None) -> int:
        base = self._base
        if not self._bufferable():
            self._compact()
            return base.insert_rows(rows, columns=columns,
                                    begin_ts=begin_ts, log=log)
        names = columns or base.insertable_names()
        cols = [base.schema.col(n) for n in names]
        m = len(rows)
        if m == 0:
            return 0
        provided = {c.name for c in cols}
        buf = self._cols
        if not buf:
            for c in base.schema.columns:
                buf[c.name] = []
        # convert at statement time: type and NOT NULL errors surface on
        # THIS statement, exactly like the write-through path. A failed
        # conversion must leave the buffer untouched.
        staged: Dict[str, List] = {c.name: [] for c in base.schema.columns}
        for c in base.schema.columns:
            if c.name in provided:
                continue
            if c.auto_increment:
                staged[c.name] = list(range(base._auto_inc, base._auto_inc + m))
            elif c.default is not None:
                staged[c.name] = [base.to_device_value(c, c.default)] * m
            elif c.not_null and not any(
                    g.col == c.name for g in base.generated):
                raise ExecutionError(
                    f"column {c.name!r} has no default and is NOT NULL")
            else:
                # NULL, or a generated column computed at compaction
                staged[c.name] = [None] * m
        for j, (name, c) in enumerate(zip(names, cols)):
            vals = [base.to_device_value(c, r[j]) for r in rows]
            if c.not_null and any(v is None for v in vals):
                raise ExecutionError(f"NULL in NOT NULL column {c.name!r}")
            staged[name] = vals
        # conversion succeeded: commit the batch to the memtable
        for c in base.schema.columns:
            buf[c.name].extend(staged[c.name])
        for c in base.schema.columns:
            if c.auto_increment and c.name not in provided:
                base._auto_inc += m
        ts = base._next_ts() if begin_ts is None else begin_ts
        self._ts.extend([ts] * m)
        self._logs.extend([log] * m)
        self._count += m
        if self._count >= FLUSH_ROWS:
            self._compact()
        return m

    # -- txn lifecycle (buffered rows keep their markers) ------------------

    def txn_commit(self, marker: int, commit_ts: int, log=None) -> None:
        if self._count:
            # committed rows no longer belong to an open txn log
            self._logs = [None if t == marker else lg
                          for t, lg in zip(self._ts, self._logs)]
            self._ts = [commit_ts if t == marker else t for t in self._ts]
        if log is not None and not log.ranges and not log.ended:
            # the txn's writes live entirely in the memtable: nothing of
            # this marker reached the base, and skipping the call keeps
            # base.version (and every cache keyed on it) stable across
            # buffered-only commits
            return
        self._base.txn_commit(marker, commit_ts, log=log)

    def txn_rollback(self, marker: int, log=None) -> None:
        if self._count:
            keep = [i for i, t in enumerate(self._ts) if t != marker]
            if len(keep) != self._count:
                for name, vals in self._cols.items():
                    self._cols[name] = [vals[i] for i in keep]
                self._ts = [self._ts[i] for i in keep]
                self._logs = [self._logs[i] for i in keep]
                self._count = len(keep)
        if log is not None and not log.ranges and not log.ended:
            return
        self._base.txn_rollback(marker, log=log)

    def truncate(self):
        self._cols = {}
        self._ts = []
        self._logs = []
        self._count = 0
        return self._base.truncate()

    # -- compaction --------------------------------------------------------

    def _compact(self) -> None:
        """Fold the memtable into the columnar base: one bulk append,
        one dictionary merge per string column, one version bump."""
        if not self._count:
            return
        base = self._base
        arrays: Dict[str, np.ndarray] = {}
        valids: Dict[str, np.ndarray] = {}
        strings: Dict[str, List[Optional[str]]] = {}
        m = self._count
        for c in base.schema.columns:
            vals = self._cols[c.name]
            if c.type_.is_dict_encoded:
                strings[c.name] = vals
                continue
            vd = np.array([v is not None for v in vals], dtype=np.bool_)
            arr = np.zeros(m, dtype=c.type_.np_dtype)
            if vd.any():
                arr[vd] = [v for v in vals if v is not None]
            arrays[c.name] = arr
            valids[c.name] = vd
        ts = np.array(self._ts, dtype=np.int64)
        logs = self._logs
        self._cols = {}
        self._ts = []
        self._logs = []
        self._count = 0
        base.insert_columns(arrays, valids, strings=strings)
        start = base.n - m
        # bulk appends stamp "committed at origin"; restore each row's
        # real timestamp (commit ts or still-open txn marker)
        base.begin_ts[start: base.n] = ts
        # rows buffered under an OPEN txn log must register their base
        # ranges NOW: the txn's later commit/rollback walks log.ranges to
        # rewrite markers, and an unlogged compacted row would keep its
        # provisional marker forever (committed data silently vanishing)
        i = 0
        while i < m:
            j = i
            while j < m and logs[j] is logs[i]:
                j += 1
            if logs[i] is not None:
                logs[i].ranges.append((start + i, start + j))
                # the version-window cache-carry optimization assumes
                # ranges were appended at their own version bumps;
                # a compaction batches them — disable it conservatively
                logs[i].contiguous = False
            i = j
        # memtable DML counts toward the auto-analyze churn trigger
        base.modify_count += m
