"""Two-phase commit over the columnar storage (ref: store/tikv's
twoPhaseCommitter + the Percolator model: prewrite locks -> primary
commit point -> secondary commits, with lock resolution on recovery).

In this engine a txn's provisional writes are already "locks": rows it
inserted carry begin_ts=marker and rows it ended carry end_ts=marker
(both > any read_ts, so invisible/blocking to others). The committer
adds the structure the reference has:

  1. PREWRITE  — validate every logged lock is still ours (the analogue
                 of prewrite's conflict check; single-writer storage
                 makes this a sanity pass, but it is the extension point
                 for a multi-writer backend)
  2. COMMIT POINT — one atomic write: the catalog's txn-status record
                 (marker -> committed@ts). This is the Percolator
                 primary: after it, the txn IS committed even if the
                 process dies before any table is touched.
  3. SECONDARIES — rewrite each table's markers to the commit ts
                 (idempotent; crash here leaves residue that
                 resolve_locks finishes from the status record).

Failpoints at every boundary let tests kill the commit mid-flight and
assert atomicity across the "restart" (catalog.resolve_locks)."""

from __future__ import annotations

from typing import List, Tuple

from tidb_tpu.errors import ExecutionError
from tidb_tpu.utils.failpoint import inject

__all__ = ["TwoPhaseCommitter"]


class TwoPhaseCommitter:
    def __init__(self, catalog, marker: int, logs: List[Tuple[object, object]]):
        self.catalog = catalog
        self.marker = marker
        self.logs = logs

    # ------------------------------------------------------------------

    def _prewrite(self, table, log) -> None:
        """Every lock this txn took must still be ours."""
        import numpy as np

        for s, e in log.ranges:
            b = table.begin_ts[s:e]
            if not (b[b >= self.marker] == self.marker).all():
                raise ExecutionError(
                    f"prewrite conflict on {table.schema.name!r}: "
                    "provisional rows clobbered")
        for ids in log.ended:
            if len(ids) == 0:
                continue
            e_ = table.end_ts[np.asarray(ids)]
            from tidb_tpu.storage.table import MAX_TS

            theirs = (e_ != self.marker) & (e_ < MAX_TS)  # not ours, not open
            if theirs.any():
                raise ExecutionError(
                    f"prewrite conflict on {table.schema.name!r}: "
                    "lock lost to another transaction")

    def execute(self) -> int:
        """Run the full protocol; returns the commit timestamp."""
        inject("2pc.before_prewrite")
        for t, log in self.logs:
            self._prewrite(t, log)
            inject("2pc.after_prewrite_one")

        inject("2pc.before_commit_point")
        commit_ts = self.catalog.commit_point(self.marker)
        inject("2pc.after_commit_point")

        for t, log in self.logs:
            inject("2pc.before_secondary")
            t.txn_commit(self.marker, commit_ts, log)
        self.catalog.finish_txn(self.marker)
        return commit_ts

    def rollback(self) -> None:
        """Aborted txn: record the decision, then erase the locks."""
        self.catalog.abort_point(self.marker)
        inject("2pc.after_abort_point")
        for t, log in self.logs:
            inject("2pc.before_rollback_one")
            t.txn_rollback(self.marker, log)
        self.catalog.finish_txn(self.marker)
