"""TPC-H data generator (the dbgen stand-in for benchmarks/tests).

Deterministic numpy generation following the TPC-H schema and the spec's
key relationships (retailprice formula, lineitem date/flag derivation,
1-7 lines per order) at any scale factor. Text columns draw from small
pools instead of spec grammar — irrelevant for the target queries
(BASELINE.json configs: Q1/Q5/Q6/Q18, SSB, TPC-DS-style joins) and keeps
dictionaries compact.

Dates are stored as days-since-epoch ints, money as scale-2 ints — i.e.
already in device representation for bulk ingest.
"""

from __future__ import annotations

import datetime
from typing import Dict, Optional

import numpy as np

from tidb_tpu.storage.catalog import Catalog
from tidb_tpu.storage.table import ColumnInfo, TableSchema
from tidb_tpu.types import DATE, FLOAT64, INT64, STRING, date_to_days, decimal_type

__all__ = ["load_tpch", "TPCH_SCHEMAS"]

D152 = decimal_type(15, 2)

TPCH_SCHEMAS = {
    "region": [
        ("r_regionkey", INT64, True),
        ("r_name", STRING, True),
        ("r_comment", STRING, False),
    ],
    "nation": [
        ("n_nationkey", INT64, True),
        ("n_name", STRING, True),
        ("n_regionkey", INT64, True),
        ("n_comment", STRING, False),
    ],
    "supplier": [
        ("s_suppkey", INT64, True),
        ("s_name", STRING, True),
        ("s_address", STRING, True),
        ("s_nationkey", INT64, True),
        ("s_phone", STRING, True),
        ("s_acctbal", D152, True),
        ("s_comment", STRING, False),
    ],
    "customer": [
        ("c_custkey", INT64, True),
        ("c_name", STRING, True),
        ("c_address", STRING, True),
        ("c_nationkey", INT64, True),
        ("c_phone", STRING, True),
        ("c_acctbal", D152, True),
        ("c_mktsegment", STRING, True),
        ("c_comment", STRING, False),
    ],
    "part": [
        ("p_partkey", INT64, True),
        ("p_name", STRING, True),
        ("p_mfgr", STRING, True),
        ("p_brand", STRING, True),
        ("p_type", STRING, True),
        ("p_size", INT64, True),
        ("p_container", STRING, True),
        ("p_retailprice", D152, True),
        ("p_comment", STRING, False),
    ],
    "partsupp": [
        ("ps_partkey", INT64, True),
        ("ps_suppkey", INT64, True),
        ("ps_availqty", INT64, True),
        ("ps_supplycost", D152, True),
        ("ps_comment", STRING, False),
    ],
    "orders": [
        ("o_orderkey", INT64, True),
        ("o_custkey", INT64, True),
        ("o_orderstatus", STRING, True),
        ("o_totalprice", D152, True),
        ("o_orderdate", DATE, True),
        ("o_orderpriority", STRING, True),
        ("o_clerk", STRING, True),
        ("o_shippriority", INT64, True),
        ("o_comment", STRING, False),
    ],
    "lineitem": [
        ("l_orderkey", INT64, True),
        ("l_partkey", INT64, True),
        ("l_suppkey", INT64, True),
        ("l_linenumber", INT64, True),
        ("l_quantity", D152, True),
        ("l_extendedprice", D152, True),
        ("l_discount", D152, True),
        ("l_tax", D152, True),
        ("l_returnflag", STRING, True),
        ("l_linestatus", STRING, True),
        ("l_shipdate", DATE, True),
        ("l_commitdate", DATE, True),
        ("l_receiptdate", DATE, True),
        ("l_shipinstruct", STRING, True),
        ("l_shipmode", STRING, True),
        ("l_comment", STRING, False),
    ],
}

_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
_INSTRUCT = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
_CONTAINERS = [
    f"{a} {b}"
    for a in ["SM", "MED", "LG", "JUMBO", "WRAP"]
    for b in ["BAG", "BOX", "CAN", "CASE", "DRUM", "JAR", "PACK", "PKG"]
]
_TYPES = [
    f"{a} {b} {c}"
    for a in ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
    for b in ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
    for c in ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
]
_BRANDS = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
_COMMENT_POOL = [f"final deps c{i} haggle" for i in range(64)]
_P_NAME_WORDS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished",
]

_START = date_to_days(datetime.date(1992, 1, 1))
_END = date_to_days(datetime.date(1998, 8, 2))
_CURRENT = date_to_days(datetime.date(1995, 6, 17))


def _money(x: np.ndarray) -> np.ndarray:
    """float dollars -> scale-2 int cents."""
    return np.round(x * 100).astype(np.int64)


def _pool_pick(rng, pool, n):
    return [pool[i] for i in rng.integers(0, len(pool), n)]


def _load_orders_lineitem_native(make_table, counts, sf, seed,
                                 npart, nsupp, ncust) -> bool:
    """Fill orders+lineitem via the C++ generator; False if unavailable."""
    from tidb_tpu.storage.native_gen import native_orders_lineitem

    nclerk = max(1, int(1000 * sf))
    out = native_orders_lineitem(sf, seed, npart, nsupp, ncust, nclerk)
    if out is None:
        return False
    o, l = out

    t = make_table("lineitem")
    counts["lineitem"] = t.ingest_encoded(
        {
            "l_orderkey": l["l_orderkey"], "l_partkey": l["l_partkey"],
            "l_suppkey": l["l_suppkey"], "l_linenumber": l["l_linenumber"],
            "l_quantity": l["l_quantity"],
            "l_extendedprice": l["l_extendedprice"],
            "l_discount": l["l_discount"], "l_tax": l["l_tax"],
            "l_returnflag": l["l_returnflag_code"],
            "l_linestatus": l["l_linestatus_code"],
            "l_shipdate": l["l_shipdate"], "l_commitdate": l["l_commitdate"],
            "l_receiptdate": l["l_receiptdate"],
            "l_shipinstruct": l["l_instruct_code"],
            "l_shipmode": l["l_shipmode_code"],
            "l_comment": l["l_comment_code"],
        },
        pools={
            "l_returnflag": ["A", "N", "R"],
            "l_linestatus": ["F", "O"],
            "l_shipinstruct": sorted(_INSTRUCT),
            "l_shipmode": sorted(_SHIPMODES),
            "l_comment": sorted(_COMMENT_POOL),
        },
    )
    t = make_table("orders")
    counts["orders"] = t.ingest_encoded(
        {
            "o_orderkey": o["o_orderkey"], "o_custkey": o["o_custkey"],
            "o_totalprice": o["o_totalprice"], "o_orderdate": o["o_orderdate"],
            "o_shippriority": o["o_shippriority"],
            "o_orderstatus": o["o_status_code"],
            "o_orderpriority": o["o_priority_code"],
            "o_clerk": o["o_clerk_code"], "o_comment": o["o_comment_code"],
        },
        pools={
            "o_orderstatus": ["F", "O", "P"],
            "o_orderpriority": sorted(_PRIORITIES),
            "o_clerk": [f"Clerk#{k + 1:09d}" for k in range(nclerk)],
            "o_comment": sorted(_COMMENT_POOL),
        },
    )
    return True


def load_tpch(catalog: Catalog, sf: float = 0.01, db: str = "test", seed: int = 7,
              native: Optional[bool] = None,
              cluster_lineitem: bool = False,
              cluster: bool = True) -> Dict[str, int]:
    """Generate and ingest all eight TPC-H tables at scale factor `sf`.
    Returns table -> row count.

    `native` selects the C++ generator (native/tpch_gen.cpp) for the two
    big tables — orders and lineitem fill as int64 columns + dictionary
    codes with no per-row Python objects. None = auto (native when the
    library builds/loads); False forces the numpy oracle generator.

    `cluster` (default) declares ``CLUSTER BY (l_shipdate)`` on
    lineitem: ordered compaction (ISSUE 18) physically sorts the fact
    table at the first delta->segment fold, so the columnar store's
    date zone maps prune (ISSUE 8's Q6 floor) regardless of ingest
    order — no hand-ordered load needed. Row order is not observable
    through SQL, so query results are unaffected.

    `cluster_lineitem` (DEPRECATED — `cluster` supersedes it) ingests
    lineitem pre-sorted in l_shipdate order. Implies the numpy
    generator for orders/lineitem."""
    if cluster_lineitem:
        import warnings

        warnings.warn(
            "load_tpch(cluster_lineitem=True) is deprecated: lineitem "
            "now carries CLUSTER BY (l_shipdate) by default "
            "(cluster=True) and ordered compaction sorts it at the "
            "first delta->segment fold", DeprecationWarning,
            stacklevel=2)
    rng = np.random.default_rng(seed)
    counts = {}

    def make_table(name):
        cols = [ColumnInfo(n, t, not_null=nn) for n, t, nn in TPCH_SCHEMAS[name]]
        pk = {
            "region": ["r_regionkey"], "nation": ["n_nationkey"],
            "supplier": ["s_suppkey"], "customer": ["c_custkey"],
            "part": ["p_partkey"], "partsupp": ["ps_partkey", "ps_suppkey"],
            "orders": ["o_orderkey"], "lineitem": ["l_orderkey", "l_linenumber"],
        }[name]
        cb = "l_shipdate" if cluster and name == "lineitem" else None
        return catalog.create_table(
            db, TableSchema(name, cols, primary_key=pk, cluster_by=cb))

    # region / nation -------------------------------------------------------
    t = make_table("region")
    counts["region"] = t.insert_columns(
        {"r_regionkey": np.arange(5)},
        strings={"r_name": _REGIONS, "r_comment": _COMMENT_POOL[:5]},
    )
    t = make_table("nation")
    counts["nation"] = t.insert_columns(
        {"n_nationkey": np.arange(25), "n_regionkey": np.array([r for _, r in _NATIONS])},
        strings={"n_name": [n for n, _ in _NATIONS], "n_comment": _COMMENT_POOL[:25]},
    )

    # supplier ---------------------------------------------------------------
    ns = max(1, int(10_000 * sf))
    keys = np.arange(1, ns + 1)
    t = make_table("supplier")
    counts["supplier"] = t.insert_columns(
        {
            "s_suppkey": keys,
            "s_nationkey": rng.integers(0, 25, ns),
            "s_acctbal": _money(rng.uniform(-999.99, 9999.99, ns)),
        },
        strings={
            "s_name": [f"Supplier#{k:09d}" for k in keys],
            "s_address": _pool_pick(rng, _COMMENT_POOL, ns),
            "s_phone": [f"{10+k%25}-{k%1000:03d}-{(k*7)%1000:03d}-{(k*13)%10000:04d}" for k in keys],
            "s_comment": _pool_pick(rng, _COMMENT_POOL, ns),
        },
    )

    # customer ---------------------------------------------------------------
    nc = max(1, int(150_000 * sf))
    keys = np.arange(1, nc + 1)
    t = make_table("customer")
    counts["customer"] = t.insert_columns(
        {
            "c_custkey": keys,
            "c_nationkey": rng.integers(0, 25, nc),
            "c_acctbal": _money(rng.uniform(-999.99, 9999.99, nc)),
        },
        strings={
            "c_name": [f"Customer#{k:09d}" for k in keys],
            "c_address": _pool_pick(rng, _COMMENT_POOL, nc),
            "c_phone": [f"{10+k%25}-{k%1000:03d}-{(k*7)%1000:03d}-{(k*13)%10000:04d}" for k in keys],
            "c_mktsegment": _pool_pick(rng, _SEGMENTS, nc),
            "c_comment": _pool_pick(rng, _COMMENT_POOL, nc),
        },
    )

    # part -------------------------------------------------------------------
    npart = max(1, int(200_000 * sf))
    keys = np.arange(1, npart + 1)
    # spec retailprice formula: ties part price to key so lineitem prices join up
    retail = (90000 + (keys // 10) % 20001 + 100 * (keys % 1000))  # cents
    t = make_table("part")
    counts["part"] = t.insert_columns(
        {
            "p_partkey": keys,
            "p_size": rng.integers(1, 51, npart),
            "p_retailprice": retail,
        },
        strings={
            "p_name": [
                f"{_P_NAME_WORDS[k % 13]} {_P_NAME_WORDS[(k // 13) % 13]}" for k in keys
            ],
            "p_mfgr": [f"Manufacturer#{1 + k % 5}" for k in keys],
            "p_brand": _pool_pick(rng, _BRANDS, npart),
            "p_type": _pool_pick(rng, _TYPES, npart),
            "p_container": _pool_pick(rng, _CONTAINERS, npart),
            "p_comment": _pool_pick(rng, _COMMENT_POOL, npart),
        },
    )

    # partsupp ---------------------------------------------------------------
    t = make_table("partsupp")
    ps_part = np.repeat(keys, 4)
    nps = len(ps_part)
    ps_supp = ((ps_part + (np.tile(np.arange(4), npart) * (ns // 4 + 1))) % ns) + 1
    counts["partsupp"] = t.insert_columns(
        {
            "ps_partkey": ps_part,
            "ps_suppkey": ps_supp,
            "ps_availqty": rng.integers(1, 10_000, nps),
            "ps_supplycost": _money(rng.uniform(1.0, 1000.0, nps)),
        },
        strings={"ps_comment": _pool_pick(rng, _COMMENT_POOL, nps)},
    )

    # orders + lineitem ------------------------------------------------------
    if native is not False and not cluster_lineitem:
        done = _load_orders_lineitem_native(
            make_table, counts, sf, seed, npart, ns, nc)
        if done:
            return counts
        if native is True:
            raise RuntimeError("native TPC-H generator unavailable")

    no = max(1, int(1_500_000 * sf))
    okeys = np.arange(1, no + 1)
    odate = rng.integers(_START, _END - 151, no)
    ocust = rng.integers(1, nc + 1, no)
    lines_per = rng.integers(1, 8, no)  # 1..7
    nl = int(lines_per.sum())

    l_orderkey = np.repeat(okeys, lines_per)
    l_linenumber = np.concatenate([np.arange(1, c + 1) for c in lines_per])
    l_odate = np.repeat(odate, lines_per)
    l_partkey = rng.integers(1, npart + 1, nl)
    l_suppkey = ((l_partkey + rng.integers(0, 4, nl) * (ns // 4 + 1)) % ns) + 1
    l_qty = rng.integers(1, 51, nl)
    l_retail = 90000 + (l_partkey // 10) % 20001 + 100 * (l_partkey % 1000)
    l_extended = l_qty * l_retail  # cents, scale 2
    l_discount = rng.integers(0, 11, nl)  # 0.00..0.10 at scale 2
    l_tax = rng.integers(0, 9, nl)
    l_ship = l_odate + rng.integers(1, 122, nl)
    l_commit = l_odate + rng.integers(30, 91, nl)
    l_receipt = l_ship + rng.integers(1, 31, nl)
    returned = l_receipt <= _CURRENT
    rflag = np.where(returned, np.where(rng.random(nl) < 0.5, "R", "A"), "N")
    lstatus = np.where(l_ship > _CURRENT, "O", "F")
    l_instruct = _pool_pick(rng, _INSTRUCT, nl)
    l_shipmode = _pool_pick(rng, _SHIPMODES, nl)
    l_comment = _pool_pick(rng, _COMMENT_POOL, nl)

    if cluster_lineitem:
        # time-ordered ingest: every per-row array permutes together
        # (aggregate derivations below key on l_orderkey, so the
        # permutation is invisible to them)
        order = np.argsort(l_ship, kind="stable")
        l_orderkey, l_linenumber = l_orderkey[order], l_linenumber[order]
        l_partkey, l_suppkey = l_partkey[order], l_suppkey[order]
        l_qty, l_extended = l_qty[order], l_extended[order]
        l_discount, l_tax = l_discount[order], l_tax[order]
        l_ship, l_commit = l_ship[order], l_commit[order]
        l_receipt = l_receipt[order]
        rflag, lstatus = rflag[order], lstatus[order]
        l_instruct = [l_instruct[i] for i in order]
        l_shipmode = [l_shipmode[i] for i in order]
        l_comment = [l_comment[i] for i in order]

    t = make_table("lineitem")
    counts["lineitem"] = t.insert_columns(
        {
            "l_orderkey": l_orderkey,
            "l_partkey": l_partkey,
            "l_suppkey": l_suppkey,
            "l_linenumber": l_linenumber,
            "l_quantity": l_qty * 100,  # scale-2
            "l_extendedprice": l_extended,
            "l_discount": l_discount,
            "l_tax": l_tax,
            "l_shipdate": l_ship,
            "l_commitdate": l_commit,
            "l_receiptdate": l_receipt,
        },
        strings={
            "l_returnflag": rflag.tolist(),
            "l_linestatus": lstatus.tolist(),
            "l_shipinstruct": l_instruct,
            "l_shipmode": l_shipmode,
            "l_comment": l_comment,
        },
    )

    # o_totalprice = sum(l_extendedprice*(1+tax)*(1-discount)) per order;
    # o_orderstatus from line statuses (F/O/P)
    disc_price = l_extended * (100 - l_discount) * (100 + l_tax)  # scale 6
    totals = np.zeros(no + 1, dtype=np.int64)
    np.add.at(totals, l_orderkey, disc_price // 10_000)  # back to scale 2
    n_f = np.zeros(no + 1, dtype=np.int64)
    np.add.at(n_f, l_orderkey, (lstatus == "F").astype(np.int64))
    n_lines = np.zeros(no + 1, dtype=np.int64)
    np.add.at(n_lines, l_orderkey, 1)
    status = np.where(n_f[1:] == n_lines[1:], "F", np.where(n_f[1:] == 0, "O", "P"))

    t = make_table("orders")
    counts["orders"] = t.insert_columns(
        {
            "o_orderkey": okeys,
            "o_custkey": ocust,
            "o_totalprice": totals[1:],
            "o_orderdate": odate,
            "o_shippriority": np.zeros(no, dtype=np.int64),
        },
        strings={
            "o_orderstatus": status.tolist(),
            "o_orderpriority": _pool_pick(rng, _PRIORITIES, no),
            "o_clerk": [f"Clerk#{1 + k % max(1, int(1000 * sf)):09d}" for k in okeys],
            "o_comment": _pool_pick(rng, _COMMENT_POOL, no),
        },
    )
    return counts
