"""TPC-DS subset for the Q95 eval config (BASELINE.md: "TPC-DS Q95
SF100 — semi-join / correlated subquery, MPP exchange").

Q95 counts web orders shipped from more than one warehouse AND
returned (both IN-subqueries must hold), within a date window and
shipping state. It needs four base
tables (web_sales, web_returns, date_dim, customer_address, web_site)
and exercises exactly the shapes the config names: a self-join
duplicate-detection CTE, two IN-subquery semi-joins over it, and
COUNT(DISTINCT)."""

from __future__ import annotations

import datetime
from typing import Dict

import numpy as np

from tidb_tpu.storage.catalog import Catalog
from tidb_tpu.storage.table import ColumnInfo, TableSchema
from tidb_tpu.types import DATE, INT64, STRING, date_to_days, decimal_type

__all__ = ["load_tpcds_q95", "TPCDS_SCHEMAS", "Q95", "Q95_SQLITE"]

D72 = decimal_type(7, 2)

TPCDS_SCHEMAS = {
    "date_dim": [
        ("d_date_sk", INT64, True),
        ("d_date", DATE, True),
    ],
    "customer_address": [
        ("ca_address_sk", INT64, True),
        ("ca_state", STRING, True),
    ],
    "web_site": [
        ("web_site_sk", INT64, True),
        ("web_company_name", STRING, True),
    ],
    "web_sales": [
        ("ws_order_number", INT64, True),
        ("ws_item_sk", INT64, True),
        ("ws_warehouse_sk", INT64, True),
        ("ws_ship_date_sk", INT64, True),
        ("ws_ship_addr_sk", INT64, True),
        ("ws_web_site_sk", INT64, True),
        ("ws_ext_ship_cost", D72, True),
        ("ws_net_profit", D72, True),
    ],
    "web_returns": [
        ("wr_order_number", INT64, True),
        ("wr_item_sk", INT64, True),
    ],
}

_STATES = ["CA", "GA", "IL", "NY", "TX"]


def load_tpcds_q95(catalog: Catalog, sf: float = 0.01, db: str = "test",
                   seed: int = 13) -> Dict[str, int]:
    rng = np.random.default_rng(seed)
    counts = {}

    def make_table(name, pk=None):
        cols = [ColumnInfo(n, t, not_null=nn) for n, t, nn in TPCDS_SCHEMAS[name]]
        return catalog.create_table(db, TableSchema(name, cols, primary_key=pk))

    first = datetime.date(1999, 1, 1)
    ndates = 730
    t = make_table("date_dim", ["d_date_sk"])
    counts["date_dim"] = t.insert_columns({
        "d_date_sk": np.arange(1, ndates + 1),
        "d_date": np.array(
            [date_to_days(first + datetime.timedelta(days=i)) for i in range(ndates)],
            dtype=np.int32),
    })

    naddr = max(5, int(1000 * sf))
    t = make_table("customer_address", ["ca_address_sk"])
    counts["customer_address"] = t.insert_columns(
        {"ca_address_sk": np.arange(1, naddr + 1)},
        strings={"ca_state": [_STATES[i] for i in rng.integers(0, 5, naddr)]},
    )

    t = make_table("web_site", ["web_site_sk"])
    counts["web_site"] = t.insert_columns(
        {"web_site_sk": np.arange(1, 7)},
        strings={"web_company_name": ["pri", "pri", "ally", "ought", "eing", "able"]},
    )

    # web_sales: multiple line items per order; 30% of MULTI-LINE orders
    # ship from two warehouses (single-line orders can't — the ws_wh
    # self-join needs two rows), so ~22% of all orders qualify
    norders = max(10, int(60_000 * sf))
    lines = rng.integers(1, 5, norders)
    n = int(lines.sum())
    okey = np.repeat(np.arange(1, norders + 1), lines)
    two_wh = (rng.random(norders) < 0.3) & (lines >= 2)
    wh_base = rng.integers(1, 6, norders)
    # first line of a two-warehouse order ships from a second warehouse
    wh = np.repeat(wh_base, lines)
    firsts = np.cumsum(np.concatenate([[0], lines[:-1]]))
    wh[firsts[two_wh]] = (wh_base[two_wh] % 5) + 1 + 5
    t = make_table("web_sales")
    counts["web_sales"] = t.insert_columns({
        "ws_order_number": okey,
        "ws_item_sk": rng.integers(1, 1000, n),
        "ws_warehouse_sk": wh,
        "ws_ship_date_sk": np.repeat(rng.integers(1, ndates + 1, norders), lines),
        "ws_ship_addr_sk": np.repeat(rng.integers(1, naddr + 1, norders), lines),
        "ws_web_site_sk": np.repeat(rng.integers(1, 7, norders), lines),
        "ws_ext_ship_cost": rng.integers(100, 100_00, n),
        "ws_net_profit": rng.integers(-50_00, 200_00, n),
    })

    # a quarter of orders returned (high vs the spec's ~8% so the full
    # filter chain keeps survivors at test scale factors)
    returned = np.nonzero(rng.random(norders) < 0.25)[0] + 1
    t = make_table("web_returns")
    counts["web_returns"] = t.insert_columns({
        "wr_order_number": returned,
        "wr_item_sk": rng.integers(1, 1000, len(returned)),
    })
    return counts


# the official Q95 shape (60-day window, one state, one company) ------------
Q95 = """with ws_wh as (
    select ws1.ws_order_number as wswh_order_number
    from web_sales ws1, web_sales ws2
    where ws1.ws_order_number = ws2.ws_order_number
      and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk
)
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-04-02'
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk and ca_state = 'IL'
  and ws1.ws_web_site_sk = web_site_sk and web_company_name = 'pri'
  and ws1.ws_order_number in (select wswh_order_number from ws_wh)
  and ws1.ws_order_number in (select wr_order_number
                              from web_returns, ws_wh
                              where wr_order_number = wswh_order_number)
order by order_count"""

# sqlite mirror variant: sqlite has no DATE '...' literal syntax; the
# mirror stores dates as ISO text, which compares correctly as strings
Q95_SQLITE = Q95.replace("date '1999-02-01'", "'1999-02-01'").replace(
    "date '1999-04-02'", "'1999-04-02'")
