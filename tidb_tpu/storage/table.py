"""Host columnar tables.

Layout decisions (device-first):
  * column-major numpy buffers in the device representation already
    (scaled ints, day counts, dict codes) so staging to HBM is a straight
    jnp.asarray of a slice — no row pivots on the hot path
  * appends grow buffers geometrically; deletes set a tombstone bit;
    updates write in place (single-writer host model, like the reference's
    single leaseholder per region)
  * each string column owns a sorted Dictionary; appends that introduce new
    strings re-encode the column (dictionaries grow rarely in analytics
    workloads; re-encode is vectorized)
  * `version` bumps on every mutation — executors snapshot (version,
    row_count) so EXPLAIN ANALYZE and the scheduler can detect staleness
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from tidb_tpu.chunk.dictionary import Dictionary
from tidb_tpu.errors import ExecutionError, SchemaError, TypeError_
from tidb_tpu.types import (
    SQLType,
    TypeKind,
    date_to_days,
    datetime_to_micros,
    decimal_to_scaled,
)

__all__ = ["ColumnInfo", "TableSchema", "Table", "TableTxnLog"]


@dataclass
class TableTxnLog:
    """Rows one transaction touched in one table, so commit/rollback cost
    O(rows written) not O(table) (ref: the txn's memdb buffer keying the
    2PC mutations)."""

    ranges: List[tuple] = field(default_factory=list)  # appended [start,end)
    ended: List[np.ndarray] = field(default_factory=list)  # end_ts-stamped ids


@dataclass
class ColumnInfo:
    name: str
    type_: SQLType
    not_null: bool = False
    default: object = None
    auto_increment: bool = False


@dataclass
class TableSchema:
    name: str
    columns: List[ColumnInfo]
    primary_key: Optional[List[str]] = None

    def col(self, name: str) -> ColumnInfo:
        for c in self.columns:
            if c.name == name:
                return c
        raise SchemaError(f"no column {name!r} in table {self.name!r}")

    def names(self) -> List[str]:
        return [c.name for c in self.columns]


_GROW = 1.5
_MIN_CAP = 1024

# MVCC timestamps: committed rows carry ts < TXN_TS_BASE; an open
# transaction stamps its provisional writes with marker = TXN_TS_BASE +
# txn_id (greater than every possible read_ts, so invisible to others —
# and, sitting in end_ts, an effective row lock). MAX_TS = "not deleted".
TXN_TS_BASE = 1 << 60
MAX_TS = 1 << 62


class Table:
    """Append-friendly columnar store for one table."""

    def __init__(self, schema: TableSchema):
        self.schema = schema
        self.n = 0  # physical rows incl. dead versions
        self.version = 0
        self._auto_inc = 1
        self._local_ts = 0  # fallback TSO for catalog-less tables
        self.ts_source = None  # catalog-provided TSO (set by create_table)
        cap = _MIN_CAP
        self._cap = cap
        self.data: Dict[str, np.ndarray] = {}
        self.valid: Dict[str, np.ndarray] = {}
        self.dicts: Dict[str, Dictionary] = {}
        for c in schema.columns:
            self.data[c.name] = np.zeros(cap, dtype=c.type_.np_dtype)
            self.valid[c.name] = np.zeros(cap, dtype=np.bool_)
            if c.type_.kind == TypeKind.STRING:
                self.dicts[c.name] = Dictionary([])
        # MVCC visibility range per physical row (see TXN_TS_BASE above)
        self.begin_ts = np.zeros(cap, dtype=np.int64)
        self.end_ts = np.full(cap, MAX_TS, dtype=np.int64)

    def _next_ts(self) -> int:
        if self.ts_source is not None:
            return self.ts_source()
        self._local_ts += 1
        return self._local_ts

    # -- row count ---------------------------------------------------------

    @property
    def live_rows(self) -> int:
        """Committed-latest row count (provisional writes excluded)."""
        if self.n == 0:
            return 0
        b = self.begin_ts[: self.n]
        e = self.end_ts[: self.n]
        return int(((b < TXN_TS_BASE) & (e >= TXN_TS_BASE)).sum())

    def _ensure(self, extra: int):
        need = self.n + extra
        if need <= self._cap:
            return
        cap = max(int(self._cap * _GROW), need, _MIN_CAP)
        for name in self.data:
            self.data[name] = np.resize(self.data[name], cap)
            self.data[name][self.n:] = 0
            self.valid[name] = np.resize(self.valid[name], cap)
            self.valid[name][self.n:] = False
        self.begin_ts = np.resize(self.begin_ts, cap)
        self.begin_ts[self.n:] = 0
        self.end_ts = np.resize(self.end_ts, cap)
        self.end_ts[self.n:] = MAX_TS
        self._cap = cap

    # -- ingestion ---------------------------------------------------------

    def to_device_value(self, col: ColumnInfo, v):
        """Host python value -> device representation scalar."""
        import datetime

        if v is None:
            return None
        k = col.type_.kind
        try:
            if k == TypeKind.INT:
                return int(v)
            if k == TypeKind.FLOAT:
                return float(v)
            if k == TypeKind.BOOL:
                return bool(v)
            if k == TypeKind.DECIMAL:
                return decimal_to_scaled(v, col.type_.scale)
            if k == TypeKind.DATE:
                if isinstance(v, str):
                    v = datetime.date.fromisoformat(v)
                return date_to_days(v)
            if k == TypeKind.DATETIME:
                if isinstance(v, str):
                    v = datetime.datetime.fromisoformat(v)
                return datetime_to_micros(v)
            if k == TypeKind.STRING:
                return str(v)  # encoded in bulk by insert_rows
        except (ValueError, TypeError) as e:
            raise TypeError_(f"bad value {v!r} for column {col.name}: {e}")
        raise TypeError_(f"unsupported type {col.type_}")

    def insert_rows(self, rows: Sequence[Sequence], columns: Optional[List[str]] = None,
                    begin_ts: Optional[int] = None,
                    log: Optional["TableTxnLog"] = None) -> int:
        """Insert python rows (already in logical form; strings as str,
        dates as date/str, decimals as str/float). Returns rows inserted.
        begin_ts: commit timestamp, or a txn marker for provisional writes;
        None commits immediately at the next TSO tick."""
        names = columns or self.schema.names()
        cols = [self.schema.col(n) for n in names]
        m = len(rows)
        if m == 0:
            return 0
        self._ensure(m)
        start, end = self.n, self.n + m
        provided = set(names)
        # columns not provided get default/NULL/auto-inc
        for c in self.schema.columns:
            if c.name in provided:
                continue
            if c.auto_increment:
                vals = np.arange(self._auto_inc, self._auto_inc + m, dtype=np.int64)
                self._auto_inc += m
                self.data[c.name][start:end] = vals
                self.valid[c.name][start:end] = True
            elif c.default is not None:
                dv = self.to_device_value(c, c.default)
                if c.type_.kind == TypeKind.STRING:
                    self._append_strings(c.name, [dv] * m, start, end)
                else:
                    self.data[c.name][start:end] = dv
                    self.valid[c.name][start:end] = True
            elif c.not_null:
                raise ExecutionError(f"column {c.name!r} has no default and is NOT NULL")
            # else: stays NULL
        for j, (name, c) in enumerate(zip(names, cols)):
            vals = [self.to_device_value(c, r[j]) for r in rows]
            if any(v is None for v in vals) and c.not_null:
                raise ExecutionError(f"NULL in NOT NULL column {c.name!r}")
            if c.type_.kind == TypeKind.STRING:
                self._append_strings(name, vals, start, end)
            else:
                arr = self.data[name]
                vd = self.valid[name]
                for i, v in enumerate(vals):
                    if v is None:
                        vd[start + i] = False
                    else:
                        arr[start + i] = v
                        vd[start + i] = True
        self.begin_ts[start:end] = self._next_ts() if begin_ts is None else begin_ts
        self.end_ts[start:end] = MAX_TS
        self.n = end
        if log is not None:
            log.ranges.append((start, end))
        self.version += 1
        return m

    def insert_columns(self, arrays: Dict[str, np.ndarray], valids: Optional[Dict[str, np.ndarray]] = None, strings: Optional[Dict[str, list]] = None):
        """Bulk columnar ingest (datagen / LOAD). `arrays` hold device reprs
        for non-string columns; `strings` holds raw python strings per
        string column."""
        sizes = [len(a) for a in arrays.values()] + [len(s) for s in (strings or {}).values()]
        if not sizes:
            return 0
        m = sizes[0]
        if any(s != m for s in sizes):
            raise ExecutionError(f"bulk insert length mismatch: {sizes}")
        self._ensure(m)
        start, end = self.n, self.n + m
        for c in self.schema.columns:
            name = c.name
            if strings and name in strings:
                self._append_strings(name, strings[name], start, end)
            elif name in arrays:
                self.data[name][start:end] = arrays[name].astype(c.type_.np_dtype, copy=False)
                if valids and name in valids:
                    self.valid[name][start:end] = valids[name]
                else:
                    self.valid[name][start:end] = True
            elif c.not_null:
                raise ExecutionError(f"bulk insert missing NOT NULL column {name!r}")
        self.begin_ts[start:end] = 0  # bulk loads are committed "at origin"
        self.end_ts[start:end] = MAX_TS
        self.n = end
        self.version += 1
        return m

    def _append_strings(self, name: str, vals: list, start: int, end: int):
        d = self.dicts[name]
        new = {v for v in vals if v is not None and v not in d}
        if new:
            # dictionary grows: build union dict and re-encode existing codes
            nd = Dictionary(list(d.values) + list(new))
            if self.n > 0 and len(d) > 0:
                trans = d.translate_to(nd)
                self.data[name][: self.n] = trans[self.data[name][: self.n]]
            self.dicts[name] = nd
            d = nd
        codes, valid = d.encode_with(vals)
        self.data[name][start:end] = codes
        self.valid[name][start:end] = valid

    # -- mutation ----------------------------------------------------------

    def _writable_mask(self, ids: np.ndarray, marker: int) -> np.ndarray:
        """Mask over `ids` this write may stamp: rows already ended by
        another txn's marker (lock conflict) or by a commit (optimistic
        conflict) raise; rows already ended by OUR marker are skipped."""
        in_bounds = (ids >= 0) & (ids < self.n)
        cur = np.where(in_bounds, self.end_ts[np.clip(ids, 0, max(self.n - 1, 0))], MAX_TS)
        ours = cur == marker if marker else np.zeros(len(ids), dtype=np.bool_)
        blocked = (cur != MAX_TS) & ~ours & in_bounds
        if blocked.any():
            raise ExecutionError(
                "write conflict: row modified by another transaction "
                f"(table {self.schema.name!r})"
            )
        return in_bounds & ~ours

    def delete_rows(self, row_ids: np.ndarray, end_ts: Optional[int] = None,
                    marker: int = 0, log: Optional["TableTxnLog"] = None) -> int:
        """End rows' visibility at end_ts (a commit ts, or a txn marker for
        provisional deletes). Returns count newly deleted."""
        ids = np.asarray(row_ids, dtype=np.int64)
        ids = ids[self._writable_mask(ids, marker)]
        self.end_ts[ids] = self._next_ts() if end_ts is None else end_ts
        if log is not None:
            log.ended.append(ids)
        self.version += 1
        return len(ids)

    def update_rows(self, row_ids: np.ndarray, updates: Dict[str, list],
                    begin_ts: Optional[int] = None, end_ts: Optional[int] = None,
                    marker: int = 0, log: Optional["TableTxnLog"] = None) -> int:
        """MVCC update: end the old row versions and append new versions
        carrying the updated values (ref: TiDB writes a new MVCC version
        per update; here the version chain is physical-row append)."""
        ids = np.asarray(row_ids, dtype=np.int64)
        keep = self._writable_mask(ids, marker)
        ids = ids[keep]
        m = len(ids)
        if m == 0:
            return 0
        # convert values BEFORE mutating any state: a bad value must leave
        # the table untouched, or an explicit txn could commit half a row
        converted: Dict[str, list] = {}
        for name, vals in updates.items():
            c = self.schema.col(name)
            vals = [v for v, k in zip(vals, keep) if k]
            if c.type_.kind == TypeKind.STRING:
                converted[name] = [None if v is None else str(v) for v in vals]
            else:
                converted[name] = [
                    None if v is None else self.to_device_value(c, v) for v in vals
                ]

        if begin_ts is None and end_ts is None:
            begin_ts = end_ts = self._next_ts()
        self.end_ts[ids] = end_ts

        self._ensure(m)
        start, end = self.n, self.n + m
        for name in self.data:
            self.data[name][start:end] = self.data[name][ids]
            self.valid[name][start:end] = self.valid[name][ids]
        self.begin_ts[start:end] = begin_ts
        self.end_ts[start:end] = MAX_TS
        self.n = end
        if log is not None:
            log.ended.append(ids)
            log.ranges.append((start, end))

        # overwrite the updated columns in the new versions
        for name, vals in converted.items():
            c = self.schema.col(name)
            if c.type_.kind == TypeKind.STRING:
                self._append_strings(name, vals, start, end)
            else:
                for i, v in zip(range(start, end), vals):
                    if v is None:
                        self.valid[name][i] = False
                    else:
                        self.data[name][i] = v
                        self.valid[name][i] = True
        self.version += 1
        return m

    def txn_commit(self, marker: int, commit_ts: int,
                   log: Optional["TableTxnLog"] = None) -> None:
        """Rewrite this txn's markers to the commit timestamp. With a log,
        only the logged rows are touched (O(rows written)); without one,
        the full version arrays are scanned."""
        if log is not None:
            for s, e in log.ranges:
                b = self.begin_ts[s:e]
                b[b == marker] = commit_ts
            for ids in log.ended:
                e_ = self.end_ts[ids]
                self.end_ts[ids] = np.where(e_ == marker, commit_ts, e_)
        else:
            b = self.begin_ts[: self.n]
            e = self.end_ts[: self.n]
            b[b == marker] = commit_ts
            e[e == marker] = commit_ts
        self.version += 1

    def txn_rollback(self, marker: int, log: Optional["TableTxnLog"] = None) -> None:
        """Discard provisional writes; restore provisional deletes."""
        if log is not None:
            # restore deletes first; then kill inserted versions (a row both
            # inserted and deleted by this txn must end up dead)
            for ids in log.ended:
                e_ = self.end_ts[ids]
                self.end_ts[ids] = np.where(e_ == marker, MAX_TS, e_)
            for s, e in log.ranges:
                b = self.begin_ts[s:e]
                dead = b == marker
                self.end_ts[s:e][dead] = 0
                b[dead] = 0
        else:
            b = self.begin_ts[: self.n]
            e = self.end_ts[: self.n]
            dead = b == marker
            e[dead] = 0
            b[dead] = 0
            e[e == marker] = MAX_TS
        self.version += 1

    def truncate(self):
        self.n = 0
        self.version += 1
        self.begin_ts[:] = 0
        self.end_ts[:] = MAX_TS
        for c in self.schema.columns:
            # valid[] must clear: insert paths that omit a column rely on
            # stale slots reading as NULL
            self.valid[c.name][:] = False
            self.data[c.name][:] = 0
            if c.type_.kind == TypeKind.STRING:
                self.dicts[c.name] = Dictionary([])

    # -- reads -------------------------------------------------------------

    def column_slice(self, name: str, start: int, end: int):
        """(data, valid) physical slice incl. dead row versions — executor
        masks them via live_mask."""
        return self.data[name][start:end], self.valid[name][start:end]

    def live_mask(self, start: int, end: int, read_ts: Optional[int] = None,
                  marker: int = 0) -> np.ndarray:
        """Row visibility. read_ts=None reads committed-latest; a snapshot
        read at read_ts additionally sees its own txn's marker writes."""
        b = self.begin_ts[start:end]
        e = self.end_ts[start:end]
        if read_ts is None:
            return (b < TXN_TS_BASE) & (e >= TXN_TS_BASE)
        vis = (b <= read_ts) & (e > read_ts)
        if marker:
            vis = ((b <= read_ts) | (b == marker)) & (e > read_ts) & (e != marker)
        return vis

    def partition_bounds(self, num_partitions: int) -> List[tuple]:
        """Split [0, n) into near-equal contiguous partitions (the region/
        shard analogue for the scan scheduler)."""
        if num_partitions <= 0:
            raise ValueError("num_partitions must be positive")
        edges = np.linspace(0, self.n, num_partitions + 1, dtype=np.int64)
        return [(int(edges[i]), int(edges[i + 1])) for i in range(num_partitions)]
