"""Host columnar tables.

Layout decisions (device-first):
  * column-major numpy buffers in the device representation already
    (scaled ints, day counts, dict codes) so staging to HBM is a straight
    jnp.asarray of a slice — no row pivots on the hot path
  * appends grow buffers geometrically; deletes set a tombstone bit;
    updates write in place (single-writer host model, like the reference's
    single leaseholder per region)
  * each string column owns a sorted Dictionary; appends that introduce new
    strings re-encode the column (dictionaries grow rarely in analytics
    workloads; re-encode is vectorized)
  * `version` bumps on every mutation — executors snapshot (version,
    row_count) so EXPLAIN ANALYZE and the scheduler can detect staleness
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from tidb_tpu.chunk.dictionary import Dictionary
from tidb_tpu.errors import ExecutionError, SchemaError, TypeError_
from tidb_tpu.types import (
    SQLType,
    TypeKind,
    date_to_days,
    datetime_to_micros,
    decimal_to_scaled,
)

__all__ = ["ColumnInfo", "TableSchema", "Table", "TableTxnLog",
           "ShardByInfo"]


@dataclass
class TableTxnLog:
    """Rows one transaction touched in one table, so commit/rollback cost
    O(rows written) not O(table) (ref: the txn's memdb buffer keying the
    2PC mutations)."""

    ranges: List[tuple] = field(default_factory=list)  # appended [start,end)
    ended: List[np.ndarray] = field(default_factory=list)  # end_ts-stamped ids
    # commit-time cache-merge bookkeeping (Table._log_mark): table version
    # before this txn's first logged write, version after its last one,
    # and whether every bump in between was this txn's own
    vstart: int = -1
    vlast: int = -1
    contiguous: bool = True


@dataclass
class ColumnInfo:
    name: str
    type_: SQLType
    not_null: bool = False
    default: object = None
    auto_increment: bool = False
    # the DDL's declared type text (e.g. "varchar(20)") — SQLType erases
    # display-only details like string lengths; SHOW CREATE TABLE needs
    # them back verbatim
    type_text: Optional[str] = None
    # string collation (ref: MySQL per-column collations); None means the
    # MySQL-compatible default (utf8mb4_general_ci — case-insensitive)
    collation: Optional[str] = None
    # online-DDL schema state (ref: the none→delete-only→write-only→
    # public state machine, SURVEY.md:180-185): "write_only" columns are
    # invisible to reads (star expansion, positional INSERT width) but
    # default-filled on writes, so an instance one schema version behind
    # still writes correct rows during ADD COLUMN
    state: str = "public"

    @property
    def coll(self) -> str:
        from tidb_tpu.chunk.dictionary import DEFAULT_COLLATION

        return self.collation or DEFAULT_COLLATION


@dataclass
class FKInfo:
    """A FOREIGN KEY constraint (ref: ddl/ foreign-key DDL + the
    executor's constraint checks): multi-column, with referential
    actions. `parent` is the referenced Table object (wired by the
    catalog at CREATE time), whose `referencing` list holds the
    back-edge for parent-side checks/actions. NULL matching is MySQL's
    simple match: a child row with ANY NULL component passes."""

    columns: List[str]
    parent: object          # storage Table of the referenced table
    parent_cols: List[str]
    name: str = ""
    parent_db: str = ""     # the parent's database (cross-db introspection)
    on_delete: str = "restrict"   # restrict | cascade | set_null
    on_update: str = "restrict"

    @property
    def column(self) -> str:  # single-column convenience (display)
        return self.columns[0]

    @property
    def parent_col(self) -> str:
        return self.parent_cols[0]


@dataclass
class GeneratedInfo:
    """A generated column (ref: MySQL GENERATED ALWAYS AS): `fn` is the
    compiled chunk->Column evaluator over the row's other columns,
    bound at DDL time like CHECK constraints. Both STORED and VIRTUAL
    are materialized at write time here (a columnar engine reads
    columns, not rows — recomputing per read would cost more than the
    storage, so VIRTUAL is accepted syntax with STORED semantics)."""

    col: str
    fn: object
    cols: List[str]
    sql: str
    stored: bool = True


@dataclass
class CheckInfo:
    """A CHECK constraint: bound predicate over this table's columns
    (uids == column names), compiled once at DDL time. SQL semantics:
    a row violates only when the predicate is FALSE — NULL/UNKNOWN
    passes."""

    name: str
    pred: object          # compiled chunk -> Column evaluator
    cols: List[str]
    sql: str


@dataclass
class IndexInfo:
    """Secondary index metadata. Unique indexes are ENFORCED on every
    write (ref: the reference's index KV records + unique-key checks);
    the columnar engine scans by mask, so the index's query-side role is
    the constraint, plus a lazily built sorted lookup for point DML."""

    name: str
    columns: List[str]
    unique: bool = False
    # online-DDL state: "write_only" indexes are maintained/enforced on
    # every write but invisible to the planner's access paths until the
    # backfill validates existing rows and flips them public
    state: str = "public"


@dataclass
class PartitionInfo:
    """Logical table partitioning (ref: MySQL PARTITION BY RANGE/HASH;
    the reference prunes partitions in the planner the same way).
    RANGE: partition i holds rows with uppers[i-1] <= col < uppers[i]
    (None = MAXVALUE). HASH: pid = value % n_parts (NULL rows land in
    partition 0, like MySQL)."""

    kind: str                     # "range" | "hash"
    column: str
    names: List[str] = field(default_factory=list)
    uppers: List[Optional[int]] = field(default_factory=list)  # range
    n_parts: int = 0              # hash

    def count(self) -> int:
        return len(self.names) if self.kind == "range" else self.n_parts

    def part_name(self, pid: int) -> str:
        if self.kind == "range":
            return self.names[pid]
        return f"p{pid}"

    def ids_of_values(self, vals: np.ndarray, valid: np.ndarray) -> np.ndarray:
        """Partition id per value. Without a MAXVALUE partition the
        returned id can equal count() — an overflow the write path
        rejects (_check_partition)."""
        v = np.where(valid, vals.astype(np.int64), 0)
        if self.kind == "hash":
            return np.where(valid, v % max(self.n_parts, 1), 0)
        bounds = np.array(
            [u for u in self.uppers if u is not None], dtype=np.int64)
        pid = np.searchsorted(bounds, v, side="right")
        return np.where(valid, pid, 0)


@dataclass
class ShardByInfo:
    """Cross-worker placement metadata (SHARD BY ... DDL; consumed by
    tidb_tpu/sharding). HASH: shard = mix(value) % shards, NULL -> 0.
    RANGE: `bounds` are k ascending exclusive uppers making k+1 shards
    (shard i holds bounds[i-1] <= value < bounds[i]; the last shard is
    unbounded above), NULL -> 0. `version` bumps on every reshard so
    placement snapshots and plan-cache entries keyed on it invalidate —
    the catalog's schema_version bumps alongside."""

    kind: str                 # "hash" | "range"
    column: str
    shards: int
    bounds: List[int] = field(default_factory=list)  # range only
    version: int = 0


@dataclass
class TableSchema:
    name: str
    columns: List[ColumnInfo]
    primary_key: Optional[List[str]] = None
    # table default COLLATE: applied to later ADD/MODIFY COLUMN when the
    # column declares none (MySQL persists the table default the same way)
    collation: Optional[str] = None
    # PARTITION BY metadata; None = unpartitioned
    partition: Optional[PartitionInfo] = None
    # SHARD BY metadata (cross-worker placement); None = unsharded
    shard_by: Optional[ShardByInfo] = None
    # CLUSTER BY column (ISSUE 18): delta->segment compaction keeps the
    # table physically sorted by this column (ASC, NULLs first) so the
    # columnar store's zone maps prune range filters without the loader
    # having to hand-order ingest; None = no ordered compaction
    cluster_by: Optional[str] = None

    def col(self, name: str) -> ColumnInfo:
        for c in self.columns:
            if c.name == name:
                return c
        raise SchemaError(f"no column {name!r} in table {self.name!r}")

    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def public_columns(self) -> List[ColumnInfo]:
        """Columns visible to reads (online-DDL write_only excluded)."""
        return [c for c in self.columns if c.state == "public"]

    def public_names(self) -> List[str]:
        return [c.name for c in self.public_columns()]


_GROW = 1.5
_MIN_CAP = 1024

# MVCC timestamps: committed rows carry ts < TXN_TS_BASE; an open
# transaction stamps its provisional writes with marker = TXN_TS_BASE +
# txn_id (greater than every possible read_ts, so invisible to others —
# and, sitting in end_ts, an effective row lock). MAX_TS = "not deleted".
TXN_TS_BASE = 1 << 60
MAX_TS = 1 << 62


class Table:
    """Append-friendly columnar store for one table (the default
    ``columnar`` engine of kvapi.TABLE_ENGINE_API)."""

    engine = "columnar"

    def __init__(self, schema: TableSchema):
        self.schema = schema
        self.n = 0  # physical rows incl. dead versions
        self.version = 0
        # bumps whenever EXISTING physical rows' data/valid buffers are
        # rewritten in place (dictionary-growth re-encode, GC
        # compaction, MODIFY/ADD/DROP COLUMN, TRUNCATE) — appends and
        # MVCC timestamp changes don't count. The columnar segment
        # store (tidb_tpu/columnar) snapshots row-range payloads and
        # invalidates on any epoch move; `version` alone over-triggers
        # (every DML bumps it) and under-describes (it can't tell an
        # append from a rewrite).
        self.data_epoch = 0
        # CLUSTER BY watermark: leading physical rows known to be in
        # cluster order. Appends grow `n` past it (the delta is
        # unordered); recluster() advances it to `n`. Order-preserving
        # rewrites (gc's mask compaction) keep a full watermark valid.
        self.clustered_rows = 0
        self._auto_inc = 1
        self._local_ts = 0  # fallback TSO for catalog-less tables
        self.ts_source = None  # catalog-provided TSO (set by create_table)
        # owning catalog (set by create_table): recluster() takes its
        # writer lock and consults its open-txn registry, because the
        # single-writer invariant it must respect is CATALOG-wide (a
        # DML's collect-to-apply window under catalog.lock), not
        # visible from this table's provisional state alone
        self.txn_guard = None
        cap = _MIN_CAP
        self._cap = cap
        self.data: Dict[str, np.ndarray] = {}
        self.valid: Dict[str, np.ndarray] = {}
        self.dicts: Dict[str, Dictionary] = {}
        for c in schema.columns:
            self.data[c.name] = np.zeros(cap, dtype=c.type_.np_dtype)
            self.valid[c.name] = np.zeros(cap, dtype=np.bool_)
            if c.type_.is_dict_encoded:
                self.dicts[c.name] = Dictionary([], c.coll)
        # MVCC visibility range per physical row (see TXN_TS_BASE above)
        self.begin_ts = np.zeros(cap, dtype=np.int64)
        self.end_ts = np.full(cap, MAX_TS, dtype=np.int64)
        self.indexes: Dict[str, IndexInfo] = {}
        if schema.primary_key:
            # the primary key IS a unique index and is ENFORCED like one
            # (ref: the clustered index / unique-key checks on write)
            self.indexes["PRIMARY"] = IndexInfo(
                "PRIMARY", list(schema.primary_key), unique=True)
        # per-unique-index sorted key cache: name -> (version, keys);
        # fresh only across pure inserts, rebuilt lazily otherwise
        self._uniq_cache: Dict[str, tuple] = {}
        self._uniq_pending: Dict[str, np.ndarray] = {}
        # point-lookup cache: index name -> (version, sorted keys, rows)
        self._lookup_cache: Dict[str, tuple] = {}
        # rows provisionally ended per open txn marker (REPLACE/upsert
        # re-insert freedom + O(dead) instead of O(n) scans)
        self._txn_dead: Dict[int, list] = {}
        # rows modified since the last ANALYZE (auto-analyze trigger)
        self.modify_count = 0
        # per-column KMV NDV sketches (statistics.NDVSketch), seeded by
        # ANALYZE and fed by every insert so distinct-count estimates
        # track DML churn between analyzes
        self.ndv_sketch: Dict[str, object] = {}
        # FOREIGN KEY constraints: this table's child-side FKs, and
        # back-edges from tables whose FKs reference THIS table
        self.foreign_keys: List[FKInfo] = []
        self.referencing: List[tuple] = []  # (child Table, FKInfo)
        # fk-check cache: col -> (version, sorted live values)
        self._fk_keys: Dict[str, tuple] = {}
        # CHECK constraints (CheckInfo), wired by the session at DDL time
        self.checks: List[CheckInfo] = []
        # generated columns (GeneratedInfo), wired at DDL time; computed
        # on every write before constraints run
        self.generated: List[GeneratedInfo] = []
        # pessimistic row locks from SELECT ... FOR UPDATE / SHARE
        # (ref: the pessimistic-txn lock CF): rid -> {txn marker: "x"|"s"}.
        # Guarded by the catalog lock like every mutation; writers check
        # it in _writable_mask, commit/rollback release by marker.
        self.row_locks: Dict[int, Dict[int, str]] = {}

    def _next_ts(self) -> int:
        if self.ts_source is not None:
            return self.ts_source()
        self._local_ts += 1
        return self._local_ts

    # -- row count ---------------------------------------------------------

    @property
    def live_rows(self) -> int:
        """Committed-latest row count (provisional writes excluded)."""
        if self.n == 0:
            return 0
        b = self.begin_ts[: self.n]
        e = self.end_ts[: self.n]
        return int(((b < TXN_TS_BASE) & (e >= TXN_TS_BASE)).sum())

    def maintenance_stats(self):
        """(physical_rows, dead_rows) for background-maintenance
        thresholds (auto-analyze / auto-GC). Engines may answer this
        WITHOUT materializing buffered writes — it drives threshold
        checks, not query answers."""
        return self.n, self.n - self.live_rows

    def _ensure(self, extra: int):
        need = self.n + extra
        if need <= self._cap:
            return
        cap = max(int(self._cap * _GROW), need, _MIN_CAP)
        for name in self.data:
            self.data[name] = np.resize(self.data[name], cap)
            self.data[name][self.n:] = 0
            self.valid[name] = np.resize(self.valid[name], cap)
            self.valid[name][self.n:] = False
        self.begin_ts = np.resize(self.begin_ts, cap)
        self.begin_ts[self.n:] = 0
        self.end_ts = np.resize(self.end_ts, cap)
        self.end_ts[self.n:] = MAX_TS
        self._cap = cap

    # -- ingestion ---------------------------------------------------------

    def to_device_value(self, col: ColumnInfo, v):
        """Host python value -> device representation scalar."""
        import datetime

        if v is None:
            return None
        k = col.type_.kind
        try:
            if k == TypeKind.INT:
                return int(v)
            if k == TypeKind.FLOAT:
                return float(v)
            if k == TypeKind.BOOL:
                return bool(v)
            if k == TypeKind.DECIMAL:
                return decimal_to_scaled(v, col.type_.scale)
            if k == TypeKind.DATE:
                if isinstance(v, str):
                    v = datetime.date.fromisoformat(v)
                return date_to_days(v)
            if k == TypeKind.DATETIME:
                if isinstance(v, str):
                    v = datetime.datetime.fromisoformat(v)
                return datetime_to_micros(v)
            if k == TypeKind.TIME:
                from tidb_tpu.types import time_to_micros

                return time_to_micros(v)
            if k == TypeKind.ENUM:
                members = col.type_.members
                if isinstance(v, int):  # 1-based index form
                    if not 1 <= v <= len(members):
                        raise ValueError(f"ENUM index {v} out of range")
                    return v
                try:
                    return members.index(str(v)) + 1
                except ValueError:
                    raise ValueError(f"unknown ENUM member {v!r}")
            if k == TypeKind.SET:
                from tidb_tpu.types import set_to_mask

                return set_to_mask(v, list(col.type_.members))
            if k in (TypeKind.STRING, TypeKind.JSON):
                return str(v)  # encoded in bulk by insert_rows
        except (ValueError, TypeError) as e:
            raise TypeError_(f"bad value {v!r} for column {col.name}: {e}")
        raise TypeError_(f"unsupported type {col.type_}")

    def insert_rows(self, rows: Sequence[Sequence], columns: Optional[List[str]] = None,
                    begin_ts: Optional[int] = None,
                    log: Optional["TableTxnLog"] = None) -> int:
        """Insert python rows (already in logical form; strings as str,
        dates as date/str, decimals as str/float). Returns rows inserted.
        begin_ts: commit timestamp, or a txn marker for provisional writes;
        None commits immediately at the next TSO tick."""
        # positional inserts carry the PUBLIC column width: a writer one
        # schema version behind an in-flight ADD COLUMN (write_only)
        # supplies the old shape and the new column default-fills below
        names = columns or self.insertable_names()
        cols = [self.schema.col(n) for n in names]
        m = len(rows)
        if m == 0:
            return 0
        self._ensure(m)
        start, end = self.n, self.n + m
        provided = set(names)
        # columns not provided get default/NULL/auto-inc
        for c in self.schema.columns:
            if c.name in provided:
                continue
            if c.auto_increment:
                vals = np.arange(self._auto_inc, self._auto_inc + m, dtype=np.int64)
                self._auto_inc += m
                self.data[c.name][start:end] = vals
                self.valid[c.name][start:end] = True
            elif c.default is not None:
                dv = self.to_device_value(c, c.default)
                if c.type_.is_dict_encoded:
                    self._append_strings(c.name, [dv] * m, start, end)
                else:
                    self.data[c.name][start:end] = dv
                    self.valid[c.name][start:end] = True
            elif c.not_null and not any(
                    g.col == c.name for g in self.generated):
                # generated columns compute below (_apply_generated),
                # so NOT NULL on them never needs a default
                raise ExecutionError(f"column {c.name!r} has no default and is NOT NULL")
            # else: stays NULL
        for j, (name, c) in enumerate(zip(names, cols)):
            vals = [self.to_device_value(c, r[j]) for r in rows]
            if any(v is None for v in vals) and c.not_null:
                raise ExecutionError(f"NULL in NOT NULL column {c.name!r}")
            if c.type_.is_dict_encoded:
                self._append_strings(name, vals, start, end)
            else:
                arr = self.data[name]
                vd = self.valid[name]
                for i, v in enumerate(vals):
                    if v is None:
                        vd[start + i] = False
                    else:
                        arr[start + i] = v
                        vd[start + i] = True
        # marker exclusion (rows this txn deleted don't conflict) costs an
        # O(n) end_ts scan — only pay it when the txn actually deleted
        # something in this table (REPLACE / upsert flows)
        in_txn = begin_ts is not None and begin_ts >= TXN_TS_BASE
        txn_deleted = log is not None and bool(log.ended)
        self._apply_generated(start, end)
        self._enforce_unique_new(
            start, end, marker=begin_ts if in_txn and txn_deleted else None)
        self._check_fk_parents(start, end)
        self._check_row_constraints(start, end)
        self._check_partition(start, end)
        # before n advances: a violation leaves the table untouched
        self.begin_ts[start:end] = self._next_ts() if begin_ts is None else begin_ts
        self.end_ts[start:end] = MAX_TS
        self.n = end
        if log is not None:
            log.ranges.append((start, end))
        self.version += 1
        if log is not None:
            self._log_mark(log)
        self._uniq_commit()
        self._sketch_insert(start, end)
        return m

    def insert_columns(self, arrays: Dict[str, np.ndarray], valids: Optional[Dict[str, np.ndarray]] = None, strings: Optional[Dict[str, list]] = None):
        """Bulk columnar ingest (datagen / LOAD). `arrays` hold device reprs
        for non-string columns; `strings` holds raw python strings per
        string column."""
        sizes = [len(a) for a in arrays.values()] + [len(s) for s in (strings or {}).values()]
        if not sizes:
            return 0
        m = sizes[0]
        if any(s != m for s in sizes):
            raise ExecutionError(f"bulk insert length mismatch: {sizes}")
        self._ensure(m)
        start, end = self.n, self.n + m
        for c in self.schema.columns:
            name = c.name
            if strings and name in strings:
                self._append_strings(name, strings[name], start, end)
            elif name in arrays:
                self.data[name][start:end] = arrays[name].astype(c.type_.np_dtype, copy=False)
                if valids and name in valids:
                    self.valid[name][start:end] = valids[name]
                else:
                    self.valid[name][start:end] = True
            elif c.not_null and not any(
                    g.col == c.name for g in self.generated):
                raise ExecutionError(f"bulk insert missing NOT NULL column {name!r}")
        self._apply_generated(start, end)
        self._enforce_unique_new(start, end)
        self._check_fk_parents(start, end)
        self._check_row_constraints(start, end)
        self._check_partition(start, end)
        self.begin_ts[start:end] = 0  # bulk loads are committed "at origin"
        self.end_ts[start:end] = MAX_TS
        self.n = end
        self.version += 1
        self._uniq_commit()
        self._sketch_insert(start, end)
        return m

    # -- foreign keys ------------------------------------------------------

    def _fk_decode(self, col: str, vals: np.ndarray,
                   fold: bool = True) -> np.ndarray:
        """Decode this table's values of `col` for cross-table FK
        comparison: the collation FOLD KEY for dict columns (so
        'abc' matches a parent's 'ABC' under _ci — canonical codes are
        table-local and must never cross tables), raw otherwise.
        fold=False decodes the raw stored strings — what a cascade WRITE
        must use, or a _ci cascade would lowercase the child's data."""
        dic = self.dicts.get(col)
        if dic is None:
            return vals
        if not fold:
            return np.array(
                [dic.values[int(c)] for c in vals], dtype=object)
        return np.array(
            [dic.fold(dic.values[int(c)]) for c in vals], dtype=object)

    def _fk_tuples(self, cols: List[str], rows: np.ndarray):
        """(key tuples, all-components-valid mask) at `rows` — MySQL's
        simple match: a row with ANY NULL component never participates."""
        ok = np.ones(len(rows), dtype=np.bool_)
        for c in cols:
            ok &= self.valid[c][rows]
        sel = rows[ok]
        decoded = [self._fk_decode(c, self.data[c][sel]) for c in cols]
        return list(zip(*decoded)) if len(sel) else [], ok

    def _live_key_tuples(self, cols: List[str]) -> set:
        """Key-tuple set of present rows (the parent side of an FK
        probe), cached per version; values are decoded so they compare
        across tables."""
        key = tuple(cols)
        hit = self._fk_keys.get(key)
        if hit is not None and hit[0] == self.version:
            return hit[1]
        present = np.nonzero(self._present_mask())[0]
        tuples, _ok = self._fk_tuples(cols, present)
        keys = set(tuples)
        self._fk_keys[key] = (self.version, keys)
        return keys

    def _live_key_array(self, col: str) -> np.ndarray:
        """Single-column vectorized variant of _live_key_tuples: sorted
        unique decoded values of present rows, cached per version —
        keeps the common one-column FK probe on the np.isin fast path."""
        key = (col, "arr")
        hit = self._fk_keys.get(key)
        if hit is not None and hit[0] == self.version:
            return hit[1]
        present = self._present_mask()
        vals = self.data[col][: self.n][present & self.valid[col][: self.n]]
        keys = np.unique(vals)
        dic = self.dicts.get(col)
        if dic is not None:
            keys = np.unique(np.array(
                [dic.fold(dic.values[int(c)]) for c in keys], dtype=object))
        self._fk_keys[key] = (self.version, keys)
        return keys

    def _check_fk_parents(self, start: int, end: int,
                          cols: Optional[set] = None,
                          fks=None, live_only: bool = False) -> None:
        """Every fully-non-NULL FK key in rows [start, end) must exist
        in its parent (RESTRICT on the child write). Raises BEFORE the
        rows become visible. `fks` restricts to specific constraints and
        `live_only` to present row versions (ALTER TABLE ADD FOREIGN KEY
        back-filling existing data)."""
        rows = np.arange(start, end)
        if live_only:
            rows = rows[self._present_mask()[start:end]]
        for fk in (fks if fks is not None else self.foreign_keys):
            if cols is not None and not (set(fk.columns) & cols):
                continue
            if len(fk.columns) == 1:
                # vectorized single-column fast path (the common case)
                c = fk.columns[0]
                vd = self.valid[c][rows]
                vals = self._fk_decode(c, self.data[c][rows][vd])
                if not len(vals):
                    continue
                keys = fk.parent._live_key_array(fk.parent_cols[0])
                ok = np.isin(vals, keys)
                if not ok.all():
                    raise ExecutionError(
                        f"foreign key {fk.name or fk.column!r} violation: "
                        f"{vals[~ok][0]!r} not present in "
                        f"{fk.parent.schema.name}.{fk.parent_cols[0]}")
                continue
            tuples, _ok = self._fk_tuples(fk.columns, rows)
            if not tuples:
                continue
            keys = fk.parent._live_key_tuples(fk.parent_cols)
            for t in tuples:
                if t not in keys:
                    raise ExecutionError(
                        f"foreign key {fk.name or fk.column!r} violation: "
                        f"{t if len(t) > 1 else t[0]!r} not present in "
                        f"{fk.parent.schema.name}"
                        f".({', '.join(fk.parent_cols)})")

    def _fk_referencing_rows(self, cols: List[str], keys: set) -> np.ndarray:
        """Present row ids whose (fully non-NULL) FK tuple is in `keys`."""
        present = np.nonzero(self._present_mask())[0]
        if len(cols) == 1:
            c = cols[0]
            vd = self.valid[c][present]
            sel = present[vd]
            if not len(sel):
                return np.zeros(0, dtype=np.int64)
            vals = self._fk_decode(c, self.data[c][sel])
            karr = np.array([k[0] for k in keys], dtype=object)
            return sel[np.isin(vals, karr)]
        tuples, ok = self._fk_tuples(cols, present)
        sel = present[ok]
        if not tuples:
            return np.zeros(0, dtype=np.int64)
        hit = np.fromiter((t in keys for t in tuples), dtype=np.bool_,
                          count=len(tuples))
        return sel[hit]

    def _fk_tuples_aligned(self, cols: List[str], rows: np.ndarray,
                           fold: bool = True):
        """Row-aligned key tuples with None for NULL components.
        fold=True yields comparison keys; fold=False the raw values."""
        out = []
        for i in rows.tolist():
            t = []
            for c in cols:
                if self.valid[c][i]:
                    t.append(self._fk_decode(
                        c, self.data[c][i:i + 1], fold=fold)[0])
                else:
                    t.append(None)
            out.append(tuple(t))
        return out

    def _check_fk_children(self, ids: np.ndarray, *, action: str = "delete",
                           end_ts=None, marker: int = 0, log_for=None,
                           new_rows: Optional[np.ndarray] = None,
                           depth: int = 0, phase: str = "both") -> None:
        """Rows `ids` are about to be deleted (action="delete") or have
        their key columns rewritten (action="update", with `new_keys`
        mapping old key tuple -> new key tuple). Applies each child FK's
        referential action: restrict raises, cascade deletes/updates the
        child rows (recursively, bounded like MySQL's 15-level cascade
        limit), set_null NULLs the child key columns. `log_for` maps a
        child Table to its TableTxnLog so cascaded writes stay inside
        the caller's transaction."""
        if not self.referencing or not len(ids):
            return
        if depth > 15:
            raise ExecutionError("foreign key cascade depth exceeded")
        for child, fk in list(self.referencing):
            act = fk.on_delete if action == "delete" else fk.on_update
            # phase="pre" runs BEFORE the parent mutation (abort-early
            # restrict checks); phase="post" runs after the parent's new
            # versions are visible, so a cascaded child write re-checks
            # its FK against the UPDATED parent keys
            if phase == "pre" and act != "restrict":
                continue
            if phase == "post" and act == "restrict":
                continue
            tuples, _ok = self._fk_tuples(fk.parent_cols, ids)
            keys = set(tuples)
            if not keys:
                continue
            rows = child._fk_referencing_rows(fk.columns, keys)
            if not len(rows):
                continue
            if act == "restrict":
                hit_c, _ok = child._fk_tuples(fk.columns, rows[:1])
                bad = hit_c[0] if hit_c else "?"
                raise ExecutionError(
                    f"cannot delete or update {self.schema.name!r} row: "
                    f"key {bad if len(fk.columns) > 1 else bad[0]!r} is "
                    f"referenced by "
                    f"{child.schema.name}.({', '.join(fk.columns)})")
            clog = log_for(child) if log_for is not None else None
            if act == "set_null":
                for c in fk.columns:
                    if child.schema.col(c).not_null:
                        raise ExecutionError(
                            f"FK {fk.name!r} ON {action.upper()} SET NULL: "
                            f"{child.schema.name}.{c} is NOT NULL")
                child.update_rows(
                    rows, {c: [None] * len(rows) for c in fk.columns},
                    begin_ts=marker or None, end_ts=end_ts if marker else None,
                    marker=marker, log=clog, log_for=log_for,
                    _fk_depth=depth + 1)
            elif act == "cascade" and action == "delete":
                child.delete_rows(rows, end_ts=end_ts, marker=marker,
                                  log=clog, log_for=log_for,
                                  _fk_depth=depth + 1)
            elif act == "cascade":  # update: rewrite child keys old->new
                # match on FOLD keys (how the referencing rows were
                # found), but write the parent's RAW new values — a _ci
                # cascade must not replace 'BOB' with its fold 'bob'
                old_al = self._fk_tuples_aligned(fk.parent_cols, ids)
                new_raw = self._fk_tuples_aligned(
                    fk.parent_cols,
                    new_rows if new_rows is not None else ids, fold=False)
                new_keys = {o: n for o, n in zip(old_al, new_raw)
                            if None not in o}
                tuples_c, ok_c = child._fk_tuples(fk.columns, rows)
                rows_ok = rows[ok_c]
                raw_c = child._fk_tuples_aligned(fk.columns, rows_ok,
                                                 fold=False)
                updates = {c: [] for c in fk.columns}
                for t, raw in zip(tuples_c, raw_c):
                    # unmatched keys keep the child's own raw value
                    nt = new_keys.get(t, raw)
                    for c, v in zip(fk.columns, nt):
                        updates[c].append(v)
                child.update_rows(
                    rows_ok, updates,
                    begin_ts=marker or None, end_ts=end_ts if marker else None,
                    marker=marker, log=clog, log_for=log_for,
                    _fk_depth=depth + 1)

    def _apply_generated(self, start: int, end: int) -> None:
        """Materialize generated columns for buffer rows [start, end)
        from their source columns — BEFORE uniqueness/CHECK/FK
        validation, which may reference them."""
        if not self.generated:
            return
        from tidb_tpu.chunk.chunk import Chunk
        from tidb_tpu.chunk.column import Column
        from tidb_tpu.utils.device import host_eager

        n = end - start
        cap = 8
        while cap < n:
            cap *= 2
        for gen in self.generated:
            cs = {}
            for cname in gen.cols:
                t = self.schema.col(cname).type_
                cs[cname] = Column.from_numpy(
                    self.data[cname][start:end], t,
                    valid=self.valid[cname][start:end], capacity=cap)
            sel = np.zeros(cap, dtype=np.bool_)
            sel[:n] = True
            with host_eager():
                col = gen.fn(Chunk(cs, sel))
                data = np.asarray(col.data)[:n]
                valid = np.asarray(col.valid)[:n]
            col = self.schema.col(gen.col)
            if col.not_null and not valid.all():
                raise ExecutionError(
                    f"generated column {gen.col!r} computed NULL but is "
                    "declared NOT NULL")
            self.data[gen.col][start:end] = data.astype(
                col.type_.np_dtype, copy=False)
            self.valid[gen.col][start:end] = valid

    def insertable_names(self) -> List[str]:
        """Positional-INSERT width: public columns minus generated ones
        (their values are never supplied; MySQL requires DEFAULT in the
        slot — omitting the slot entirely is the friendlier contract
        for a columnar engine and keeps old writers working)."""
        gen = {g.col for g in self.generated}
        return [n for n in self.schema.public_names() if n not in gen]

    def _check_row_constraints(self, start: int, end: int,
                               cols: Optional[set] = None,
                               live_only: bool = False,
                               checks=None) -> None:
        """CHECK constraints over rows [start, end): violation =
        predicate FALSE (NULL passes, per SQL). Runs the compiled
        evaluator on the host backend regardless of the default device.
        `live_only` restricts to present row versions (ALTER TABLE ADD
        CHECK validating existing data must skip dead versions)."""
        if not self.checks:
            return
        from tidb_tpu.chunk.chunk import Chunk
        from tidb_tpu.chunk.column import Column
        from tidb_tpu.utils.device import host_eager

        n = end - start
        cap = 8
        while cap < n:
            cap *= 2
        rows_live = None
        if live_only:
            rows_live = self._present_mask()[start:end]
        for chk in (checks if checks is not None else self.checks):
            if cols is not None and not (set(chk.cols) & cols):
                continue
            cs = {}
            for cname in chk.cols:
                t = self.schema.col(cname).type_
                cs[cname] = Column.from_numpy(
                    self.data[cname][start:end], t,
                    valid=self.valid[cname][start:end], capacity=cap)
            sel = np.zeros(cap, dtype=np.bool_)
            sel[:n] = True
            with host_eager():
                col = chk.pred(Chunk(cs, sel))
                data = np.asarray(col.data)[:n]
                valid = np.asarray(col.valid)[:n]
            bad = valid & ~data.astype(bool)
            if rows_live is not None:
                bad &= rows_live
            if bad.any():
                raise ExecutionError(
                    f"CHECK constraint {chk.name!r} violated: ({chk.sql})")

    def _sketch_insert(self, start: int, end: int) -> None:
        """Feed newly written rows into the per-column NDV sketches (a
        no-op until ANALYZE seeds them). Dict-encoded columns hash the
        decoded strings — codes shift when the sorted dictionary grows,
        so they are not stable identities over time."""
        if not self.ndv_sketch:
            return
        from tidb_tpu.statistics import hash_column_values

        for name, sk in self.ndv_sketch.items():
            vd = self.valid[name][start:end]
            vals = self.data[name][start:end][vd]
            if not len(vals):
                continue
            sk.update(hash_column_values(vals, self.dicts.get(name)))

    def ingest_encoded(self, arrays: Dict[str, np.ndarray],
                       pools: Dict[str, list]) -> int:
        """Bulk ingest with PRE-ENCODED dictionary codes (the native
        data-loader path): string columns arrive as int codes indexing
        their sorted unique `pools` entry; no Python string objects are
        materialized for the rows. Table must be empty."""
        if self.n:
            raise ExecutionError("encoded ingest requires an empty table")
        sizes = {len(a) for a in arrays.values()}
        if len(sizes) != 1:
            raise ExecutionError(f"encoded ingest length mismatch: {sizes}")
        m = sizes.pop()
        self._ensure(m)
        for c in self.schema.columns:
            name = c.name
            if name in pools:
                pool = pools[name]
                if sorted(set(pool)) != list(pool):
                    raise ExecutionError(
                        f"pool for {name!r} must be sorted and unique")
                codes = arrays.get(name)
                if codes is not None and len(codes) and (
                        codes.min() < 0 or codes.max() >= len(pool)):
                    raise ExecutionError(
                        f"codes for {name!r} outside [0, {len(pool)})")
                d = Dictionary(pool, c.coll)
                self.dicts[name] = d
                if codes is not None and d.values != list(pool):
                    # a _ci collation reorders the bytewise pool: remap
                    # the pre-encoded codes onto the collation order
                    remap = np.array([d._index[v] for v in pool],
                                     dtype=np.int32)
                    arrays[name] = remap[codes]
            if name in arrays:
                self.data[name][:m] = arrays[name].astype(
                    c.type_.np_dtype, copy=False)
                self.valid[name][:m] = True
            elif c.not_null:
                raise ExecutionError(f"encoded ingest missing NOT NULL {name!r}")
        self._enforce_unique_new(0, m)
        self.begin_ts[:m] = 0
        self.end_ts[:m] = MAX_TS
        self.n = m
        self.version += 1
        self._uniq_commit()
        return m

    def _append_strings(self, name: str, vals: list, start: int, end: int):
        d = self.dicts[name]
        new = {v for v in vals if v is not None and v not in d}
        if new:
            # dictionary grows: build union dict and re-encode existing codes
            nd = Dictionary(list(d.values) + list(new), d.collation)
            if self.n > 0 and len(d) > 0:
                trans = d.translate_to(nd)
                self.data[name][: self.n] = trans[self.data[name][: self.n]]
            self.dicts[name] = nd
            d = nd
            # re-encoding is a physical change: cached structures keyed on
            # version (unique-key sets, shardings) must see it NOW — a
            # unique check later in this same statement would otherwise
            # compare old-code cache entries against new-code rows
            self.version += 1
            self.data_epoch += 1  # existing codes rewrote in place
        codes, valid = d.encode_with(vals)
        self.data[name][start:end] = codes
        self.valid[name][start:end] = valid

    # -- mutation ----------------------------------------------------------

    def _writable_mask(self, ids: np.ndarray, marker: int) -> np.ndarray:
        """Mask over `ids` this write may stamp: rows already ended by
        another txn's marker (lock conflict) or by a commit (optimistic
        conflict) raise; rows already ended by OUR marker are skipped.
        Rows pessimistically locked by ANOTHER txn (FOR UPDATE/SHARE)
        also conflict — a shared lock blocks writers too."""
        if self.row_locks:
            for rid in ids.tolist():
                holders = self.row_locks.get(int(rid))
                if holders and any(m != marker for m in holders):
                    from tidb_tpu.errors import WriteConflictError

                    raise WriteConflictError(
                        "write conflict: row locked by another "
                        f"transaction (table {self.schema.name!r})")
        in_bounds = (ids >= 0) & (ids < self.n)
        clipped = np.clip(ids, 0, max(self.n - 1, 0))
        cur = np.where(in_bounds, self.end_ts[clipped], MAX_TS)
        ours = cur == marker if marker else np.zeros(len(ids), dtype=np.bool_)
        blocked = (cur != MAX_TS) & ~ours & in_bounds
        # another txn's UNCOMMITTED insert is a lock too: its end_ts is
        # still MAX_TS, but its begin_ts marker makes it untouchable
        bts = np.where(in_bounds, self.begin_ts[clipped], 0)
        blocked |= (bts >= TXN_TS_BASE) & (bts != marker) & in_bounds
        if blocked.any():
            from tidb_tpu.errors import WriteConflictError

            raise WriteConflictError(
                "write conflict: row modified by another transaction "
                f"(table {self.schema.name!r})"
            )
        return in_bounds & ~ours

    def lock_conflict(self, ids: np.ndarray, marker: int, mode: str):
        """First conflict preventing `marker` from locking `ids` in
        `mode` ("x"|"s"), or None. Caller holds the catalog lock.
        Conflicts: another holder when either side is exclusive, or a
        provisional write (insert/update/delete marker) by another txn."""
        for rid in ids.tolist():
            holders = self.row_locks.get(int(rid))
            if holders and any(
                    m != marker and (mode == "x" or md == "x")
                    for m, md in holders.items()):
                return f"row {int(rid)} locked"
        if len(ids):
            in_b = (ids >= 0) & (ids < self.n)
            cl = np.clip(ids, 0, max(self.n - 1, 0))
            ets = np.where(in_b, self.end_ts[cl], MAX_TS)
            bts = np.where(in_b, self.begin_ts[cl], 0)
            prov = ((ets >= TXN_TS_BASE) & (ets < MAX_TS) & (ets != marker)) \
                | ((bts >= TXN_TS_BASE) & (bts != marker))
            if prov.any():
                return f"row {int(ids[prov.argmax()])} has an uncommitted write"
        return None

    def lock_rows(self, ids: np.ndarray, marker: int, mode: str) -> None:
        """Register `marker`'s locks over `ids` (no conflict checking —
        call lock_conflict first, same catalog-lock hold). An existing
        shared lock upgrades to exclusive, never downgrades."""
        for rid in ids.tolist():
            holders = self.row_locks.setdefault(int(rid), {})
            if mode == "x" or holders.get(marker) != "x":
                holders[marker] = mode

    def release_locks(self, marker: int) -> None:
        """Drop every lock `marker` holds (commit/rollback/resolve)."""
        if not self.row_locks:
            return
        for rid in list(self.row_locks):
            holders = self.row_locks[rid]
            if holders.pop(marker, None) is not None and not holders:
                del self.row_locks[rid]

    def delete_rows(self, row_ids: np.ndarray, end_ts: Optional[int] = None,
                    marker: int = 0, log: Optional["TableTxnLog"] = None,
                    log_for=None, _fk_depth: int = 0) -> int:
        """End rows' visibility at end_ts (a commit ts, or a txn marker for
        provisional deletes). Returns count newly deleted. `log_for`
        maps child tables to their txn logs so ON DELETE CASCADE /
        SET NULL writes join the caller's transaction."""
        ids = np.asarray(row_ids, dtype=np.int64)
        ids = ids[self._writable_mask(ids, marker)]
        self._check_fk_children(ids, action="delete", end_ts=end_ts,
                                marker=marker, log_for=log_for,
                                depth=_fk_depth)
        self.end_ts[ids] = self._next_ts() if end_ts is None else end_ts
        if end_ts is not None and end_ts >= TXN_TS_BASE and len(ids):
            self._txn_dead.setdefault(end_ts, []).extend(ids.tolist())
        if log is not None:
            log.ended.append(ids)
        self.version += 1
        if log is not None:
            self._log_mark(log)
        return len(ids)

    def update_rows(self, row_ids: np.ndarray, updates: Dict[str, list],
                    begin_ts: Optional[int] = None, end_ts: Optional[int] = None,
                    marker: int = 0, log: Optional["TableTxnLog"] = None,
                    log_for=None, _fk_depth: int = 0) -> int:
        """MVCC update: end the old row versions and append new versions
        carrying the updated values (ref: TiDB writes a new MVCC version
        per update; here the version chain is physical-row append)."""
        ids = np.asarray(row_ids, dtype=np.int64)
        keep = self._writable_mask(ids, marker)
        ids = ids[keep]
        m = len(ids)
        if m == 0:
            return 0
        # convert values BEFORE mutating any state: a bad value must leave
        # the table untouched, or an explicit txn could commit half a row
        converted: Dict[str, list] = {}
        for name, vals in updates.items():
            c = self.schema.col(name)
            vals = [v for v, k in zip(vals, keep) if k]
            if c.type_.is_dict_encoded:
                converted[name] = [None if v is None else str(v) for v in vals]
            else:
                converted[name] = [
                    None if v is None else self.to_device_value(c, v) for v in vals
                ]

        if begin_ts is None and end_ts is None:
            begin_ts = end_ts = self._next_ts()

        # write the new versions into buffer slots FIRST (n not advanced,
        # old versions not ended): a unique violation must leave the
        # table untouched
        self._ensure(m)
        start, end = self.n, self.n + m
        for name in self.data:
            self.data[name][start:end] = self.data[name][ids]
            self.valid[name][start:end] = self.valid[name][ids]
        # overwrite the updated columns in the new versions
        for name, vals in converted.items():
            c = self.schema.col(name)
            if c.type_.is_dict_encoded:
                self._append_strings(name, vals, start, end)
            else:
                for i, v in zip(range(start, end), vals):
                    if v is None:
                        self.valid[name][i] = False
                    else:
                        self.data[name][i] = v
                        self.valid[name][i] = True
        self._apply_generated(start, end)
        if any(ix.unique for ix in self.indexes.values()):
            # the replaced versions don't count as present for uniqueness;
            # full-scan check (the incremental cache can't express the
            # simultaneous remove+add of an update). Rejected slots clear
            # their valid bits so stale values never resurrect.
            saved = self.end_ts[ids].copy()
            self.end_ts[ids] = 0
            try:
                for ix in self.indexes.values():
                    if ix.unique:
                        self._check_unique(ix, extra=(start, end), marker=end_ts if end_ts >= TXN_TS_BASE else None)
            except ExecutionError:
                for name in self.valid:
                    self.valid[name][start:end] = False
                raise
            finally:
                self.end_ts[ids] = saved

        upd_cols = set(converted)
        try:
            self._check_fk_parents(start, end, cols=upd_cols)
            self._check_row_constraints(start, end, cols=upd_cols)
            if (self.schema.partition is not None
                    and self.schema.partition.column in upd_cols):
                self._check_partition(start, end)
            ref_cols = set()
            for _c, fk in self.referencing:
                ref_cols |= set(fk.parent_cols)
            fk_changed = None
            if ref_cols & upd_cols:
                changed = np.zeros(len(ids), dtype=np.bool_)
                for pcol in ref_cols & upd_cols:
                    old = self.data[pcol][ids]
                    ov = self.valid[pcol][ids]
                    new = self.data[pcol][start:end]
                    nv = self.valid[pcol][start:end]
                    changed |= (ov != nv) | (ov & nv & (old != new))
                if changed.any():
                    fk_changed = (ids[changed].copy(),
                                  np.arange(start, end)[changed])
                    # abort-early half: ON UPDATE RESTRICT children
                    self._check_fk_children(
                        fk_changed[0], action="update", phase="pre",
                        depth=_fk_depth)
        except ExecutionError:
            for name in self.valid:
                self.valid[name][start:end] = False
            raise
        self.end_ts[ids] = end_ts
        if end_ts >= TXN_TS_BASE and m:
            self._txn_dead.setdefault(end_ts, []).extend(ids.tolist())
        self.begin_ts[start:end] = begin_ts
        self.end_ts[start:end] = MAX_TS
        self.n = end
        if log is not None:
            log.ended.append(ids)
            log.ranges.append((start, end))
        self.version += 1
        if log is not None:
            self._log_mark(log)
        self._sketch_insert(start, end)
        if fk_changed is not None:
            # action half AFTER the new parent keys are visible, so a
            # cascaded child write FK-checks against the updated parent;
            # statement atomicity on a mid-cascade failure is the txn
            # layer's (marker rollback), like any multi-table statement
            self._check_fk_children(
                fk_changed[0], action="update", phase="post",
                end_ts=end_ts, marker=marker, log_for=log_for,
                new_rows=fk_changed[1], depth=_fk_depth)
        return m

    def _log_mark(self, log: "TableTxnLog") -> None:
        """Called right after each logged mutation's version bump.
        Records the version window this txn's writes span so txn_commit
        can tell whether a point-lookup cache predates the txn (safe to
        merge the new rows into) or postdates its last write (already
        complete). `contiguous` survives only if every bump since
        `vstart` was this txn's own — a foreign bump (another writer,
        GC compaction moving physical ids) disables merging."""
        if log.vstart < 0:
            log.vstart = self.version - 1
        elif log.vlast != self.version - 1:
            log.contiguous = False
        log.vlast = self.version

    def txn_commit(self, marker: int, commit_ts: int,
                   log: Optional["TableTxnLog"] = None) -> None:
        """Rewrite this txn's markers to the commit timestamp. With a log,
        only the logged rows are touched (O(rows written)); without one,
        the full version arrays are scanned."""
        self._txn_dead.pop(marker, None)
        vbefore = self.version
        if log is not None:
            for s, e in log.ranges:
                b = self.begin_ts[s:e]
                b[b == marker] = commit_ts
                self.modify_count += e - s
            for ids in log.ended:
                e_ = self.end_ts[ids]
                self.end_ts[ids] = np.where(e_ == marker, commit_ts, e_)
                self.modify_count += len(ids)
        else:
            b = self.begin_ts[: self.n]
            e = self.end_ts[: self.n]
            bm = b == marker
            em = e == marker
            if not bm.any() and not em.any():
                return  # no residue here: don't invalidate caches
            b[bm] = commit_ts
            e[em] = commit_ts
            # full-scan commits must still advance the auto-analyze
            # trigger or stats silently go stale for these workloads
            self.modify_count += int(bm.sum()) + int(em.sum())
        self.version += 1
        if log is not None and not log.ended:
            # a pure-insert commit doesn't change the present key set:
            # carry fresh unique caches forward so autocommit insert
            # workloads keep the O(m log n) merge path instead of
            # re-sorting the table every statement
            for name, (v, keys) in list(self._uniq_cache.items()):
                if v == vbefore:
                    self._uniq_cache[name] = (self.version, keys)
            # point-lookup caches: one built AFTER this txn's last write
            # (v == vbefore — inserts bump version at write time, and
            # index_lookup rebuilds from all physical rows, so it already
            # holds the new ids) is complete — carry it forward untouched;
            # merging it back in would duplicate the new rows on every
            # subsequent point get. One built just BEFORE the txn's first
            # write (v == vstart, with no foreign bump in the window —
            # _log_mark's contiguity proof) predates the new physical
            # positions: MERGE them in, O(m log n + n) memcpy instead of
            # a full re-sort on the next probe (autocommit insert path).
            if self._lookup_cache:
                new_ids = (np.concatenate([np.arange(s, e) for s, e in log.ranges])
                           if log.ranges else np.zeros(0, dtype=np.int64))
                mergeable = (log.contiguous and log.vstart >= 0
                             and log.vlast == vbefore)
                for name, hit in list(self._lookup_cache.items()):
                    v, skeys, srows = hit
                    idx = self.indexes.get(name)
                    if idx is None:
                        del self._lookup_cache[name]
                        continue
                    if v == vbefore:
                        # commit only rewrites timestamps, not keys/rows
                        self._lookup_cache[name] = (self.version, skeys, srows)
                        continue
                    if not (mergeable and v == log.vstart):
                        continue  # stale: next probe rebuilds
                    mat, ids = self._uniq_key_rows(idx, new_ids)
                    add = np.ascontiguousarray(mat).view(skeys.dtype).reshape(-1)
                    order = np.argsort(add, kind="stable")
                    add, ids = add[order], ids[order]
                    pos = np.searchsorted(skeys, add)
                    self._lookup_cache[name] = (
                        self.version,
                        np.insert(skeys, pos, add),
                        np.insert(srows, pos, ids),
                    )

    def txn_rollback(self, marker: int, log: Optional["TableTxnLog"] = None) -> None:
        """Discard provisional writes; restore provisional deletes."""
        self._txn_dead.pop(marker, None)
        if log is not None:
            # restore deletes first; then kill inserted versions (a row both
            # inserted and deleted by this txn must end up dead)
            for ids in log.ended:
                e_ = self.end_ts[ids]
                self.end_ts[ids] = np.where(e_ == marker, MAX_TS, e_)
            for s, e in log.ranges:
                b = self.begin_ts[s:e]
                dead = b == marker
                self.end_ts[s:e][dead] = 0
                b[dead] = 0
        else:
            b = self.begin_ts[: self.n]
            e = self.end_ts[: self.n]
            dead = b == marker
            # rows both inserted and deleted by this txn must end dead:
            # only restore provisional deletes of rows we didn't insert
            em = (e == marker) & ~dead
            if not dead.any() and not em.any():
                return  # no residue here: don't invalidate caches
            e[dead] = 0
            b[dead] = 0
            e[em] = MAX_TS
        self.version += 1

    # -- DDL ---------------------------------------------------------------
    # (ref: ddl/ online schema change; single-process => synchronous, but
    # the backfill-over-existing-rows step is the same job)

    def add_column(self, col: ColumnInfo) -> None:
        if any(c.name == col.name for c in self.schema.columns):
            raise SchemaError(f"duplicate column {col.name!r}")
        if col.not_null and col.default is None and self.live_rows > 0:
            raise ExecutionError(
                f"cannot add NOT NULL column {col.name!r} without DEFAULT "
                "to a non-empty table")
        self.schema.columns.append(col)
        self.data[col.name] = np.zeros(self._cap, dtype=col.type_.np_dtype)
        self.valid[col.name] = np.zeros(self._cap, dtype=np.bool_)
        if col.type_.is_dict_encoded:
            self.dicts[col.name] = Dictionary([], col.coll)
        if col.default is not None:
            # backfill existing rows with the default
            dv = self.to_device_value(col, col.default)
            if col.type_.is_dict_encoded:
                self._append_strings(col.name, [dv] * self.n, 0, self.n)
            else:
                self.data[col.name][: self.n] = dv
                self.valid[col.name][: self.n] = True
        self.version += 1
        self.data_epoch += 1  # column set changed under existing rows

    def drop_column(self, name: str) -> None:
        if any(name in fk.columns for fk in self.foreign_keys) or any(
                name in fk.parent_cols for _c, fk in self.referencing):
            raise SchemaError(
                f"cannot drop column {name!r}: used by a foreign key")
        if any(name in chk.cols for chk in self.checks):
            raise SchemaError(
                f"cannot drop column {name!r}: used by a CHECK constraint")
        col = self.schema.col(name)  # raises if absent
        if self.schema.primary_key and name in self.schema.primary_key:
            raise ExecutionError(f"cannot drop primary-key column {name!r}")
        for idx in self.indexes.values():
            if name in idx.columns:
                raise ExecutionError(
                    f"cannot drop column {name!r}: used by index {idx.name!r}")
        self.schema.columns.remove(col)
        del self.data[name]
        del self.valid[name]
        self.dicts.pop(name, None)
        if self.schema.cluster_by == name:
            self.schema.cluster_by = None  # ordering key is gone
        self.version += 1
        self.data_epoch += 1  # column set changed under existing rows

    def modify_column(self, col: ColumnInfo) -> None:
        """Change a column's type, converting existing values. Numeric
        widenings and integer-domain decimal scale shifts only; anything
        lossy (non-integral, indivisible scale-down, out-of-domain BOOL,
        int64 overflow, precision loss above 2^53 into FLOAT) raises
        rather than corrupting. Lossy-value checks look only at valid
        slots of PRESENT versions — stale bytes under NULLs and dead
        (ended) versions are never read by current/future readers and
        must not turn the statement into an error."""
        old = self.schema.col(col.name)
        ok_kinds = {TypeKind.INT, TypeKind.FLOAT, TypeKind.DECIMAL, TypeKind.BOOL}
        ok, nk = old.type_.kind, col.type_.kind
        n = self.n
        valid = self.valid[col.name][:n]
        # lossiness is judged on present (not-ended) valid values only
        chk = valid & self._present_mask()
        # zero stale bytes under NULL/dead slots: they are never read,
        # but they must not overflow or NaN-poison the bulk conversion
        data = np.where(valid, self.data[col.name][:n],
                        np.zeros((), dtype=self.data[col.name].dtype))

        def lossy(msg):
            raise ExecutionError(f"MODIFY {col.name}: {msg}")

        saved_dict = saved_coll = None
        if (ok == nk == TypeKind.STRING
                and col.collation is not None and col.collation != old.coll):
            # MODIFY ... COLLATE: re-sort the dictionary under the new
            # collation and translate stored codes; new-collation unique
            # semantics re-validate below like any narrowing
            d_old = self.dicts[col.name]
            saved_dict, saved_coll = d_old, old.collation
            d_new = Dictionary(list(d_old.values), col.collation)
            trans = d_old.translate_to(d_new)
            conv = np.where(valid, trans[np.clip(data, 0, max(len(trans) - 1, 0))]
                            if len(trans) else data, 0)
            self.dicts[col.name] = d_new
            old.collation = col.collation
        elif ok == nk and not (ok == TypeKind.DECIMAL
                               and old.type_.scale != col.type_.scale):
            conv = data
        elif ok not in ok_kinds or nk not in ok_kinds:
            lossy(f"cannot convert {ok.name} to {nk.name}")
        elif nk == TypeKind.BOOL:
            if ((data[chk] != 0) & (data[chk] != 1)).any():
                lossy("values outside 0/1 cannot become BOOL")
            conv = data.astype(np.bool_)
        elif {ok, nk} <= {TypeKind.INT, TypeKind.DECIMAL, TypeKind.BOOL}:
            # pure integer-domain scale shift: no float round trip, so
            # 18-digit decimals survive exactly
            shift = ((col.type_.scale if nk == TypeKind.DECIMAL else 0)
                     - (old.type_.scale if ok == TypeKind.DECIMAL else 0))
            src = np.where(chk, data.astype(np.int64), 0)
            if shift >= 0:
                mul = 10 ** shift
                if len(src) and np.abs(src).max() > (2 ** 63 - 1) // mul:
                    lossy(f"scale-up by {mul} overflows int64")
                conv = src * mul
            else:
                div = 10 ** (-shift)
                if (src[chk] % div != 0).any():
                    lossy(f"scale reduction loses digits (divide by {div})")
                conv = src // div
        elif nk == TypeKind.FLOAT:
            src = np.where(chk, data, np.zeros((), dtype=data.dtype))
            if np.issubdtype(src.dtype, np.integer) and len(src) and (
                    np.abs(src).max() > (1 << 53)):
                lossy("magnitudes above 2^53 lose precision in FLOAT")
            conv = src.astype(np.float64)
            if ok == TypeKind.DECIMAL:
                conv = conv / (10 ** old.type_.scale)
        elif ok == TypeKind.FLOAT and nk == TypeKind.DECIMAL:
            conv = np.round(data * 10 ** col.type_.scale)
            back = conv[chk] / (10 ** col.type_.scale)
            if not np.allclose(back, data[chk], rtol=0, atol=0.5 * 10 ** -col.type_.scale):
                lossy(f"values do not fit DECIMAL scale {col.type_.scale}")
            conv = conv.astype(np.int64)
        else:  # FLOAT -> INT
            if not np.allclose(data[chk], np.round(data[chk])):
                lossy("non-integral values")
            conv = np.round(data).astype(np.int64)

        if col.not_null and n and (
                ~valid[self.live_mask(0, n)]).any():
            lossy("NULLs present, NOT NULL requested")
        buf = np.zeros(self._cap, dtype=col.type_.np_dtype)
        buf[:n] = conv
        saved = self.data[col.name]
        self.data[col.name] = buf
        # a narrowing conversion (e.g. float -> decimal rounding) can
        # merge previously distinct unique keys: re-validate, and restore
        # the old column on violation so the table stays consistent
        try:
            for idx in self.indexes.values():
                if idx.unique and col.name in idx.columns:
                    self._check_unique(idx)
        except ExecutionError:
            self.data[col.name] = saved
            if saved_dict is not None:
                self.dicts[col.name] = saved_dict
                old.collation = saved_coll
            raise
        old.type_ = col.type_
        old.not_null = col.not_null
        if col.default is not None:
            old.default = col.default
        self.version += 1
        self.data_epoch += 1  # stored values converted in place

    # -- indexes -----------------------------------------------------------

    def create_index(self, name: str, columns: List[str],
                     unique: bool = False, state: str = "public") -> None:
        for c in columns:
            self.schema.col(c)  # raises if absent
        if name in self.indexes:
            raise SchemaError(f"duplicate index {name!r}")
        idx = IndexInfo(name=name, columns=list(columns), unique=unique,
                        state=state)
        if unique and state == "public":
            # atomic path validates now; a write_only (online DDL)
            # index defers existing-row validation to its backfill stage
            self._check_unique(idx)
        self.indexes[name] = idx
        self.version += 1

    def drop_index(self, name: str) -> None:
        if name not in self.indexes:
            raise SchemaError(f"no index {name!r}")
        del self.indexes[name]
        self.version += 1

    def _present_mask(self) -> np.ndarray:
        """Rows that exist for constraint purposes: every version not yet
        ended by a commit (includes provisional writes and rows under a
        txn's delete marker — conservative, like InnoDB's locked checks)."""
        return self.end_ts[: self.n] >= TXN_TS_BASE

    def _uniq_key_rows(self, idx: IndexInfo, sel: np.ndarray):
        """(int64 key matrix, surviving row ids) at positions `sel`;
        rows with any NULL key column are dropped (MySQL: NULLs never
        conflict). The single source of index-key encoding — the unique
        checks, conflict maps, and point lookups all go through it."""
        ok = np.ones(len(sel), dtype=np.bool_)
        cols = []
        for cname in idx.columns:
            d = self.data[cname][sel]
            v = self.valid[cname][sel]
            ok &= v
            dic = self.dicts.get(cname)
            if dic is not None and dic.is_ci:
                # fold-class representative: 'abc' and 'ABC' must collide
                # in a unique index under a _ci collation (MySQL)
                lut = dic.canon_lut()
                d = lut[np.clip(d.astype(np.int64), 0, max(len(lut) - 1, 0))] \
                    if len(lut) else d
            if np.issubdtype(d.dtype, np.floating):
                d = d.astype(np.float64).view(np.int64)
            cols.append(d.astype(np.int64))
        mat = np.stack(cols, axis=1)[ok] if cols else np.zeros((0, 0), np.int64)
        return mat, sel[ok]

    def _uniq_keys_at(self, idx: IndexInfo, sel: np.ndarray) -> np.ndarray:
        """Key rows at `sel` as a sortable structured array."""
        mat, _ids = self._uniq_key_rows(idx, sel)
        dt = np.dtype([(f"k{i}", np.int64) for i in range(len(idx.columns))])
        return np.ascontiguousarray(mat).view(dt).reshape(-1)

    def index_key_at(self, idx: IndexInfo, rid: int):
        """One physical row's key tuple for `idx`, or None (NULL key)."""
        mat, ids = self._uniq_key_rows(idx, np.array([rid], dtype=np.int64))
        if len(ids) == 0:
            return None
        return tuple(mat[0].tolist())

    def _sorted_index(self, idx_name: str):
        """Sorted (keys, row ids) for `idx_name`, cached per version —
        the shared substrate of point and range index access."""
        idx = self.indexes[idx_name]
        hit = self._lookup_cache.get(idx_name)
        if hit is None or hit[0] != self.version:
            all_rows = np.arange(self.n, dtype=np.int64)
            mat, ids = self._uniq_key_rows(idx, all_rows)
            dt = np.dtype([(f"k{i}", np.int64) for i in range(len(idx.columns))])
            keys = np.ascontiguousarray(mat).view(dt).reshape(-1)
            order = np.argsort(keys, kind="stable")
            hit = (self.version, keys[order], ids[order])
            self._lookup_cache[idx_name] = hit
        return hit[1], hit[2]

    def _mvcc_mask(self, cand: np.ndarray, read_ts=None,
                   marker: int = 0) -> np.ndarray:
        """Visibility mask over candidate physical rows at `read_ts`
        (own-txn writes included via `marker`)."""
        b = self.begin_ts[cand]
        e = self.end_ts[cand]
        if read_ts is None:
            keep = (b < TXN_TS_BASE) & (e >= TXN_TS_BASE)
            if marker:
                # same own-writes rule as live_mask's committed-latest
                # branch (point gets / index lookups under FOR UPDATE)
                keep = (((b < TXN_TS_BASE) | (b == marker))
                        & (e >= TXN_TS_BASE) & (e != marker))
            return keep
        keep = (b <= read_ts) & (e > read_ts)
        if marker:
            keep = (((b <= read_ts) | (b == marker))
                    & (e > read_ts) & (e != marker))
        return keep

    def _mvcc_visible(self, cand: np.ndarray, read_ts=None,
                      marker: int = 0) -> np.ndarray:
        """Filter candidate physical rows to the versions visible at
        `read_ts` (own-txn writes included via `marker`)."""
        if len(cand) == 0:
            return cand
        return cand[self._mvcc_mask(cand, read_ts, marker)]

    def index_lookup(self, idx_name: str, key_vals, read_ts=None,
                     marker: int = 0) -> np.ndarray:
        """Visible physical row positions whose index key equals
        `key_vals` — O(log n) against a sorted (key, row) cache per
        index+version instead of a full scan (ref: the reference's
        PointGetExecutor reading the index KV record, SURVEY.md:91).
        MVCC versions share a key; visibility filters them here."""
        skeys, srows = self._sorted_index(idx_name)
        probe = np.zeros(1, dtype=skeys.dtype)
        for i, v in enumerate(key_vals):
            probe[f"k{i}"] = np.int64(v)
        lo = np.searchsorted(skeys, probe[0], side="left")
        hi = np.searchsorted(skeys, probe[0], side="right")
        return self._mvcc_visible(srows[lo:hi], read_ts, marker)

    def index_range_lookup(self, idx_name: str, eq_vals, lo=None, hi=None,
                           lo_incl: bool = True, hi_incl: bool = True,
                           read_ts=None, marker: int = 0) -> np.ndarray:
        """Visible physical rows whose index key has prefix `eq_vals`
        and whose next key column lies in [lo, hi] (either bound open
        when None, inclusive per the _incl flags) — two binary searches
        against the same sorted cache point lookups use (ref: the
        reference's IndexRangeScan feeding IndexLookUpExecutor,
        SURVEY.md:91). Rows with NULL in any key column are absent from
        the cache, matching MySQL range-access semantics."""
        skeys, srows = self._sorted_index(idx_name)
        p = len(eq_vals)
        i64 = np.iinfo(np.int64)

        def bound(range_val, fill, side):
            probe = np.zeros(1, dtype=skeys.dtype)
            for i, v in enumerate(eq_vals):
                probe[f"k{i}"] = np.int64(v)
            for i, name in enumerate(skeys.dtype.names):
                if i < p:
                    continue
                probe[name] = np.int64(range_val) if (
                    i == p and range_val is not None) else fill
            return int(np.searchsorted(skeys, probe[0], side=side))

        # lower edge: >= lo (or > lo when exclusive); open bound floors
        # the suffix at int64 min so the whole eq-prefix group is kept
        if lo is None:
            start = bound(None, i64.min, "left")
        elif lo_incl:
            start = bound(lo, i64.min, "left")
        else:
            start = bound(lo, i64.max, "right")
        if hi is None:
            stop = bound(None, i64.max, "right")
        elif hi_incl:
            stop = bound(hi, i64.max, "right")
        else:
            stop = bound(hi, i64.min, "left")
        if stop <= start:
            return np.zeros(0, dtype=np.int64)
        return self._mvcc_visible(srows[start:stop], read_ts, marker)

    def _uniq_sorted(self, idx: IndexInfo) -> np.ndarray:
        """Sorted key set of present rows, cached per table version.
        Kept incrementally fresh across pure-insert workloads (the
        bulk-load path), so per-insert cost is O(m log n + n) memcpy
        instead of a full O(n log n) re-sort."""
        hit = self._uniq_cache.get(idx.name)
        if hit is not None and hit[0] == self.version:
            return hit[1]
        sel = np.nonzero(self._present_mask())[0]
        keys = np.sort(self._uniq_keys_at(idx, sel))
        self._uniq_cache[idx.name] = (self.version, keys)
        return keys

    def _check_unique_batch(self, idx: IndexInfo, start: int, end: int,
                            marker: Optional[int] = None) -> None:
        """Insert-path uniqueness: buffer rows [start, end) vs the sorted
        key cache. Stages the merged key set in _uniq_pending; the caller
        commits it after the version bump."""
        cache = self._uniq_sorted(idx)
        if marker is not None:
            # keys of rows this txn deleted are free for re-insertion;
            # a rollback resurrects them but also bumps the version,
            # which rebuilds the cache. O(dead) via the per-marker
            # registry, not an O(n) end_ts scan per insert.
            dead = np.asarray(self._txn_dead.get(marker, []), dtype=np.int64)
            if len(dead):
                dk = np.sort(self._uniq_keys_at(idx, dead))
                pos = np.searchsorted(cache, dk)
                ok = (pos < len(cache))
                if ok.any():
                    hitpos = pos[ok]
                    match = cache[hitpos] == dk[ok]
                    cache = np.delete(cache, np.unique(hitpos[match]))
        batch = np.sort(self._uniq_keys_at(idx, np.arange(start, end)))
        if len(batch) == 0:
            return
        if len(batch) > 1 and (batch[1:] == batch[:-1]).any():
            raise ExecutionError(
                f"duplicate entry for unique index {idx.name!r} "
                f"on {self.schema.name!r}")
        pos = np.searchsorted(cache, batch)
        if len(cache):
            hit = (pos < len(cache)) & (
                cache[np.minimum(pos, len(cache) - 1)] == batch)
            if hit.any():
                raise ExecutionError(
                    f"duplicate entry for unique index {idx.name!r} "
                    f"on {self.schema.name!r}")
        self._uniq_pending[idx.name] = np.insert(cache, pos, batch)

    def _uniq_commit(self) -> None:
        """Adopt staged key sets at the (just bumped) current version."""
        for name, keys in self._uniq_pending.items():
            self._uniq_cache[name] = (self.version, keys)
        self._uniq_pending.clear()

    def _check_unique(self, idx: IndexInfo, extra: Optional[tuple] = None,
                      marker: Optional[int] = None) -> None:
        """Raise if the index's key columns contain duplicates among
        present rows (rows with any NULL key are exempt, MySQL-style).
        `extra`=(start, end) adds not-yet-committed buffer slots;
        `marker` exempts versions this txn already superseded."""
        mask = self._present_mask()
        if marker is not None:
            mask = mask & (self.end_ts[: self.n] != marker)
        sel = np.nonzero(mask)[0]
        if extra is not None:
            sel = np.concatenate([sel, np.arange(extra[0], extra[1])])
        if len(sel) < 2:
            return
        cols, ok = [], np.ones(len(sel), dtype=np.bool_)
        for cname in idx.columns:
            d = self.data[cname][sel]
            v = self.valid[cname][sel]
            ok &= v
            dic = self.dicts.get(cname)
            if dic is not None and dic.is_ci:
                # _ci uniqueness folds case variants (same mapping as
                # _uniq_key_rows)
                lut = dic.canon_lut()
                if len(lut):
                    d = lut[np.clip(d.astype(np.int64), 0, len(lut) - 1)]
            if np.issubdtype(d.dtype, np.floating):
                d = d.astype(np.float64).view(np.int64)
            cols.append(d.astype(np.int64))
        mat = np.stack(cols, axis=1)[ok]
        if len(mat) < 2:
            return
        _, counts = np.unique(mat, axis=0, return_counts=True)
        if (counts > 1).any():
            raise ExecutionError(
                f"duplicate entry for unique index {idx.name!r} "
                f"on {self.schema.name!r}")

    def _enforce_unique_new(self, start: int, end: int,
                            marker: Optional[int] = None) -> None:
        """Validate unique indexes counting buffer slots [start, end) as
        present; called BEFORE self.n advances so a violation leaves the
        table untouched. On rejection the written slots' valid bits are
        cleared — later inserts that omit a column must read them as
        NULL, not as the rejected row's values. `marker`: rows this txn
        provisionally deleted don't conflict (REPLACE's delete+insert)."""
        try:
            for idx in self.indexes.values():
                if idx.unique:
                    self._check_unique_batch(idx, start, end, marker)
        except ExecutionError:
            self._uniq_pending.clear()
            for name in self.valid:
                self.valid[name][start:end] = False
            raise

    # -- conflict lookup for REPLACE / ON DUPLICATE KEY UPDATE ----------

    def encode_index_key(self, idx: IndexInfo, value_map: Dict[str, object]):
        """Logical column values -> the index's comparable int key tuple,
        or None when the key can't conflict (a NULL component, or a
        string not present in the column dictionary)."""
        out = []
        for cname in idx.columns:
            v = value_map.get(cname)
            if v is None:
                return None  # NULL never conflicts (MySQL)
            col = self.schema.col(cname)
            dv = self.to_device_value(col, v)
            if col.type_.is_dict_encoded:
                # collation-equal class, canonically coded (matches
                # _uniq_key_rows' canon mapping for _ci columns)
                lo, hi = self.dicts[cname].eq_range(str(dv))
                if lo >= hi:
                    return None  # new string: cannot equal any stored key
                out.append(int(lo))
            elif col.type_.kind == TypeKind.FLOAT:
                out.append(int(np.float64(dv).view(np.int64)))
            else:
                out.append(int(np.int64(dv)))
        return tuple(out)

    def conflict_map(self, idx: IndexInfo, marker: Optional[int] = None) -> dict:
        """key tuple -> physical row id over rows present for constraint
        purposes, minus rows this txn provisionally deleted AND minus
        other open txns' provisional inserts (those are locked rows a
        REPLACE/upsert must not touch — colliding with one surfaces as
        a unique-violation/write-conflict instead of silent clobbering).
        One O(n) pass; callers keep it fresh across their own
        statement's mutations instead of rescanning per VALUES row."""
        mask = self._present_mask()
        if marker is not None:
            mask = mask & (self.end_ts[: self.n] != marker)
            b = self.begin_ts[: self.n]
            mask = mask & ~((b >= TXN_TS_BASE) & (b != marker))
        sel = np.nonzero(mask)[0]
        mat, ids = self._uniq_key_rows(idx, sel)
        if mat.size == 0 and len(ids) == 0:
            return {}
        return {tuple(k): int(i) for k, i in zip(mat.tolist(), ids.tolist())}

    def row_value_map(self, names, row) -> Dict[str, object]:
        """Column name -> logical value for one INSERT row, with schema
        defaults filled in for omitted columns (so unique indexes over
        default-valued columns still detect conflicts)."""
        out = dict(zip(names, row))
        for c in self.schema.columns:
            if c.name not in out and c.default is not None and not c.auto_increment:
                out[c.name] = c.default
        return out

    def gc(self, safepoint: int) -> int:
        """Reclaim row versions invisible to every current and future
        reader (ref: the TiKV GC worker below the safepoint): versions
        whose end_ts committed at or before the safepoint, including
        rollback-dead rows (begin=end=0). Rows ended by an open txn's
        marker (>= TXN_TS_BASE) are never garbage. Compacts the column
        buffers in place and shrinks them when mostly empty.

        Caller contract: no open transaction may hold physical row ids
        into this table (txn write logs use positions) — the catalog's
        GC driver only runs with zero open transactions."""
        n = self.n
        if n == 0:
            return 0
        e = self.end_ts[:n]
        garbage = (e <= safepoint) & (e < TXN_TS_BASE)
        k = int(garbage.sum())
        if k == 0:
            return 0
        keep = ~garbage
        m = n - k
        for name in self.data:
            self.data[name][:m] = self.data[name][:n][keep]
            self.valid[name][:m] = self.valid[name][:n][keep]
            # vacated tail must read as NULL: insert paths that omit a
            # column rely on slots >= n having valid=False
            self.valid[name][m:n] = False
        self.begin_ts[:m] = self.begin_ts[:n][keep]
        self.end_ts[:m] = self.end_ts[:n][keep]
        # mask compaction preserves relative order: a FULLY clustered
        # table stays clustered; a partial watermark would need per-row
        # accounting, so it conservatively resets
        self.clustered_rows = m if self.clustered_rows >= n else 0
        self.n = m
        self.data_epoch += 1  # physical row positions moved
        # release buffer memory when the table shrank far below capacity
        want = max(_MIN_CAP, int(m * _GROW))
        if self._cap > 4 * want:
            for name in self.data:
                self.data[name] = np.resize(self.data[name], want)
                self.valid[name] = np.resize(self.valid[name], want)
            self.begin_ts = np.resize(self.begin_ts, want)
            self.end_ts = np.resize(self.end_ts, want)
            self._cap = want
        self.version += 1
        return k

    def recluster(self, quiesced: bool = False) -> bool:
        """Physically re-sort ALL rows by the CLUSTER BY column (ASC,
        NULLs first, stable — so same-key rows keep arrival order) so
        segment zone maps over the rebuild prune range filters (ISSUE
        18). Returns True when rows actually moved (data_epoch bumps,
        invalidating the segment store for an ordered rebuild).

        Row positions may only move under the catalog's writer lock
        with NO transaction open — the same contract as gc(): txn write
        logs address rows by position, and _run_dml's collect-to-apply
        window assumes positions are stable while it holds the catalog
        lock. Open txns are NOT the only readers of physical positions:
        an autocommit SELECT reads the live arrays lock-free (it never
        enters _open_txns), so the permute additionally requires the
        catalog's reader registry to be quiescent — no registered
        statement window, no open scan executor or paged cursor — and
        holds the registry lock across the move so no new reader can
        start mid-permute. ``quiesced=True`` is the catalog's own
        run_pending_reclusters path, which already holds that lock.
        Refusals return False; the queued fold retries at a later
        statement boundary. Catalog-less tables (unit fixtures) fall
        back to the table-local evidence of an open txn: provisional
        begin/end timestamps, pessimistic row locks, provisionally-ended
        rows."""
        col = self.schema.cluster_by
        if not col or col not in self.data or self.n <= 1:
            return False
        if self.clustered_rows >= self.n:
            return False  # already in order
        guard = self.txn_guard
        if guard is None:
            return self._recluster_locked()
        with guard.lock:
            if guard._open_txns:
                return False
            if quiesced:
                return self._recluster_locked()
            with guard._readers_lock:
                if guard._stmt_readers or guard._open_scans:
                    return False
                return self._recluster_locked()

    def _recluster_locked(self) -> bool:
        """The permute body; caller holds the catalog lock (or owns the
        table outright). The table-local open-txn checks stay as
        defense in depth for catalog-less tables."""
        col = self.schema.cluster_by
        n = self.n
        if self.clustered_rows >= n:
            return False  # raced: another caller sorted first
        if self.row_locks or self._txn_dead:
            return False
        b, e = self.begin_ts[:n], self.end_ts[:n]
        if (b >= TXN_TS_BASE).any() or \
                ((e >= TXN_TS_BASE) & (e < MAX_TS)).any():
            return False
        d, v = self.data[col][:n], self.valid[col][:n]
        if np.issubdtype(d.dtype, np.floating):
            key = d.astype(np.float64)
        else:
            # dict codes order lexicographically by construction, so
            # sorting string/date columns by code is sorting by value
            key = d.astype(np.int64)
        key = np.where(v, key, 0)
        nullrank = v.astype(np.int64)  # NULLs first, like ASC sort
        order = np.lexsort((key, nullrank))
        if (order == np.arange(n)).all():
            self.clustered_rows = n  # already sorted: watermark only
            return False
        # permute into FRESH buffers first — each fancy-index allocates
        # (tens of MB per column at SF1), and a MemoryError halfway
        # through an in-place loop would leave some columns permuted
        # and others not, permanently. The install loop below is plain
        # buffer copies into existing storage: nothing left to fail.
        perm = [(name, self.data[name][:n][order],
                 self.valid[name][:n][order]) for name in self.data]
        b_new = self.begin_ts[:n][order]
        e_new = self.end_ts[:n][order]
        for name, d_new, v_new in perm:
            self.data[name][:n] = d_new
            self.valid[name][:n] = v_new
        self.begin_ts[:n] = b_new
        self.end_ts[:n] = e_new
        self.clustered_rows = n
        self.data_epoch += 1  # physical row positions moved
        self.version += 1
        return True

    def truncate(self):
        if any(child is not self for child, _fk in self.referencing):
            raise ExecutionError(
                f"cannot truncate {self.schema.name!r}: referenced by a "
                "foreign key")
        self.n = 0
        self.version += 1
        self.data_epoch += 1  # every stored payload discarded
        self.clustered_rows = 0
        self.begin_ts[:] = 0
        self.end_ts[:] = MAX_TS
        for c in self.schema.columns:
            # valid[] must clear: insert paths that omit a column rely on
            # stale slots reading as NULL
            self.valid[c.name][:] = False
            self.data[c.name][:] = 0
            if c.type_.is_dict_encoded:
                self.dicts[c.name] = Dictionary([], c.coll)

    # -- reads -------------------------------------------------------------

    def column_slice(self, name: str, start: int, end: int):
        """(data, valid) physical slice incl. dead row versions — executor
        masks them via live_mask."""
        return self.data[name][start:end], self.valid[name][start:end]

    def live_mask(self, start: int, end: int, read_ts: Optional[int] = None,
                  marker: int = 0) -> np.ndarray:
        """Row visibility. read_ts=None reads committed-latest; a snapshot
        read at read_ts additionally sees its own txn's marker writes."""
        b = self.begin_ts[start:end]
        e = self.end_ts[start:end]
        if read_ts is None:
            vis = (b < TXN_TS_BASE) & (e >= TXN_TS_BASE)
            if marker:
                # committed-latest (locking reads) still sees the txn's
                # OWN provisional writes: an UPDATE then FOR UPDATE in
                # one txn must lock the new version, not the stale row
                vis = (((b < TXN_TS_BASE) | (b == marker))
                       & (e >= TXN_TS_BASE) & (e != marker))
            return vis
        vis = (b <= read_ts) & (e > read_ts)
        if marker:
            vis = ((b <= read_ts) | (b == marker)) & (e > read_ts) & (e != marker)
        return vis

    def _check_partition(self, start: int, end: int) -> None:
        """RANGE partitioning without a MAXVALUE partition rejects
        out-of-range rows at write time (MySQL: 'no partition for
        value')."""
        pi = self.schema.partition
        if pi is None or pi.kind != "range" or pi.uppers[-1] is None:
            return
        vals = self.data[pi.column][start:end]
        valid = self.valid[pi.column][start:end]
        pids = pi.ids_of_values(vals, valid)
        if (pids[valid] >= pi.count()).any():
            bad = vals[valid][pids[valid] >= pi.count()][0]
            raise ExecutionError(
                f"table {self.schema.name!r} has no partition for "
                f"value {int(bad)}")

    def partition_rows(self, pids, read_ts=None, marker: int = 0) -> np.ndarray:
        """Visible physical rows in the given partitions, via a
        per-version cache of partition -> physical row ids (one
        vectorized pass over the partition column; the pruned-scan
        analogue of the sorted index cache)."""
        pi = self.schema.partition
        assert pi is not None
        hit = getattr(self, "_part_cache", None)
        if hit is None or hit[0] != self.version:
            vals = self.data[pi.column][: self.n]
            valid = self.valid[pi.column][: self.n]
            all_pids = pi.ids_of_values(vals, valid)
            by_pid = {}
            for pid in range(pi.count() + 1):  # +1: overflow bucket
                rows = np.nonzero(all_pids == pid)[0]
                if len(rows):
                    by_pid[pid] = rows
            hit = (self.version, by_pid)
            self._part_cache = hit
        rows = [hit[1].get(int(p), np.zeros(0, dtype=np.int64))
                for p in pids]
        allrows = np.sort(np.concatenate(rows)) if rows else \
            np.zeros(0, dtype=np.int64)
        return self._mvcc_visible(allrows, read_ts, marker)

    def partition_bounds(self, num_partitions: int) -> List[tuple]:
        """Split [0, n) into near-equal contiguous partitions (the region/
        shard analogue for the scan scheduler)."""
        if num_partitions <= 0:
            raise ValueError("num_partitions must be positive")
        edges = np.linspace(0, self.n, num_partitions + 1, dtype=np.int64)
        return [(int(edges[i]), int(edges[i + 1])) for i in range(num_partitions)]
