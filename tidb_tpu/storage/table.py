"""Host columnar tables.

Layout decisions (device-first):
  * column-major numpy buffers in the device representation already
    (scaled ints, day counts, dict codes) so staging to HBM is a straight
    jnp.asarray of a slice — no row pivots on the hot path
  * appends grow buffers geometrically; deletes set a tombstone bit;
    updates write in place (single-writer host model, like the reference's
    single leaseholder per region)
  * each string column owns a sorted Dictionary; appends that introduce new
    strings re-encode the column (dictionaries grow rarely in analytics
    workloads; re-encode is vectorized)
  * `version` bumps on every mutation — executors snapshot (version,
    row_count) so EXPLAIN ANALYZE and the scheduler can detect staleness
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from tidb_tpu.chunk.dictionary import Dictionary
from tidb_tpu.errors import ExecutionError, SchemaError, TypeError_
from tidb_tpu.types import (
    SQLType,
    TypeKind,
    date_to_days,
    datetime_to_micros,
    decimal_to_scaled,
)

__all__ = ["ColumnInfo", "TableSchema", "Table"]


@dataclass
class ColumnInfo:
    name: str
    type_: SQLType
    not_null: bool = False
    default: object = None
    auto_increment: bool = False


@dataclass
class TableSchema:
    name: str
    columns: List[ColumnInfo]
    primary_key: Optional[List[str]] = None

    def col(self, name: str) -> ColumnInfo:
        for c in self.columns:
            if c.name == name:
                return c
        raise SchemaError(f"no column {name!r} in table {self.name!r}")

    def names(self) -> List[str]:
        return [c.name for c in self.columns]


_GROW = 1.5
_MIN_CAP = 1024


class Table:
    """Append-friendly columnar store for one table."""

    def __init__(self, schema: TableSchema):
        self.schema = schema
        self.n = 0  # logical rows incl. tombstoned
        self.version = 0
        self._auto_inc = 1
        cap = _MIN_CAP
        self._cap = cap
        self.data: Dict[str, np.ndarray] = {}
        self.valid: Dict[str, np.ndarray] = {}
        self.dicts: Dict[str, Dictionary] = {}
        for c in schema.columns:
            self.data[c.name] = np.zeros(cap, dtype=c.type_.np_dtype)
            self.valid[c.name] = np.zeros(cap, dtype=np.bool_)
            if c.type_.kind == TypeKind.STRING:
                self.dicts[c.name] = Dictionary([])
        self.tombstone = np.zeros(cap, dtype=np.bool_)

    # -- row count ---------------------------------------------------------

    @property
    def live_rows(self) -> int:
        return int(self.n - self.tombstone[: self.n].sum())

    def _ensure(self, extra: int):
        need = self.n + extra
        if need <= self._cap:
            return
        cap = max(int(self._cap * _GROW), need, _MIN_CAP)
        for name in self.data:
            self.data[name] = np.resize(self.data[name], cap)
            self.data[name][self.n:] = 0
            self.valid[name] = np.resize(self.valid[name], cap)
            self.valid[name][self.n:] = False
        self.tombstone = np.resize(self.tombstone, cap)
        self.tombstone[self.n:] = False
        self._cap = cap

    # -- ingestion ---------------------------------------------------------

    def to_device_value(self, col: ColumnInfo, v):
        """Host python value -> device representation scalar."""
        import datetime

        if v is None:
            return None
        k = col.type_.kind
        try:
            if k == TypeKind.INT:
                return int(v)
            if k == TypeKind.FLOAT:
                return float(v)
            if k == TypeKind.BOOL:
                return bool(v)
            if k == TypeKind.DECIMAL:
                return decimal_to_scaled(v, col.type_.scale)
            if k == TypeKind.DATE:
                if isinstance(v, str):
                    v = datetime.date.fromisoformat(v)
                return date_to_days(v)
            if k == TypeKind.DATETIME:
                if isinstance(v, str):
                    v = datetime.datetime.fromisoformat(v)
                return datetime_to_micros(v)
            if k == TypeKind.STRING:
                return str(v)  # encoded in bulk by insert_rows
        except (ValueError, TypeError) as e:
            raise TypeError_(f"bad value {v!r} for column {col.name}: {e}")
        raise TypeError_(f"unsupported type {col.type_}")

    def insert_rows(self, rows: Sequence[Sequence], columns: Optional[List[str]] = None) -> int:
        """Insert python rows (already in logical form; strings as str,
        dates as date/str, decimals as str/float). Returns rows inserted."""
        names = columns or self.schema.names()
        cols = [self.schema.col(n) for n in names]
        m = len(rows)
        if m == 0:
            return 0
        self._ensure(m)
        start, end = self.n, self.n + m
        provided = set(names)
        # columns not provided get default/NULL/auto-inc
        for c in self.schema.columns:
            if c.name in provided:
                continue
            if c.auto_increment:
                vals = np.arange(self._auto_inc, self._auto_inc + m, dtype=np.int64)
                self._auto_inc += m
                self.data[c.name][start:end] = vals
                self.valid[c.name][start:end] = True
            elif c.default is not None:
                dv = self.to_device_value(c, c.default)
                if c.type_.kind == TypeKind.STRING:
                    self._append_strings(c.name, [dv] * m, start, end)
                else:
                    self.data[c.name][start:end] = dv
                    self.valid[c.name][start:end] = True
            elif c.not_null:
                raise ExecutionError(f"column {c.name!r} has no default and is NOT NULL")
            # else: stays NULL
        for j, (name, c) in enumerate(zip(names, cols)):
            vals = [self.to_device_value(c, r[j]) for r in rows]
            if any(v is None for v in vals) and c.not_null:
                raise ExecutionError(f"NULL in NOT NULL column {c.name!r}")
            if c.type_.kind == TypeKind.STRING:
                self._append_strings(name, vals, start, end)
            else:
                arr = self.data[name]
                vd = self.valid[name]
                for i, v in enumerate(vals):
                    if v is None:
                        vd[start + i] = False
                    else:
                        arr[start + i] = v
                        vd[start + i] = True
        self.n = end
        self.version += 1
        return m

    def insert_columns(self, arrays: Dict[str, np.ndarray], valids: Optional[Dict[str, np.ndarray]] = None, strings: Optional[Dict[str, list]] = None):
        """Bulk columnar ingest (datagen / LOAD). `arrays` hold device reprs
        for non-string columns; `strings` holds raw python strings per
        string column."""
        sizes = [len(a) for a in arrays.values()] + [len(s) for s in (strings or {}).values()]
        if not sizes:
            return 0
        m = sizes[0]
        if any(s != m for s in sizes):
            raise ExecutionError(f"bulk insert length mismatch: {sizes}")
        self._ensure(m)
        start, end = self.n, self.n + m
        for c in self.schema.columns:
            name = c.name
            if strings and name in strings:
                self._append_strings(name, strings[name], start, end)
            elif name in arrays:
                self.data[name][start:end] = arrays[name].astype(c.type_.np_dtype, copy=False)
                if valids and name in valids:
                    self.valid[name][start:end] = valids[name]
                else:
                    self.valid[name][start:end] = True
            elif c.not_null:
                raise ExecutionError(f"bulk insert missing NOT NULL column {name!r}")
        self.n = end
        self.version += 1
        return m

    def _append_strings(self, name: str, vals: list, start: int, end: int):
        d = self.dicts[name]
        new = {v for v in vals if v is not None and v not in d}
        if new:
            # dictionary grows: build union dict and re-encode existing codes
            nd = Dictionary(list(d.values) + list(new))
            if self.n > 0 and len(d) > 0:
                trans = d.translate_to(nd)
                self.data[name][: self.n] = trans[self.data[name][: self.n]]
            self.dicts[name] = nd
            d = nd
        codes, valid = d.encode_with(vals)
        self.data[name][start:end] = codes
        self.valid[name][start:end] = valid

    # -- mutation ----------------------------------------------------------

    def delete_rows(self, row_ids: np.ndarray) -> int:
        """Tombstone rows by physical id; returns count newly deleted."""
        ids = np.asarray(row_ids, dtype=np.int64)
        ids = ids[(ids >= 0) & (ids < self.n)]
        fresh = ~self.tombstone[ids]
        self.tombstone[ids] = True
        self.version += 1
        return int(fresh.sum())

    def update_rows(self, row_ids: np.ndarray, updates: Dict[str, list]) -> int:
        ids = np.asarray(row_ids, dtype=np.int64)
        for name, vals in updates.items():
            c = self.schema.col(name)
            if c.type_.kind == TypeKind.STRING:
                # route through append-style encoding (may grow dict)
                d = self.dicts[name]
                new = {v for v in vals if v is not None and v not in d}
                if new:
                    nd = Dictionary(list(d.values) + list(new))
                    trans = d.translate_to(nd)
                    self.data[name][: self.n] = trans[self.data[name][: self.n]]
                    self.dicts[name] = nd
                    d = nd
                codes, valid = d.encode_with(vals)
                self.data[name][ids] = codes
                self.valid[name][ids] = valid
            else:
                for i, v in zip(ids, vals):
                    if v is None:
                        self.valid[name][i] = False
                    else:
                        self.data[name][i] = self.to_device_value(c, v)
                        self.valid[name][i] = True
        self.version += 1
        return len(ids)

    def truncate(self):
        self.n = 0
        self.version += 1
        self.tombstone[:] = False
        for c in self.schema.columns:
            # valid[] must clear: insert paths that omit a column rely on
            # stale slots reading as NULL
            self.valid[c.name][:] = False
            self.data[c.name][:] = 0
            if c.type_.kind == TypeKind.STRING:
                self.dicts[c.name] = Dictionary([])

    # -- reads -------------------------------------------------------------

    def column_slice(self, name: str, start: int, end: int):
        """(data, valid) physical slice incl. tombstoned rows — executor
        masks them via live_mask."""
        return self.data[name][start:end], self.valid[name][start:end]

    def live_mask(self, start: int, end: int) -> np.ndarray:
        return ~self.tombstone[start:end]

    def partition_bounds(self, num_partitions: int) -> List[tuple]:
        """Split [0, n) into near-equal contiguous partitions (the region/
        shard analogue for the scan scheduler)."""
        if num_partitions <= 0:
            raise ValueError("num_partitions must be positive")
        edges = np.linspace(0, self.n, num_partitions + 1, dtype=np.int64)
        return [(int(edges[i]), int(edges[i + 1])) for i in range(num_partitions)]
