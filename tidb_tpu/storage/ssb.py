"""Star Schema Benchmark: schema, generator, and the 13 queries
(BASELINE.md eval config "SSB Q3.x SF100 — 4-way star hash join").

SSB is TPC-H refactored into one fact table (lineorder) plus four
dimensions (customer, supplier, part, date), specifically to exercise
star joins. The generator follows the official dbgen distributions at
the same order of magnitude (lineorder ~ 6M rows/SF) using the columnar
bulk-ingest path — date dimension is the standard 7-year 1992-1998
calendar."""

from __future__ import annotations

import datetime
from typing import Dict

import numpy as np

from tidb_tpu.storage.catalog import Catalog
from tidb_tpu.storage.table import ColumnInfo, TableSchema
from tidb_tpu.types import DATE, INT64, STRING, date_to_days, decimal_type

__all__ = ["load_ssb", "SSB_SCHEMAS", "SSB_QUERIES"]

D152 = decimal_type(15, 2)

SSB_SCHEMAS = {
    "ssb_date": [
        ("d_datekey", INT64, True),        # yyyymmdd int, the SSB convention
        ("d_date", DATE, True),
        ("d_dayofweek", STRING, True),
        ("d_month", STRING, True),
        ("d_year", INT64, True),
        ("d_yearmonthnum", INT64, True),   # yyyymm
        ("d_yearmonth", STRING, True),     # e.g. Dec1997
        ("d_weeknuminyear", INT64, True),
    ],
    "ssb_customer": [
        ("c_custkey", INT64, True),
        ("c_name", STRING, True),
        ("c_city", STRING, True),
        ("c_nation", STRING, True),
        ("c_region", STRING, True),
        ("c_mktsegment", STRING, True),
    ],
    "ssb_supplier": [
        ("s_suppkey", INT64, True),
        ("s_name", STRING, True),
        ("s_city", STRING, True),
        ("s_nation", STRING, True),
        ("s_region", STRING, True),
    ],
    "ssb_part": [
        ("p_partkey", INT64, True),
        ("p_name", STRING, True),
        ("p_mfgr", STRING, True),
        ("p_category", STRING, True),
        ("p_brand1", STRING, True),
        ("p_color", STRING, True),
    ],
    "lineorder": [
        ("lo_orderkey", INT64, True),
        ("lo_linenumber", INT64, True),
        ("lo_custkey", INT64, True),
        ("lo_partkey", INT64, True),
        ("lo_suppkey", INT64, True),
        ("lo_orderdate", INT64, True),     # d_datekey ref (yyyymmdd)
        ("lo_quantity", INT64, True),
        ("lo_extendedprice", D152, True),
        ("lo_discount", INT64, True),      # whole percent 0..10, SSB style
        ("lo_revenue", D152, True),
        ("lo_supplycost", D152, True),
    ],
}

_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = {  # 5 nations per region, the SSB reduction
    "AFRICA": ["ALGERIA", "ETHIOPIA", "KENYA", "MOROCCO", "MOZAMBIQUE"],
    "AMERICA": ["ARGENTINA", "BRAZIL", "CANADA", "PERU", "UNITED STATES"],
    "ASIA": ["CHINA", "INDIA", "INDONESIA", "JAPAN", "VIETNAM"],
    "EUROPE": ["FRANCE", "GERMANY", "ROMANIA", "RUSSIA", "UNITED KINGDOM"],
    "MIDDLE EAST": ["EGYPT", "IRAN", "IRAQ", "JORDAN", "SAUDI ARABIA"],
}
_MONTHS = ["Jan", "Feb", "Mar", "Apr", "May", "Jun",
           "Jul", "Aug", "Sep", "Oct", "Nov", "Dec"]
_DOW = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
        "Saturday", "Sunday"]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
_COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
           "black", "blanched", "blue", "blush", "brown", "burlywood"]


def _nation_region(rng, n):
    """(region, nation, city) triples. A deterministic 80-row prefix
    guarantees coverage at tiny test scale factors: rows 0-49 cover
    every nation once with city digit 1 and once with digit 5, rows
    50-79 are all UNITED KINGDOM with digits 1/5 so the city-specific
    q3.3/q3.4 flights (incl. q3.4's additional one-month date filter)
    keep a non-vacuous result. At real scale factors the prefix is
    noise-level skew."""
    regions = rng.integers(0, 5, n)
    nation_idx = rng.integers(0, 5, n)
    digits = rng.integers(0, 10, n)
    uk_region = _REGIONS.index("EUROPE")
    uk_idx = _NATIONS["EUROPE"].index("UNITED KINGDOM")
    for i in range(min(n, 80)):
        if i < 50:
            regions[i] = (i % 25) // 5
            nation_idx[i] = i % 5
            digits[i] = 1 if i < 25 else 5
        else:
            regions[i] = uk_region
            nation_idx[i] = uk_idx
            digits[i] = 1 if i % 2 else 5
    rnames = [_REGIONS[r] for r in regions]
    nnames = [_NATIONS[_REGIONS[r]][i] for r, i in zip(regions, nation_idx)]
    cities = [f"{nm[:9]:<9}{d}" for nm, d in zip(nnames, digits)]
    return rnames, nnames, cities


def load_ssb(catalog: Catalog, sf: float = 0.01, db: str = "test",
             seed: int = 11) -> Dict[str, int]:
    """Generate and ingest the five SSB tables at scale factor sf."""
    rng = np.random.default_rng(seed)
    counts = {}

    def make_table(name, pk):
        cols = [ColumnInfo(n, t, not_null=nn) for n, t, nn in SSB_SCHEMAS[name]]
        return catalog.create_table(db, TableSchema(name, cols, primary_key=pk))

    # date dimension: fixed 1992-01-01 .. 1998-12-31 -------------------------
    first = datetime.date(1992, 1, 1)
    ndays = (datetime.date(1998, 12, 31) - first).days + 1
    days = [first + datetime.timedelta(days=i) for i in range(ndays)]
    t = make_table("ssb_date", ["d_datekey"])
    counts["ssb_date"] = t.insert_columns(
        {
            "d_datekey": np.array([d.year * 10000 + d.month * 100 + d.day for d in days]),
            "d_date": np.array([date_to_days(d) for d in days], dtype=np.int32),
            "d_year": np.array([d.year for d in days]),
            "d_yearmonthnum": np.array([d.year * 100 + d.month for d in days]),
            "d_weeknuminyear": np.array([d.isocalendar()[1] for d in days]),
        },
        strings={
            "d_dayofweek": [_DOW[d.weekday()] for d in days],
            "d_month": [_MONTHS[d.month - 1] for d in days],
            "d_yearmonth": [f"{_MONTHS[d.month - 1]}{d.year}" for d in days],
        },
    )

    # customer ---------------------------------------------------------------
    # floors keep every region/nation populated at tiny test SFs
    nc = max(80, int(30_000 * sf))
    keys = np.arange(1, nc + 1)
    creg, cnat, ccity = _nation_region(rng, nc)
    t = make_table("ssb_customer", ["c_custkey"])
    counts["ssb_customer"] = t.insert_columns(
        {"c_custkey": keys},
        strings={
            "c_name": [f"Customer#{k:09d}" for k in keys],
            "c_city": ccity, "c_nation": cnat, "c_region": creg,
            "c_mktsegment": [_SEGMENTS[i] for i in rng.integers(0, 5, nc)],
        },
    )

    # supplier ---------------------------------------------------------------
    ns = max(80, int(2_000 * sf))
    keys = np.arange(1, ns + 1)
    sreg, snat, scity = _nation_region(rng, ns)
    t = make_table("ssb_supplier", ["s_suppkey"])
    counts["ssb_supplier"] = t.insert_columns(
        {"s_suppkey": keys},
        strings={
            "s_name": [f"Supplier#{k:09d}" for k in keys],
            "s_city": scity, "s_nation": snat, "s_region": sreg,
        },
    )

    # part -------------------------------------------------------------------
    npart = max(1, int(200_000 * sf))
    keys = np.arange(1, npart + 1)
    mfgr = rng.integers(1, 6, npart)
    cat = rng.integers(1, 6, npart)
    brand = rng.integers(1, 41, npart)
    t = make_table("ssb_part", ["p_partkey"])
    counts["ssb_part"] = t.insert_columns(
        {"p_partkey": keys},
        strings={
            "p_name": [f"{_COLORS[int(k) % len(_COLORS)]} part" for k in keys],
            "p_mfgr": [f"MFGR#{m}" for m in mfgr],
            "p_category": [f"MFGR#{m}{c}" for m, c in zip(mfgr, cat)],
            "p_brand1": [f"MFGR#{m}{c}{b}" for m, c, b in zip(mfgr, cat, brand)],
            "p_color": [_COLORS[i] for i in rng.integers(0, len(_COLORS), npart)],
        },
    )

    # lineorder (the fact table) --------------------------------------------
    norders = max(1, int(1_500_000 * sf))
    lines_per = rng.integers(1, 8, norders)
    n = int(lines_per.sum())
    okey = np.repeat(np.arange(1, norders + 1), lines_per)
    lnum = np.concatenate([np.arange(1, c + 1) for c in lines_per])
    datekeys = np.array([d.year * 10000 + d.month * 100 + d.day for d in days])
    odate = datekeys[rng.integers(0, ndays, norders)]
    qty = rng.integers(1, 51, n)
    price = rng.integers(90000, 10_000_000, n)  # cents
    disc = rng.integers(0, 11, n)
    t = make_table("lineorder", ["lo_orderkey", "lo_linenumber"])
    counts["lineorder"] = t.insert_columns({
        "lo_orderkey": okey,
        "lo_linenumber": lnum,
        "lo_custkey": rng.integers(1, nc + 1, n),
        "lo_partkey": rng.integers(1, npart + 1, n),
        "lo_suppkey": rng.integers(1, ns + 1, n),
        "lo_orderdate": np.repeat(odate, lines_per),
        "lo_quantity": qty,
        "lo_extendedprice": price,
        "lo_discount": disc,
        "lo_revenue": price * (100 - disc) // 100,
        "lo_supplycost": price * 6 // 10,
    })
    return counts


# the 13 SSB queries (4 flights), official shapes ---------------------------
SSB_QUERIES = {
    "q1.1": """select sum(lo_extendedprice * lo_discount) as revenue
        from lineorder, ssb_date
        where lo_orderdate = d_datekey and d_year = 1993
          and lo_discount between 1 and 3 and lo_quantity < 25""",
    "q1.2": """select sum(lo_extendedprice * lo_discount) as revenue
        from lineorder, ssb_date
        where lo_orderdate = d_datekey and d_yearmonthnum = 199401
          and lo_discount between 4 and 6 and lo_quantity between 26 and 35""",
    "q1.3": """select sum(lo_extendedprice * lo_discount) as revenue
        from lineorder, ssb_date
        where lo_orderdate = d_datekey and d_weeknuminyear = 6 and d_year = 1994
          and lo_discount between 5 and 7 and lo_quantity between 26 and 35""",
    "q2.1": """select sum(lo_revenue) as lo_revenue, d_year, p_brand1
        from lineorder, ssb_date, ssb_part, ssb_supplier
        where lo_orderdate = d_datekey and lo_partkey = p_partkey
          and lo_suppkey = s_suppkey and p_category = 'MFGR#12'
          and s_region = 'AMERICA'
        group by d_year, p_brand1 order by d_year, p_brand1""",
    "q2.2": """select sum(lo_revenue) as lo_revenue, d_year, p_brand1
        from lineorder, ssb_date, ssb_part, ssb_supplier
        where lo_orderdate = d_datekey and lo_partkey = p_partkey
          and lo_suppkey = s_suppkey
          and p_brand1 between 'MFGR#2221' and 'MFGR#2228'
          and s_region = 'ASIA'
        group by d_year, p_brand1 order by d_year, p_brand1""",
    "q2.3": """select sum(lo_revenue) as lo_revenue, d_year, p_brand1
        from lineorder, ssb_date, ssb_part, ssb_supplier
        where lo_orderdate = d_datekey and lo_partkey = p_partkey
          and lo_suppkey = s_suppkey and p_brand1 = 'MFGR#2239'
          and s_region = 'EUROPE'
        group by d_year, p_brand1 order by d_year, p_brand1""",
    "q3.1": """select c_nation, s_nation, d_year, sum(lo_revenue) as revenue
        from ssb_customer, lineorder, ssb_supplier, ssb_date
        where lo_custkey = c_custkey and lo_suppkey = s_suppkey
          and lo_orderdate = d_datekey and c_region = 'ASIA'
          and s_region = 'ASIA' and d_year >= 1992 and d_year <= 1997
        group by c_nation, s_nation, d_year
        order by d_year asc, revenue desc""",
    "q3.2": """select c_city, s_city, d_year, sum(lo_revenue) as revenue
        from ssb_customer, lineorder, ssb_supplier, ssb_date
        where lo_custkey = c_custkey and lo_suppkey = s_suppkey
          and lo_orderdate = d_datekey and c_nation = 'UNITED STATES'
          and s_nation = 'UNITED STATES' and d_year >= 1992 and d_year <= 1997
        group by c_city, s_city, d_year
        order by d_year asc, revenue desc""",
    "q3.3": """select c_city, s_city, d_year, sum(lo_revenue) as revenue
        from ssb_customer, lineorder, ssb_supplier, ssb_date
        where lo_custkey = c_custkey and lo_suppkey = s_suppkey
          and lo_orderdate = d_datekey
          and (c_city = 'UNITED KI1' or c_city = 'UNITED KI5')
          and (s_city = 'UNITED KI1' or s_city = 'UNITED KI5')
          and d_year >= 1992 and d_year <= 1997
        group by c_city, s_city, d_year
        order by d_year asc, revenue desc""",
    "q3.4": """select c_city, s_city, d_year, sum(lo_revenue) as revenue
        from ssb_customer, lineorder, ssb_supplier, ssb_date
        where lo_custkey = c_custkey and lo_suppkey = s_suppkey
          and lo_orderdate = d_datekey
          and (c_city = 'UNITED KI1' or c_city = 'UNITED KI5')
          and (s_city = 'UNITED KI1' or s_city = 'UNITED KI5')
          and d_yearmonth = 'Dec1997'
        group by c_city, s_city, d_year
        order by d_year asc, revenue desc""",
    "q4.1": """select d_year, c_nation,
               sum(lo_revenue - lo_supplycost) as profit
        from ssb_date, ssb_customer, ssb_supplier, ssb_part, lineorder
        where lo_custkey = c_custkey and lo_suppkey = s_suppkey
          and lo_partkey = p_partkey and lo_orderdate = d_datekey
          and c_region = 'AMERICA' and s_region = 'AMERICA'
          and (p_mfgr = 'MFGR#1' or p_mfgr = 'MFGR#2')
        group by d_year, c_nation order by d_year, c_nation""",
    "q4.2": """select d_year, s_nation, p_category,
               sum(lo_revenue - lo_supplycost) as profit
        from ssb_date, ssb_customer, ssb_supplier, ssb_part, lineorder
        where lo_custkey = c_custkey and lo_suppkey = s_suppkey
          and lo_partkey = p_partkey and lo_orderdate = d_datekey
          and c_region = 'AMERICA' and s_region = 'AMERICA'
          and (d_year = 1997 or d_year = 1998)
          and (p_mfgr = 'MFGR#1' or p_mfgr = 'MFGR#2')
        group by d_year, s_nation, p_category
        order by d_year, s_nation, p_category""",
    "q4.3": """select d_year, s_city, p_brand1,
               sum(lo_revenue - lo_supplycost) as profit
        from ssb_date, ssb_customer, ssb_supplier, ssb_part, lineorder
        where lo_custkey = c_custkey and lo_suppkey = s_suppkey
          and lo_partkey = p_partkey and lo_orderdate = d_datekey
          and s_nation = 'UNITED STATES' and (d_year = 1997 or d_year = 1998)
          and p_category = 'MFGR#14'
        group by d_year, s_city, p_brand1
        order by d_year, s_city, p_brand1""",
}
