"""Test utilities (ref: util/testkit — MustQuery-style helpers).

The reference tests boot a real session over mockstore and compare SQL
results; here the oracle is stdlib sqlite3: `mirror_to_sqlite` copies any
catalog table into an in-memory sqlite database so the same SQL (modulo
dialect) can be cross-checked row for row.
"""

from __future__ import annotations

import sqlite3
from typing import Iterable, Optional

import numpy as np

from tidb_tpu.storage.catalog import Catalog
from tidb_tpu.types import TypeKind, days_to_date, micros_to_datetime

__all__ = ["mirror_to_sqlite", "index_tpch_oracle", "rows_equal",
           "normalize_row"]


def index_tpch_oracle(conn: sqlite3.Connection) -> sqlite3.Connection:
    """Key indexes over a mirrored TPC-H database. Above toy scale the
    UNINDEXED oracle dominates grid wall time (a correlated EXISTS like
    Q4's goes nested-loop over all of lineitem per order row); these
    make the sqlite side O(probes) so the full 22-query grid fits the
    tier-1 budget at SF 0.1. Returns `conn` for chaining."""
    for ddl in (
            "create index li_ok on lineitem(l_orderkey)",
            "create index li_pk on lineitem(l_partkey, l_suppkey)",
            "create index li_sk on lineitem(l_suppkey)",
            "create index o_ok on orders(o_orderkey)",
            "create index o_ck on orders(o_custkey)",
            "create index c_ck on customer(c_custkey)",
            "create index s_sk on supplier(s_suppkey)",
            "create index p_pk on part(p_partkey)",
            "create index ps_pk on partsupp(ps_partkey, ps_suppkey)",
            "create index ps_sk on partsupp(ps_suppkey)"):
        conn.execute(ddl)
    conn.execute("analyze")
    return conn


def mirror_to_sqlite(catalog: Catalog, db: str = "test", tables: Optional[Iterable[str]] = None) -> sqlite3.Connection:
    """Copy catalog tables into a fresh in-memory sqlite DB.

    Decimals become REAL (compare with tolerance), dates ISO strings (so
    date literals compare lexically, matching sqlite conventions)."""
    conn = sqlite3.connect(":memory:")
    for name in tables or catalog.tables(db):
        t = catalog.table(db, name)
        cols = t.schema.columns
        # _ci collations mirror as NOCASE (identical ASCII folding), so
        # the oracle agrees on equality/LIKE/ORDER BY by construction
        decls = ", ".join(
            f"{c.name} {_sqlite_type(c.type_.kind)}"
            + (" COLLATE NOCASE"
               if c.type_.kind == TypeKind.STRING and c.coll.endswith("_ci")
               else "")
            for c in cols)
        conn.execute(f"CREATE TABLE {name} ({decls})")
        n = t.n
        if n == 0:
            continue
        pycols = []
        for c in cols:
            data, valid = t.data[c.name][:n], t.valid[c.name][:n]
            pycols.append(_to_python(c.type_, data, valid, t.dicts.get(c.name)))
        live = t.live_mask(0, n)
        rows = [tuple(col[i] for col in pycols) for i in range(n) if live[i]]
        ph = ", ".join("?" * len(cols))
        conn.executemany(f"INSERT INTO {name} VALUES ({ph})", rows)
    conn.commit()
    return conn


def _sqlite_type(kind: TypeKind) -> str:
    return {
        TypeKind.INT: "INTEGER",
        TypeKind.BOOL: "INTEGER",
        TypeKind.FLOAT: "REAL",
        TypeKind.DECIMAL: "REAL",
        TypeKind.STRING: "TEXT",
        TypeKind.DATE: "TEXT",
        TypeKind.DATETIME: "TEXT",
    }[kind]


def _to_python(type_, data: np.ndarray, valid: np.ndarray, dictionary) -> list:
    k = type_.kind
    if k == TypeKind.STRING:
        return dictionary.decode(data, valid)
    out = []
    for v, ok in zip(data, valid):
        if not ok:
            out.append(None)
        elif k == TypeKind.DECIMAL:
            out.append(int(v) / (10**type_.scale))
        elif k == TypeKind.DATE:
            out.append(days_to_date(int(v)).isoformat())
        elif k == TypeKind.DATETIME:
            out.append(micros_to_datetime(int(v)).isoformat(sep=" "))
        elif k == TypeKind.FLOAT:
            out.append(float(v))
        else:
            out.append(int(v))
    return out


def normalize_row(row: tuple) -> tuple:
    """Canonicalize a result row for comparison: decimal strings -> float,
    everything else unchanged."""
    out = []
    for v in row:
        if isinstance(v, str):
            try:
                out.append(float(v)) if _is_numeric_str(v) else out.append(v)
                continue
            except ValueError:
                pass
        out.append(v)
    return tuple(out)


def _is_numeric_str(s: str) -> bool:
    if not s:
        return False
    body = s[1:] if s[0] in "+-" else s
    return body.replace(".", "", 1).isdigit()


def rows_equal(got: list, want: list, ordered: bool = False, rel_tol: float = 1e-6) -> tuple:
    """Compare result sets; returns (ok, message). Numeric values compare
    with relative tolerance (decimals mirrored as REAL in sqlite)."""
    g = [normalize_row(r) for r in got]
    w = [normalize_row(r) for r in want]
    if not ordered:
        g = sorted(g, key=_sort_key)
        w = sorted(w, key=_sort_key)
    if len(g) != len(w):
        return False, f"row count {len(g)} != {len(w)}\n got: {g[:5]}\nwant: {w[:5]}"
    for i, (rg, rw) in enumerate(zip(g, w)):
        if len(rg) != len(rw):
            return False, f"row {i}: width {len(rg)} != {len(rw)}"
        for j, (a, b) in enumerate(zip(rg, rw)):
            if a is None or b is None:
                if a is not b:
                    return False, f"row {i} col {j}: {a!r} != {b!r}"
                continue
            if isinstance(a, (int, float)) and isinstance(b, (int, float)):
                if abs(a - b) > rel_tol * max(1.0, abs(a), abs(b)):
                    return False, f"row {i} col {j}: {a!r} != {b!r}"
            elif a != b:
                return False, f"row {i} col {j}: {a!r} != {b!r}"
    return True, "ok"


def _sort_key(row: tuple):
    return tuple((v is None, str(type(v).__name__), v if not isinstance(v, (int, float)) else float(v)) for v in row)
